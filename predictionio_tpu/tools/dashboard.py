"""Evaluation dashboard on :9000.

Reference: tools/.../dashboard/Dashboard.scala:37 — an HTML page listing
completed EvaluationInstances newest-first with their one-liner results and
links to the full HTML/JSON reports."""

from __future__ import annotations

import html
from typing import Optional

from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.obs import server_registry
from predictionio_tpu.utils.http import (
    HttpError,
    JsonHandler,
    ServerProcess,
    ThreadedServer,
)


class _Handler(JsonHandler):
    server: "_Server"  # type: ignore[assignment]

    def do_GET(self):
        self._drain_body()
        path = self.path.split("?")[0].rstrip("/") or "/"
        try:
            if path == "/":
                self._respond(200, self._index(), "text/html")
            elif path == "/metrics":
                self._serve_metrics()
            elif path == "/debug/traces":
                self._serve_debug_traces()
            elif path == "/debug/profile":
                self._serve_debug_profile()
            elif path == "/debug/faults":
                self._serve_debug_faults()
            elif path.startswith("/engine_instances/") and path.endswith(".html"):
                iid = path[len("/engine_instances/"):-len(".html")]
                inst = (
                    self.server.storage.get_meta_data_evaluation_instances()
                    .get(iid)
                )
                if inst is None:
                    raise HttpError(404, "Not Found")
                self._respond(
                    200, inst.evaluator_results_html or "<p>(no report)</p>",
                    "text/html",
                )
            elif path.startswith("/engine_instances/") and path.endswith(".json"):
                iid = path[len("/engine_instances/"):-len(".json")]
                inst = (
                    self.server.storage.get_meta_data_evaluation_instances()
                    .get(iid)
                )
                if inst is None:
                    raise HttpError(404, "Not Found")
                self._respond(200, inst.evaluator_results_json or "{}")
            else:
                raise HttpError(404, "Not Found")
        except HttpError as e:
            self._respond(e.status, {"message": e.message})

    def do_POST(self):
        self._drain_body()
        path = self.path.split("?")[0].rstrip("/")
        try:
            if path == "/debug/faults":
                self._serve_debug_faults_set()
            else:
                raise HttpError(404, "Not Found")
        except HttpError as e:
            self._respond(e.status, {"message": e.message})

    def _index(self) -> str:
        instances = (
            self.server.storage.get_meta_data_evaluation_instances()
            .get_completed()
        )
        rows = "".join(
            f"<tr><td>{i.id}</td><td>{i.start_time}</td>"
            f"<td>{html.escape(i.evaluation_class)}</td>"
            f"<td>{html.escape(i.evaluator_results)}</td>"
            f"<td><a href='/engine_instances/{i.id}.html'>HTML</a> "
            f"<a href='/engine_instances/{i.id}.json'>JSON</a></td></tr>"
            for i in instances
        )
        return f"""<!DOCTYPE html><html><head><title>predictionio_tpu dashboard</title></head>
<body><h1>Completed evaluations</h1>
<table border="1" cellpadding="4">
<tr><th>ID</th><th>Started</th><th>Evaluation</th><th>Result</th><th>Reports</th></tr>
{rows}
</table>
{self._lifecycle_html()}
{self._tenants_html()}
</body></html>"""

    def _lifecycle_html(self) -> str:
        """Model-lifecycle panel (ISSUE 5): versions newest-first with
        rollout state; active canaries lead the table. Registry fields
        carry operator-authored strings (reasons), so everything is
        escaped."""
        from predictionio_tpu.deploy.registry import ModelRegistry

        try:
            registry = getattr(self.server, "model_registry", None)
            if registry is None:
                registry = ModelRegistry(self.server.storage)
                self.server.model_registry = registry
            versions = registry.list()
        except Exception:
            return "<h1>Model lifecycle</h1><p>(registry unavailable)</p>"
        if not versions:
            return "<h1>Model lifecycle</h1><p>(no registered versions)</p>"
        order = {"canary": 0, "live": 1}
        versions.sort(key=lambda v: order.get(v.status, 2))
        rows = "".join(
            f"<tr><td>{html.escape(v.id)}</td>"
            f"<td>{html.escape(v.engine_id)}/{html.escape(v.engine_variant)}</td>"
            f"<td><b>{html.escape(v.status)}</b></td>"
            f"<td>{html.escape(v.created_at)}</td>"
            f"<td>{html.escape(v.params_hash)}</td>"
            f"<td>{html.escape(v.reason or '')}</td></tr>"
            for v in versions
        )
        return f"""<h1>Model lifecycle</h1>
<table border="1" cellpadding="4">
<tr><th>Version</th><th>Engine</th><th>Status</th><th>Created</th><th>Params hash</th><th>Note</th></tr>
{rows}
</table>"""


    def _tenants_html(self) -> str:
        """Multi-tenant panel (ISSUE 6): who shares the serving fleet,
        with weights and quotas. Descriptions are operator-authored, so
        everything is escaped."""
        from predictionio_tpu.tenancy.tenants import TenantStore

        try:
            store = getattr(self.server, "tenant_store", None)
            if store is None:
                store = TenantStore(self.server.storage)
                self.server.tenant_store = store
            tenants = store.list()
        except Exception:
            return "<h1>Tenants</h1><p>(tenant store unavailable)</p>"
        if not tenants:
            return "<h1>Tenants</h1><p>(no tenants registered)</p>"

        def fmt(v):
            return "∞" if v is None else html.escape(str(v))

        rows = "".join(
            f"<tr><td>{html.escape(t.id)}</td>"
            f"<td>{html.escape(t.engine_id)}/{html.escape(t.engine_variant)}</td>"
            f"<td>{t.weight:g}</td>"
            f"<td>{fmt(t.qps)}</td><td>{fmt(t.max_concurrency)}</td>"
            f"<td>{fmt(t.device_seconds_per_s)}</td>"
            f"<td>{'yes' if t.enabled else 'no'}</td>"
            f"<td>{html.escape(t.description)}</td></tr>"
            for t in tenants
        )
        return f"""<h1>Tenants</h1>
<table border="1" cellpadding="4">
<tr><th>Tenant</th><th>Engine</th><th>Weight</th><th>QPS</th>
<th>Concurrency</th><th>Device s/s</th><th>Enabled</th><th>Note</th></tr>
{rows}
</table>"""


class _Server(ThreadedServer):
    def __init__(self, addr, storage: Storage):
        super().__init__(addr, _Handler)
        self.storage = storage
        self.metrics = server_registry()
        self.metrics_label = "dashboard"


class Dashboard(ServerProcess):
    _name = "dashboard"

    def __init__(self, storage: Optional[Storage] = None, ip: str = "0.0.0.0",
                 port: int = 9000):
        super().__init__()
        self.storage = storage or Storage.get_instance()
        self.ip = ip
        self.port_config = port

    def _make_server(self) -> _Server:
        return _Server((self.ip, self.port_config), self.storage)
