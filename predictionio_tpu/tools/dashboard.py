"""Evaluation dashboard on :9000.

Reference: tools/.../dashboard/Dashboard.scala:37 — an HTML page listing
completed EvaluationInstances newest-first with their one-liner results and
links to the full HTML/JSON reports."""

from __future__ import annotations

import html
from typing import Optional

from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.obs import server_registry
from predictionio_tpu.utils.http import (
    HttpError,
    JsonHandler,
    ServerProcess,
    ThreadedServer,
)


class _Handler(JsonHandler):
    server: "_Server"  # type: ignore[assignment]

    def do_GET(self):
        self._drain_body()
        path = self.path.split("?")[0].rstrip("/") or "/"
        try:
            if path == "/":
                from urllib.parse import parse_qs, urlsplit

                qs = parse_qs(urlsplit(self.path).query)
                self._respond(200, self._index(qs), "text/html")
            elif path == "/metrics":
                self._serve_metrics()
            elif path == "/alerts":
                self._serve_alerts()
            elif path == "/debug/traces":
                self._serve_debug_traces()
            elif path == "/debug/tsdb":
                self._serve_debug_tsdb()
            elif path == "/debug/profile":
                self._serve_debug_profile()
            elif path == "/debug/faults":
                self._serve_debug_faults()
            elif path.startswith("/engine_instances/") and path.endswith(".html"):
                iid = path[len("/engine_instances/"):-len(".html")]
                inst = (
                    self.server.storage.get_meta_data_evaluation_instances()
                    .get(iid)
                )
                if inst is None:
                    raise HttpError(404, "Not Found")
                self._respond(
                    200, inst.evaluator_results_html or "<p>(no report)</p>",
                    "text/html",
                )
            elif path.startswith("/engine_instances/") and path.endswith(".json"):
                iid = path[len("/engine_instances/"):-len(".json")]
                inst = (
                    self.server.storage.get_meta_data_evaluation_instances()
                    .get(iid)
                )
                if inst is None:
                    raise HttpError(404, "Not Found")
                self._respond(200, inst.evaluator_results_json or "{}")
            else:
                raise HttpError(404, "Not Found")
        except HttpError as e:
            self._respond(e.status, {"message": e.message})

    def do_POST(self):
        self._drain_body()
        path = self.path.split("?")[0].rstrip("/")
        try:
            if path == "/debug/faults":
                self._serve_debug_faults_set()
            elif path == "/telemetry/push":
                self._serve_telemetry_push()
            else:
                raise HttpError(404, "Not Found")
        except HttpError as e:
            self._respond(e.status, {"message": e.message})

    def _index(self, qs: Optional[dict] = None) -> str:
        instances = (
            self.server.storage.get_meta_data_evaluation_instances()
            .get_completed()
        )
        rows = "".join(
            f"<tr><td>{i.id}</td><td>{i.start_time}</td>"
            f"<td>{html.escape(i.evaluation_class)}</td>"
            f"<td>{html.escape(i.evaluator_results)}</td>"
            f"<td><a href='/engine_instances/{i.id}.html'>HTML</a> "
            f"<a href='/engine_instances/{i.id}.json'>JSON</a></td></tr>"
            for i in instances
        )
        return f"""<!DOCTYPE html><html><head><title>predictionio_tpu dashboard</title></head>
<body><h1>Completed evaluations</h1>
<table border="1" cellpadding="4">
<tr><th>ID</th><th>Started</th><th>Evaluation</th><th>Result</th><th>Reports</th></tr>
{rows}
</table>
{self._alerts_html()}
{self._fleet_html()}
{self._traces_html()}
{self._tsdb_html(qs or {})}
{self._lifecycle_html()}
{self._evals_html()}
{self._tenants_html()}
{self._online_html()}
</body></html>"""

    # -- fleet evaluation (ISSUE 20) ---------------------------------------
    def _evals_html(self) -> str:
        """Fleet eval panel: EvalRun records newest-first — space size,
        convergence, winner, and the lineage pointer to the ModelVersion
        the winning params trained into."""
        from predictionio_tpu.evalfleet.records import EvalRecordStore

        try:
            runs = EvalRecordStore(self.server.storage).list_runs()
        except Exception:
            return "<h1>Fleet evaluations</h1><p>(eval store unavailable)</p>"
        if not runs:
            return "<h1>Fleet evaluations</h1><p>(no eval runs recorded)</p>"
        rows = "".join(
            f"<tr><td>{html.escape(r.id)}</td>"
            f"<td>{html.escape(r.engine_id)}</td>"
            f"<td>{html.escape(r.tenant or '-')}</td>"
            f"<td>{r.status}</td>"
            f"<td>{r.num_points} pts / {r.num_groups} grp "
            f"&times; {r.num_folds} folds</td>"
            f"<td>{html.escape(r.metric_header)}</td>"
            f"<td>{'-' if r.winner_score is None else f'{r.winner_score:.6g}'}"
            f"{'' if r.winner_index is None else f' (p{r.winner_index})'}</td>"
            f"<td>{html.escape(r.winner_model_version or '-')}</td></tr>"
            for r in runs[:50]
        )
        return f"""<h1>Fleet evaluations</h1>
<table border="1" cellpadding="4">
<tr><th>Run</th><th>Engine</th><th>Tenant</th><th>Status</th>
<th>Space</th><th>Metric</th><th>Winner</th><th>Model version</th></tr>
{rows}
</table>"""

    # -- online learning (ISSUE 9) -----------------------------------------
    def _online_html(self) -> str:
        """Online-learning panel: each consumer's durable cursor record —
        stream positions and cumulative fold counters."""
        from predictionio_tpu.deploy.registry import LifecycleRecordStore
        from predictionio_tpu.online import CURSOR_ENTITY

        try:
            records = LifecycleRecordStore(self.server.storage).fold(
                CURSOR_ENTITY
            )
        except Exception:
            return "<h1>Online learning</h1><p>(cursor store unavailable)</p>"
        if not records:
            return "<h1>Online learning</h1><p>(no consumers recorded)</p>"
        rows = "".join(
            f"<tr><td>{html.escape(cid)}</td>"
            f"<td>{html.escape(str(rec.get('cursor')))}</td>"
            f"<td>{rec.get('events_consumed', 0)}</td>"
            f"<td>{rec.get('events_folded', 0)}</td>"
            f"<td>{rec.get('users_folded', 0)}</td>"
            f"<td>{rec.get('items_folded', 0)}</td>"
            f"<td>{rec.get('ticks', 0)}</td></tr>"
            for cid, rec in sorted(records.items())
        )
        return f"""<h1>Online learning</h1>
<table border="1" cellpadding="4">
<tr><th>Consumer</th><th>Cursor</th><th>Consumed</th><th>Folded</th>
<th>User rows</th><th>Item rows</th><th>Ticks</th></tr>
{rows}
</table>"""

    # -- monitoring plane (ISSUE 8) ----------------------------------------
    _SPARK_BLOCKS = "▁▂▃▄▅▆▇█"

    @classmethod
    def _sparkline(cls, values: list, width: int = 40) -> str:
        """Unicode-block sparkline of the last `width` values (None →
        gap). Scaled to the window's max so shape survives any unit."""
        vals = [v for v in values[-width:]]
        nums = [v for v in vals if v is not None]
        if not nums:
            return ""
        top = max(nums) or 1.0
        out = []
        for v in vals:
            if v is None:
                out.append(" ")
            else:
                idx = min(
                    len(cls._SPARK_BLOCKS) - 1,
                    int(max(0.0, v) / top * (len(cls._SPARK_BLOCKS) - 1)),
                )
                out.append(cls._SPARK_BLOCKS[idx])
        return "".join(out)

    def _alerts_html(self) -> str:
        """Alerts panel: per-SLO state with a fast-burn-rate sparkline
        (history from the engine) — "is the error budget burning" at a
        glance. SLO names are operator-authored, so escaped."""
        from predictionio_tpu.obs.monitor import get_monitor

        monitor = get_monitor()
        engine = monitor.engine
        payload = monitor.alerts_payload()
        if not payload.get("slos"):
            return (
                "<h1>Alerts</h1><p>(no SLOs configured — set PIO_SLOS "
                "or use Monitor.set_slos)</p>"
            )
        color = {
            "firing": "#c00", "pending": "#c80",
            "resolved": "#080", "inactive": "#888",
        }
        rows = []
        for r in payload["slos"]:
            name = r["slo"]
            spark = ""
            if engine is not None:
                spark = self._sparkline(
                    [v for _t, v in engine.history(name)]
                )
            fast = r.get("fast_burn")
            slow = r.get("slow_burn")
            state = r.get("state", "inactive")
            rows.append(
                f"<tr><td>{html.escape(name)}</td>"
                f"<td style='color:{color.get(state, '#000')}'>"
                f"<b>{html.escape(state)}</b></td>"
                f"<td>{'-' if fast is None else f'{fast:.2f}'}</td>"
                f"<td>{'-' if slow is None else f'{slow:.2f}'}</td>"
                f"<td>{r.get('burn_threshold')}</td>"
                f"<td><code>{html.escape(spark)}</code></td></tr>"
            )
        return f"""<h1>Alerts</h1>
<table border="1" cellpadding="4">
<tr><th>SLO</th><th>State</th><th>Fast burn</th><th>Slow burn</th>
<th>Threshold</th><th>Burn history</th></tr>
{''.join(rows)}
</table>"""

    def _fleet_html(self) -> str:
        """Fleet panel: per-scrape-target up/latency with an `up`
        sparkline — a dead server is visible without leaving the page."""
        from predictionio_tpu.obs.monitor import get_monitor

        scraper = getattr(self.server, "fleet_scraper", None)
        if scraper is None:
            return ""
        tsdb = get_monitor().tsdb
        rows = []
        for t in scraper.status():
            ups = []
            for s in tsdb.matching("up", {"instance": t["instance"]}):
                ups = [v for _t, v in tsdb.points(s)]
            up = t["up"]
            state = (
                "?" if up is None else ("up" if up else "DOWN")
            )
            lat = t["scrape_seconds"]
            rows.append(
                f"<tr><td>{html.escape(t['instance'])}</td>"
                f"<td>{html.escape(t['url'])}</td>"
                f"<td><b>{state}</b></td>"
                f"<td>{'-' if lat is None else f'{lat * 1e3:.1f} ms'}</td>"
                f"<td><code>{html.escape(self._sparkline(ups))}</code>"
                f"</td></tr>"
            )
        return f"""<h1>Fleet</h1>
<table border="1" cellpadding="4">
<tr><th>Instance</th><th>URL</th><th>Up</th><th>Scrape</th>
<th>Up history</th></tr>
{''.join(rows)}
</table>"""

    def _traces_html(self) -> str:
        """Fleet traces panel (ISSUE 16): the collector's assembled
        cross-process traces, slowest/most recent first — the waterfall
        lives in `pio trace show --fleet`, this is the index."""
        from predictionio_tpu.obs.monitor import get_monitor

        col = get_monitor().collector
        if col is None:
            return ""
        rows = "".join(
            f"<tr><td><code>{html.escape(s['trace_id'])}</code></td>"
            f"<td>{html.escape(s['root'])}</td>"
            f"<td>{html.escape(','.join(s.get('servers') or []))}</td>"
            f"<td>{html.escape(s.get('path') or '')}</td>"
            f"<td>{s['duration_ms']:.1f} ms</td>"
            f"<td>{s['spans']}</td>"
            f"<td>{html.escape(s['kept'])}"
            f"{' <b>ERROR</b>' if s['error'] else ''}</td></tr>"
            for s in col.summaries(limit=15)
        )
        st = col.status()
        return f"""<h1>Fleet traces</h1>
<p>{st['assembled']} assembled, {st['pending_fragments']} pending
fragment(s), {st['polls']} poll(s)</p>
<table border="1" cellpadding="4">
<tr><th>Trace</th><th>Root</th><th>Servers</th><th>Path</th>
<th>Duration</th><th>Spans</th><th>Kept</th></tr>
{rows}
</table>"""

    def _tsdb_html(self, qs: dict) -> str:
        """TSDB explorer panel (ISSUE 16): a query box rendering ANY
        retained series — raw samples or recording-rule outputs — as a
        sparkline, without pre-wiring a panel per metric. Query params:
        ``?series=<name>`` plus optional ``match=k=v,k=v``."""
        from predictionio_tpu.obs.monitor import get_monitor

        tsdb = get_monitor().tsdb
        name = (qs.get("series") or [""])[0].strip()
        match_raw = (qs.get("match") or [""])[0].strip()
        expr_s = (qs.get("expr") or [""])[0].strip()
        form = f"""<form method="get" action="/">
<input name="series" size="40" value="{html.escape(name)}"
 placeholder="series name, e.g. slo_error_ratio">
<input name="match" size="30" value="{html.escape(match_raw)}"
 placeholder="label match, e.g. slo=availability">
<input type="submit" value="Plot"></form>
<form method="get" action="/">
<input name="expr" size="72" value="{html.escape(expr_s)}"
 placeholder="expression, e.g. sum by (instance) (rate(errors_total[5m]))">
<input type="submit" value="Eval"></form>"""
        if expr_s:
            # series-algebra evaluation (ISSUE 17): same engine that
            # backs expr recording rules and `pio tsdb query`
            from predictionio_tpu.obs.monitor.expr import (
                ExprError,
                evaluate_rows,
            )

            try:
                rows_v = evaluate_rows(tsdb, expr_s)
            except ExprError as e:
                return (
                    f"<h1>TSDB explorer</h1>{form}"
                    f"<p>expression error: <code>{html.escape(str(e))}"
                    f"</code></p>"
                )
            if not rows_v:
                return (
                    f"<h1>TSDB explorer</h1>{form}"
                    "<p>(expression matched no data)</p>"
                )
            body = "".join(
                "<tr><td><code>"
                + (html.escape(
                    ",".join(f"{k}={v}" for k, v in sorted(
                        r["labels"].items()
                    ))
                ) or "-")
                + f"</code></td><td>{r['value']:g}</td></tr>"
                for r in rows_v
            )
            return f"""<h1>TSDB explorer</h1>{form}
<table border="1" cellpadding="4">
<tr><th>Labels</th><th>Value</th></tr>
{body}
</table>"""
        if not name:
            durable = ""
            if hasattr(tsdb, "durable_stats"):
                # durable tier panel (ISSUE 18): block counts + spans
                # per retention tier, so an operator can see how far
                # back queries can reach past the in-memory ring
                ds = tsdb.durable_stats()
                rows = "".join(
                    f"<tr><td>{html.escape(t)}</td>"
                    f"<td>{st['blocks']}</td><td>{st['series']}</td>"
                    f"<td>{st['bytes']}</td>"
                    + (
                        f"<td>{st['max_t'] - st['min_t']:.0f}s</td>"
                        if st["min_t"] is not None else "<td>-</td>"
                    )
                    + "</tr>"
                    for t, st in ds.get("tiers", {}).items()
                )
                durable = f"""<h2>Durable tiers</h2>
<p><code>{html.escape(str(ds.get('dir')))}</code> —
wal {ds['wal']['segments']} segment(s), {ds['wal']['pending']} pending;
replayed {ds.get('replayed_points', 0)} pts /
{ds.get('replayed_series', 0)} series at attach</p>
<table border="1" cellpadding="4">
<tr><th>Tier</th><th>Blocks</th><th>Series</th><th>Bytes</th>
<th>Span</th></tr>
{rows}
</table>"""
            return (
                f"<h1>TSDB explorer</h1>{form}"
                f"<p>({tsdb.series_count()} series retained)</p>"
                f"{durable}"
            )
        match = None
        if match_raw:
            match = dict(
                p.split("=", 1) for p in match_raw.split(",") if "=" in p
            )
        series = tsdb.matching(name, match)
        if not series:
            return (
                f"<h1>TSDB explorer</h1>{form}"
                f"<p>(no series named <code>{html.escape(name)}</code>"
                + (f" matching <code>{html.escape(match_raw)}</code>"
                   if match_raw else "") + ")</p>"
            )
        rows = []
        for s in series[:32]:
            pts = tsdb.points(s)
            vals = [v for _t, v in pts]
            last = vals[-1] if vals else None
            lbls = ",".join(f"{k}={v}" for k, v in sorted(s.labels))
            rows.append(
                f"<tr><td><code>{html.escape(lbls) or '-'}</code></td>"
                f"<td>{html.escape(s.kind)}</td>"
                f"<td>{len(pts)}</td>"
                f"<td>{'-' if last is None else f'{last:g}'}</td>"
                f"<td><code>{html.escape(self._sparkline(vals))}</code>"
                f"</td></tr>"
            )
        extra = (
            f"<p>(showing 32 of {len(series)} series)</p>"
            if len(series) > 32 else ""
        )
        return f"""<h1>TSDB explorer</h1>{form}
<table border="1" cellpadding="4">
<tr><th>Labels</th><th>Kind</th><th>Points</th><th>Last</th>
<th>History</th></tr>
{''.join(rows)}
</table>{extra}"""

    def _lifecycle_html(self) -> str:
        """Model-lifecycle panel (ISSUE 5): versions newest-first with
        rollout state; active canaries lead the table. Registry fields
        carry operator-authored strings (reasons), so everything is
        escaped."""
        from predictionio_tpu.deploy.registry import ModelRegistry

        try:
            registry = getattr(self.server, "model_registry", None)
            if registry is None:
                registry = ModelRegistry(self.server.storage)
                self.server.model_registry = registry
            versions = registry.list()
        except Exception:
            return "<h1>Model lifecycle</h1><p>(registry unavailable)</p>"
        if not versions:
            return "<h1>Model lifecycle</h1><p>(no registered versions)</p>"
        order = {"canary": 0, "live": 1}
        versions.sort(key=lambda v: order.get(v.status, 2))
        rows = "".join(
            f"<tr><td>{html.escape(v.id)}</td>"
            f"<td>{html.escape(v.engine_id)}/{html.escape(v.engine_variant)}</td>"
            f"<td><b>{html.escape(v.status)}</b></td>"
            f"<td>{html.escape(v.created_at)}</td>"
            f"<td>{html.escape(v.params_hash)}</td>"
            f"<td>{html.escape(v.reason or '')}</td></tr>"
            for v in versions
        )
        return f"""<h1>Model lifecycle</h1>
<table border="1" cellpadding="4">
<tr><th>Version</th><th>Engine</th><th>Status</th><th>Created</th><th>Params hash</th><th>Note</th></tr>
{rows}
</table>"""


    def _tenants_html(self) -> str:
        """Multi-tenant panel (ISSUE 6): who shares the serving fleet,
        with weights and quotas. Descriptions are operator-authored, so
        everything is escaped."""
        from predictionio_tpu.tenancy.tenants import TenantStore

        try:
            store = getattr(self.server, "tenant_store", None)
            if store is None:
                store = TenantStore(self.server.storage)
                self.server.tenant_store = store
            tenants = store.list()
        except Exception:
            return "<h1>Tenants</h1><p>(tenant store unavailable)</p>"
        if not tenants:
            return "<h1>Tenants</h1><p>(no tenants registered)</p>"

        def fmt(v):
            return "∞" if v is None else html.escape(str(v))

        rows = "".join(
            f"<tr><td>{html.escape(t.id)}</td>"
            f"<td>{html.escape(t.engine_id)}/{html.escape(t.engine_variant)}</td>"
            f"<td>{t.weight:g}</td>"
            f"<td>{fmt(t.qps)}</td><td>{fmt(t.max_concurrency)}</td>"
            f"<td>{fmt(t.device_seconds_per_s)}</td>"
            f"<td>{'yes' if t.enabled else 'no'}</td>"
            f"<td>{html.escape(t.description)}</td></tr>"
            for t in tenants
        )
        return f"""<h1>Tenants</h1>
<table border="1" cellpadding="4">
<tr><th>Tenant</th><th>Engine</th><th>Weight</th><th>QPS</th>
<th>Concurrency</th><th>Device s/s</th><th>Enabled</th><th>Note</th></tr>
{rows}
</table>"""


class _Server(ThreadedServer):
    def __init__(self, addr, storage: Storage):
        super().__init__(addr, _Handler)
        self.storage = storage
        self.metrics = server_registry()
        self.metrics_label = "dashboard"


class Dashboard(ServerProcess):
    """The fleet aggregation point (ISSUE 8): when scrape targets are
    configured (constructor arg or PIO_MONITOR_TARGETS), a FleetScraper
    feeds every target's /metrics into the process TSDB under an
    `instance` label, and the index page grows Alerts + Fleet panels."""

    _name = "dashboard"

    def __init__(self, storage: Optional[Storage] = None, ip: str = "0.0.0.0",
                 port: int = 9000,
                 monitor_targets: Optional[str] = None,
                 scrape_interval_s: Optional[float] = None):
        from predictionio_tpu.utils.env import env_str

        super().__init__()
        self.storage = storage or Storage.get_instance()
        self.ip = ip
        self.port_config = port
        self.monitor_targets = (
            monitor_targets if monitor_targets is not None
            else env_str("PIO_MONITOR_TARGETS")
        )
        self.scrape_interval_s = scrape_interval_s
        self._scraper = None
        self._collector = None

    def _make_server(self) -> _Server:
        return _Server((self.ip, self.port_config), self.storage)

    def start(self) -> int:
        from predictionio_tpu.obs.monitor import (
            FleetScraper,
            TraceCollector,
            enabled,
            get_monitor,
            parse_targets,
        )
        from predictionio_tpu.utils.env import env_bool, env_flag, env_float

        port = super().start()
        targets = parse_targets(self.monitor_targets)
        if env_flag("PIO_PUSH_INGEST") and enabled() and targets == []:
            # pure push-ingest sink (ISSUE 17): spans arriving on
            # POST /telemetry/push need a collector to land in, but with
            # no scrape targets there is nothing to poll — mount one
            # WITHOUT starting its poll thread (zero polls, assembles
            # pushed traces only)
            self._collector = TraceCollector(targets=[], interval_s=3600)
            get_monitor().set_collector(self._collector)
        if targets and enabled():
            interval = (
                self.scrape_interval_s
                if self.scrape_interval_s is not None
                else env_float("PIO_SCRAPE_INTERVAL_S", 10.0)
            )
            self._scraper = FleetScraper(
                get_monitor().tsdb, targets, interval_s=interval,
            )
            self._scraper.start()
            self._server.fleet_scraper = self._scraper  # type: ignore
            if env_bool("PIO_TRACE_COLLECT"):
                # the dashboard doubles as the fleet's trace assembly
                # point when no gateway runs one (PIO_TRACE_COLLECT=1)
                self._collector = TraceCollector(
                    targets=list(targets), interval_s=interval,
                )
                get_monitor().set_collector(self._collector)
                self._collector.start()
            elif env_flag("PIO_PUSH_INGEST"):
                # scraping but not polling traces: pushed spans still
                # need a sink (unstarted — ingest only, zero polls)
                self._collector = TraceCollector(targets=[], interval_s=3600)
                get_monitor().set_collector(self._collector)
        return port

    def stop(self) -> None:
        if self._collector is not None:
            from predictionio_tpu.obs.monitor import get_monitor

            self._collector.stop()  # joins the collect thread
            mon = get_monitor()
            if mon.collector is self._collector:
                mon.set_collector(None)
            self._collector = None
        if self._scraper is not None:
            self._scraper.stop()  # joins the scrape thread
            self._scraper = None
        super().stop()
