"""L6 — CLI & ops tools (reference tools/src/main/scala/io/prediction/tools/)."""
