"""Device profiling: per-executable XLA cost/memory accounting + roofline.

PR 1 metrics say how long a request took and PR 2 spans say where the
wall time went — but neither says what the DEVICE did with it. This
module closes that gap (Williams et al.'s Roofline model, CACM 2009,
applied with Dapper's always-on production posture): every top-level
jit boundary the framework dispatches (train loops in models/als.py,
the dense edge passes in ops/dense.py, the serving kernels) is wrapped
by `instrument(name, fn)`, and the process-global `DeviceProfiler`
records, per named executable:

- FLOPs / bytes-accessed from XLA's `cost_analysis()` (computed ONCE
  per compiled signature from the cheap `Lowered` handle — no second
  backend compile);
- argument/output bytes from the concrete call, plus temp/generated-
  code bytes from `memory_analysis()` for wrappers that opt into
  `memory=True` (this one DOES pay a duplicate backend compile per
  signature, so only small serving programs enable it — their extra
  ~100 ms lands in warmup, never in a live query);
- compile seconds (diffed off jaxmon's compile listener around the
  first call per signature, which also keeps the first call's compile
  time OUT of the device-seconds accumulator);
- invocation counts and cumulative device seconds (dispatch + result
  ready — the wrapper blocks on the output, which every in-repo call
  site consumes immediately anyway).

From those it derives MFU (= executed FLOPs/s over the platform peak)
and HBM %-of-roof against a per-generation peak table (env-overridable
with PIO_PEAK_FLOPS / PIO_PEAK_HBM_BPS). Loop caveat, measured on this
jax: XLA's HLO cost analysis counts `fori_loop`/`scan` bodies ONCE
regardless of trip count, so train wrappers declare
`scale_by="iterations"` and per-call FLOPs multiply by that static
kwarg — the correction is framework-owned and recorded in the report
(`flops_scaled_by`).

Padding waste: the micro-batch dispatcher calls
`record_batch_padding(real, padded, flops=...)` per device batch; the
(padded-real)/padded ratio feeds a `batch_padding_ratio` histogram and
a wasted-FLOPs counter on the process-default registry, so every
server's `/metrics` and `GET /debug/profile` can say "38% of that
batch was padding".

Degradation contract (same as obs/jaxmon.py): importing this module
never imports jax; with jax absent every wrapper is a passthrough and
`report()` returns an empty profile; cost_analysis/memory_analysis
raising (private-API drift) zeroes that executable's analysis but
still counts invocations/seconds — serving must never 500 because
profiling broke. Set PIO_DEVPROF=0 to disable instrumentation wholesale.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, NamedTuple, Optional

from predictionio_tpu.obs import jaxmon as _jaxmon
from predictionio_tpu.obs.registry import MetricsRegistry, get_default_registry
from predictionio_tpu.utils.env import env_bool, env_opt_float, env_raw
from predictionio_tpu.analysis import tsan as _tsan

# -- platform peaks ---------------------------------------------------------

#: device_kind substring (lowercase) → (peak FLOP/s, peak HBM bytes/s).
#: TPU numbers are the published per-chip bf16 dense peaks; the CPU row
#: is a deliberately round server-class fallback so MFU stays a small
#: honest fraction instead of None on dev boxes. Longest match wins
#: ("tpu v5 lite" before "tpu v5").
PEAK_TABLE: dict[str, tuple[float, float]] = {
    "tpu v2": (45e12, 700e9),
    "tpu v3": (123e12, 900e9),
    "tpu v4": (275e12, 1228e9),
    "tpu v5 lite": (197e12, 819e9),
    "tpu v5e": (197e12, 819e9),
    "tpu v5p": (459e12, 2765e9),
    "tpu v5": (459e12, 2765e9),
    "tpu v6 lite": (918e12, 1640e9),
    "tpu v6e": (918e12, 1640e9),
    "cpu": (2e11, 50e9),
}

#: dtype-aware peak FLOP/s per generation (ISSUE 11 satellite, carried
#: PR-3 follow-up): an int8 serving kernel rooflined against the bf16
#: peak under-reports how far from the hardware ceiling it really is —
#: and vice versa for f32. int8 entries are the published int8 TOPS
#: where the generation has an int8 MXU mode (v5e onward; v2–v4 run
#: int8 through the bf16 path, so int8 == bf16 there); f32 entries are
#: the bf16/2 convention of the MXU's f32 passthrough. The default
#: (no-dtype) lookup stays the bf16 column, so every pre-existing
#: number keeps its meaning. Env overrides: PIO_PEAK_FLOPS (bf16 /
#: default), PIO_PEAK_FLOPS_INT8, PIO_PEAK_FLOPS_F32.
PEAK_DTYPE_TABLE: dict[str, dict[str, float]] = {
    "tpu v2": {"f32": 22.5e12, "int8": 45e12},
    "tpu v3": {"f32": 61.5e12, "int8": 123e12},
    "tpu v4": {"f32": 137.5e12, "int8": 275e12},
    "tpu v5 lite": {"f32": 98.5e12, "int8": 394e12},
    "tpu v5e": {"f32": 98.5e12, "int8": 394e12},
    "tpu v5p": {"f32": 229.5e12, "int8": 918e12},
    "tpu v5": {"f32": 229.5e12, "int8": 918e12},
    "tpu v6 lite": {"f32": 459e12, "int8": 1836e12},
    "tpu v6e": {"f32": 459e12, "int8": 1836e12},
    # CPU fallback: one round number for every dtype — dev boxes only
    "cpu": {"f32": 2e11, "int8": 2e11},
}

#: batch padding ratio lives in [0, 1); these resolve the interesting
#: shapes (exact fills at 0, the pow2-bucket half/quarter fills, tails)
PADDING_RATIO_BUCKETS: tuple[float, ...] = (
    0.0, 0.0625, 0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 0.984375,
)


def _env_float(name: str) -> Optional[float]:
    return env_opt_float(name)


def platform_info(dtype: Optional[str] = None) -> dict:
    """Platform + resolved peaks. Never imports jax: a data-plane process
    that hasn't paid the jax import reports platform None (and env
    overrides still apply, so a fleet can pin peaks centrally).

    `dtype` ("int8" | "f32" | "bf16" | None) selects the peak-FLOPs
    column (ISSUE 11 satellite); None/"bf16" keeps the legacy bf16
    entry. The resolved dtype peak rides in `peak_flops`; `peak_flops`
    with no dtype is unchanged from every prior PR."""
    platform = kind = None
    if "jax" in sys.modules:
        try:
            import jax

            dev = jax.devices()[0]
            platform, kind = dev.platform, dev.device_kind
        except Exception:
            pass
    dt = dtype if dtype in ("int8", "f32") else None
    env_name = {
        "int8": "PIO_PEAK_FLOPS_INT8", "f32": "PIO_PEAK_FLOPS_F32",
    }.get(dt, "PIO_PEAK_FLOPS")
    peak_flops = _env_float(env_name)
    if peak_flops is None and dt is not None:
        # a fleet pinning only PIO_PEAK_FLOPS pins every dtype: a
        # central override beats a table guess for the wrong column
        peak_flops = _env_float("PIO_PEAK_FLOPS")
    peak_hbm = _env_float("PIO_PEAK_HBM_BPS")
    source = "env" if (peak_flops or peak_hbm) else None
    if peak_flops is None or peak_hbm is None:
        best = None
        for key in (kind, platform):
            if not key:
                continue
            lowered = str(key).lower()
            for entry, peaks in PEAK_TABLE.items():
                if entry in lowered and (
                    best is None or len(entry) > len(best[0])
                ):
                    best = (entry, peaks)
            if best is not None:
                break
        if best is not None:
            source = source or "table"
            if peak_flops is None:
                peak_flops = best[1][0]
                if dt is not None:
                    dtyped = PEAK_DTYPE_TABLE.get(best[0], {})
                    peak_flops = dtyped.get(dt, peak_flops)
            if peak_hbm is None:
                peak_hbm = best[1][1]
    return {
        "platform": platform,
        "device_kind": kind,
        "peak_flops": peak_flops,
        "peak_hbm_bps": peak_hbm,
        "peak_source": source or "none",
        **({"peak_dtype": dt} if dt is not None else {}),
    }


def mfu(flops: float, seconds: float,
        dtype: Optional[str] = None) -> Optional[float]:
    """Executed-FLOPs utilization vs the platform peak for `dtype`
    (default bf16), clamped to 1.0 (cost-analysis estimates can
    overshoot on fused programs); None when either input or the peak is
    unknown."""
    peak = platform_info(dtype)["peak_flops"]
    if not peak or seconds <= 0 or flops <= 0:
        return None
    return min(1.0, flops / seconds / peak)


def hbm_fraction(nbytes: float, seconds: float) -> Optional[float]:
    """HBM-traffic fraction of the platform roof (same contract as mfu)."""
    peak = platform_info()["peak_hbm_bps"]
    if not peak or seconds <= 0 or nbytes <= 0:
        return None
    return min(1.0, nbytes / seconds / peak)


# -- per-executable accounting ---------------------------------------------


@dataclass
class _SigAnalysis:
    """What XLA said about one compiled signature of an executable."""

    flops: float = 0.0
    bytes_accessed: float = 0.0
    arg_bytes: float = 0.0
    output_bytes: float = 0.0
    temp_bytes: float = 0.0
    code_bytes: float = 0.0
    cost_ok: bool = False
    memory_ok: bool = False
    # loop-FLOPs calibration (ISSUE 8 satellite, the PR-3 follow-up):
    # True when `flops`/`bytes_accessed` already include the loop trip
    # count via the 1-vs-2-iteration lowering diff — the caller must
    # NOT also multiply by the `scale_by` kwarg
    calibrated: bool = False
    # the raw one-pass numbers XLA reported for the actual kwargs, kept
    # so the report can show the kwarg-scaled estimate for comparison
    flops_body: float = 0.0
    bytes_body: float = 0.0
    iterations: float = 1.0
    # per-shard attribution (ISSUE 10): the device count this
    # signature's arguments span (sharded fleet programs > 1). For the
    # shard_map programs this tree shards with, XLA lowers — and cost/
    # memory-analyzes — the PER-DEVICE module, so flops/bytes here are
    # already one shard's share; `devices` is the context a reader
    # needs to reconstruct the global program (flops × devices).
    devices: float = 1.0
    # compute dtype of this signature (ISSUE 11 satellite): set by the
    # wrapper's dtype_of hook (e.g. the serving jit reports "int8" for
    # quantized signatures); None keeps the legacy bf16 roofline
    dtype: Optional[str] = None


@dataclass
class _Exec:
    name: str
    scale_by: Optional[str] = None
    signatures: dict = field(default_factory=dict)  # sig key → _SigAnalysis
    invocations: int = 0
    device_seconds: float = 0.0
    compile_seconds: float = 0.0
    flops_total: float = 0.0
    bytes_total: float = 0.0
    # per-dtype accumulation (ISSUE 14 satellite, carried devprof
    # follow-up): a MIXED-dtype executable (fused int8+f32 serving
    # verbs, a model serving f32 while its canary serves int8) used to
    # roofline everything against its LATEST signature's peak column —
    # dtype → [flops, device_seconds, invocations] splits it so each
    # column rooflines against its own peak
    dtype_totals: dict = field(default_factory=dict)


class ProfTotals(NamedTuple):
    """Cumulative device accounting — DASE stage spans diff this across
    a stage (the compile_snapshot pattern)."""

    flops: float
    bytes: float
    device_seconds: float
    invocations: int


def _leaf_sig(obj: Any) -> Any:
    shape = getattr(obj, "shape", None)
    dtype = getattr(obj, "dtype", None)
    if shape is not None and dtype is not None:
        return ("arr", tuple(shape), str(dtype))
    if isinstance(obj, float):
        # traced python-float scalars (λ, α sweeps) share one executable;
        # keying on the value would mint a spurious "signature" per sweep
        # point. Static floats (rare) just reuse the first analysis.
        return ("f",)
    try:
        hash(obj)
        return ("v", obj)
    except TypeError:
        return ("t", type(obj).__name__)


def _signature(args: tuple, kwargs: dict) -> tuple:
    def walk(x: Any) -> Any:
        if isinstance(x, (tuple, list)):
            return tuple(walk(v) for v in x)
        if isinstance(x, dict):
            return tuple(sorted((k, walk(v)) for k, v in x.items()))
        return _leaf_sig(x)

    return (walk(args), walk(kwargs))


def _arg_device_span(args: tuple, kwargs: dict) -> float:
    """Max device count any argument's sharding spans (1 for host
    arrays and single-device jax arrays) — the divisor for per-shard
    FLOPs/HBM attribution of sharded executables (ISSUE 10)."""
    n = 1

    def walk(x: Any) -> None:
        nonlocal n
        sh = getattr(x, "sharding", None)
        if sh is not None:
            try:
                n = max(n, len(sh.device_set))
                return
            except Exception:
                pass
        if isinstance(x, dict):
            for v in x.values():
                walk(v)
        elif isinstance(x, (list, tuple)):
            for v in x:
                walk(v)

    walk(args)
    walk(kwargs)
    return float(n)


def _arg_nbytes(args: tuple, kwargs: dict) -> float:
    total = 0.0

    def walk(x: Any) -> None:
        nonlocal total
        if isinstance(x, (tuple, list)):
            for v in x:
                walk(v)
        elif isinstance(x, dict):
            for v in x.values():
                walk(v)
        else:
            n = getattr(x, "nbytes", None)
            if isinstance(n, (int, float)):
                total += n

    walk(args)
    walk(kwargs)
    return total


def _under_trace() -> bool:
    """True while an outer jit is tracing through the wrapper — nested
    dispatches must pass straight through (timing tracers is meaningless
    and blocking them raises)."""
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import core as _core

        return not _core.trace_state_clean()
    except Exception:
        return False


#: slot reservation for a signature whose first call is still in flight —
#: exactly ONE caller runs the (possibly compile-paying) analysis; racing
#: callers account their invocation with zero flops rather than also
#: analyzing (a duplicate backend compile on the live serving path)
_ANALYSIS_PENDING = _SigAnalysis()


class DeviceProfiler:
    """Thread-safe registry of profiled executables."""

    def __init__(self):
        self._lock = threading.Lock()
        self._execs: dict[str, _Exec] = {}

    # -- recording --------------------------------------------------------
    def call(self, wrapper: "_Instrumented", args: tuple, kwargs: dict):
        """Run the wrapped executable once, with best-effort accounting:
        every profiler step is fenced so a bookkeeping bug degrades to an
        unprofiled call — the wrapped function itself runs exactly once
        and its exceptions propagate untouched."""
        fn = wrapper.__wrapped__
        rec = None
        new_sig = pending_race = False
        sig: tuple = ("?",)
        t0 = s0 = 0.0
        try:
            try:
                sig = _signature(args, kwargs)
            except Exception:
                sig = ("?",)
            with self._lock:
                rec = self._execs.get(wrapper.name)
                if rec is None:
                    rec = self._execs[wrapper.name] = _Exec(
                        wrapper.name, scale_by=wrapper.scale_by
                    )
                existing = rec.signatures.get(sig)
                if existing is None:
                    # reserve the slot: racing first calls must not each
                    # run _analyze (and its optional duplicate compile)
                    rec.signatures[sig] = _ANALYSIS_PENDING
                    new_sig = True
                elif existing is _ANALYSIS_PENDING:
                    # another thread's first call is compiling this
                    # signature right now — this call will block on that
                    # compile inside jax, so its timing needs the same
                    # compile-seconds deduction a first call gets
                    pending_race = True
            if new_sig:
                # arm jax's compile listener BEFORE the compiling call so
                # the compile-seconds diff below actually sees the compile
                _jaxmon.ensure_compile_listener()
            _c0, s0 = _jaxmon.compile_snapshot()
            t0 = time.perf_counter()
        except Exception:
            rec = None
        try:
            out = fn(*args, **kwargs)
        except BaseException:
            # the reserved slot must not poison the signature forever —
            # a later successful call should get to analyze it
            if rec is not None and new_sig:
                with self._lock:
                    if rec.signatures.get(sig) is _ANALYSIS_PENDING:
                        del rec.signatures[sig]
            raise
        if rec is None:
            return out
        try:
            try:
                import jax

                out = jax.block_until_ready(out)
            except Exception:
                pass
            dt = time.perf_counter() - t0
            compile_sec = 0.0
            analysis = None
            if new_sig or pending_race:
                _c1, s1 = _jaxmon.compile_snapshot()
                # the listener is process-global: overlapping compiles on
                # OTHER threads land in this diff too — acceptable skew,
                # bounded by how often fresh signatures race
                compile_sec = max(0.0, s1 - s0)
                # compile-paying calls (the first, and racers blocked on
                # its compile) keep trace/lower/compile time out of the
                # device-seconds accumulator so MFU reflects steady state
                dt = max(0.0, dt - compile_sec)
            if new_sig:
                analysis = self._analyze(wrapper, fn, args, kwargs, out)
            scale = 1.0
            if wrapper.scale_by is not None:
                try:
                    scale = float(kwargs.get(wrapper.scale_by) or 1)
                except (TypeError, ValueError):
                    scale = 1.0
            with self._lock:
                if new_sig:
                    rec.signatures[sig] = analysis
                    rec.compile_seconds += compile_sec
                else:
                    # racing caller: the analyzer may have finished by
                    # now — use its numbers, else count flops as zero
                    analysis = rec.signatures.get(sig)
                    if analysis is None or analysis is _ANALYSIS_PENDING:
                        analysis = _ANALYSIS_PENDING
                if analysis.calibrated:
                    # the 1-vs-2-iteration lowering already folded the
                    # trip count in — kwarg scaling would double-count
                    scale = 1.0
                rec.invocations += 1
                rec.device_seconds += dt
                rec.flops_total += analysis.flops * scale
                rec.bytes_total += analysis.bytes_accessed * scale
                if analysis.dtype is not None:
                    t = rec.dtype_totals.setdefault(
                        analysis.dtype, [0.0, 0.0, 0]
                    )
                    t[0] += analysis.flops * scale
                    t[1] += dt
                    t[2] += 1
        except Exception:
            pass
        return out

    def _analyze(
        self, wrapper: "_Instrumented", fn: Any, args: tuple, kwargs: dict,
        out: Any,
    ) -> _SigAnalysis:
        """XLA's view of this signature. Everything is best-effort: the
        AOT surface (`lower`, `cost_analysis`, `memory_analysis`) is
        semi-private and has drifted across jax releases — any failure
        degrades to zeros, never to an exception."""
        res = _SigAnalysis(
            arg_bytes=_arg_nbytes(args, kwargs),
            output_bytes=_arg_nbytes((out,), {}),
        )
        try:
            res.devices = _arg_device_span(args, kwargs)
        except Exception:
            pass
        if wrapper.dtype_of is not None:
            try:
                res.dtype = wrapper.dtype_of(args, kwargs)
            except Exception:
                pass
        lower = getattr(fn, "lower", None)
        if lower is None:
            return res
        try:
            lowered = lower(*args, **kwargs)
        except Exception:
            return res
        try:
            ca = lowered.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            res.flops = float(ca.get("flops", 0.0) or 0.0)
            res.bytes_accessed = float(ca.get("bytes accessed", 0.0) or 0.0)
            res.cost_ok = True
        except Exception:
            pass
        if res.cost_ok and wrapper.scale_by is not None:
            self._calibrate_loop(wrapper, lower, args, kwargs, res)
        if wrapper.memory_enabled():
            try:
                compiled = lowered.compile()
                ma = compiled.memory_analysis()
                res.arg_bytes = float(ma.argument_size_in_bytes)
                res.output_bytes = float(ma.output_size_in_bytes)
                res.temp_bytes = float(ma.temp_size_in_bytes)
                res.code_bytes = float(ma.generated_code_size_in_bytes)
                res.memory_ok = True
                try:
                    # post-optimization cost analysis is the more honest
                    # number when we paid for the compile anyway
                    ca = compiled.cost_analysis()
                    if isinstance(ca, (list, tuple)):
                        ca = ca[0] if ca else {}
                    if ca.get("flops"):
                        res.flops = float(ca["flops"])
                    if ca.get("bytes accessed"):
                        res.bytes_accessed = float(ca["bytes accessed"])
                except Exception:
                    pass
            except Exception:
                pass
        return res

    @staticmethod
    def _calibrate_loop(wrapper: "_Instrumented", lower: Any, args: tuple,
                        kwargs: dict, res: _SigAnalysis) -> None:
        """Calibrate loop FLOPs with 1- and 2-iteration lowerings
        (ISSUE 8 satellite, the PR-3 follow-up). XLA's HLO cost
        analysis counts a `fori_loop`/`scan` body ONCE regardless of
        trip count; PR 3 corrected by multiplying the whole program by
        the static `scale_by` kwarg — which also scales the loop-
        INVARIANT work (setup, output gather). Lowering the same
        signature at 1 and 2 iterations separates the two:

            per_iteration = cost(2) - cost(1)
            total(n)      = cost(1) + (n - 1) * per_iteration

        Lowering is trace-only (no backend compile) and runs once per
        signature. Any failure — the kwarg not accepted, cost analysis
        drift, a non-positive diff (XLA fully unrolled or folded the
        loop, where the one-pass numbers are already honest) — falls
        back to the PR-3 kwarg scaling, recorded as `flops_scaled_by`
        with `flops_calibrated: false` in the report."""
        res.flops_body, res.bytes_body = res.flops, res.bytes_accessed
        try:
            n = float(kwargs.get(wrapper.scale_by) or 1)
        except (TypeError, ValueError):
            return
        res.iterations = n
        try:
            costs = []
            for iters in (1, 2):
                ca = lower(
                    *args, **{**kwargs, wrapper.scale_by: iters}
                ).cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                costs.append((
                    float(ca.get("flops", 0.0) or 0.0),
                    float(ca.get("bytes accessed", 0.0) or 0.0),
                ))
            (f1, b1), (f2, b2) = costs
        except Exception:
            return
        if f1 <= 0 or f2 <= f1:
            # the lowering's cost does NOT scale with the trip count
            # (XLA counted the while body once): the 1-vs-2 diff can't
            # see the loop, so the kwarg fallback is the honest scaling
            return
        res.flops = f1 + (n - 1) * (f2 - f1)
        res.bytes_accessed = max(b1, b1 + (n - 1) * (b2 - b1))
        res.calibrated = True

    def record_external(self, name: str, seconds: float,
                        invocations: int = 1) -> None:
        """Attribute externally-measured device seconds to a named
        executable (callers that own their timing, e.g. a dispatcher)."""
        with self._lock:
            rec = self._execs.get(name)
            if rec is None:
                rec = self._execs[name] = _Exec(name)
            rec.device_seconds += max(0.0, seconds)
            rec.invocations += invocations

    # -- reading ----------------------------------------------------------
    def snapshot(self) -> ProfTotals:
        with self._lock:
            return ProfTotals(
                flops=sum(e.flops_total for e in self._execs.values()),
                bytes=sum(e.bytes_total for e in self._execs.values()),
                device_seconds=sum(
                    e.device_seconds for e in self._execs.values()
                ),
                invocations=sum(
                    e.invocations for e in self._execs.values()
                ),
            )

    def executable_count(self) -> int:
        with self._lock:
            return len(self._execs)

    def compile_seconds_total(self) -> float:
        with self._lock:
            return sum(e.compile_seconds for e in self._execs.values())

    def executable(self, name: str) -> Optional[dict]:
        with self._lock:
            rec = self._execs.get(name)
            if rec is None:
                return None
            return self._exec_dict(rec, platform_info(), {})

    def _exec_dict(self, rec: _Exec, plat: dict,
                   dtype_peaks: Optional[dict] = None) -> dict:
        sigs = [
            s for s in rec.signatures.values()
            if s is not _ANALYSIS_PENDING
        ]
        latest = sigs[-1] if sigs else _SigAnalysis()
        out = {
            "name": rec.name,
            "signatures": len(rec.signatures),
            "invocations": rec.invocations,
            "compile_seconds": round(rec.compile_seconds, 4),
            "device_seconds": round(rec.device_seconds, 6),
            "flops_per_call": latest.flops,
            "bytes_per_call": latest.bytes_accessed,
            "flops_total": rec.flops_total,
            "bytes_total": rec.bytes_total,
            "argument_bytes": latest.arg_bytes,
            "output_bytes": latest.output_bytes,
            "temp_bytes": latest.temp_bytes,
            "generated_code_bytes": latest.code_bytes,
            "cost_analysis_ok": any(s.cost_ok for s in sigs),
            "memory_analysis_ok": any(s.memory_ok for s in sigs),
        }
        if latest.devices > 1:
            # per-shard attribution (ISSUE 10). Measured semantics (see
            # tests/test_devprof_shards.py): shard_map programs — every
            # sharded executable in this tree — LOWER THE PER-DEVICE
            # module, so cost_analysis flops/bytes (and the mfu derived
            # from them against one chip's peak) are ALREADY per-shard;
            # dividing again would under-report by devices×. Likewise
            # memory_analysis sizes are per-device with replicated
            # operands counted in full — exactly the one-chip resident
            # picture — so they pass through undivided too.
            out["devices"] = latest.devices
            if latest.memory_ok:
                out["hbm_bytes_per_shard"] = (
                    latest.arg_bytes + latest.output_bytes
                    + latest.temp_bytes
                )
        if rec.scale_by is not None:
            # kept for comparison with the calibrated numbers (ISSUE 8
            # satellite): `flops_per_call_kwarg_scaled` is what the
            # PR-3 trust-the-kwarg estimate would have claimed
            out["flops_scaled_by"] = rec.scale_by
            out["flops_calibrated"] = any(s.calibrated for s in sigs)
            if latest.calibrated:
                out["flops_per_call_kwarg_scaled"] = (
                    latest.flops_body * latest.iterations
                )
        # derived roofline fields against the caller-resolved peaks (the
        # peak table + env + jax.devices lookup is process-constant, so
        # a report resolves it ONCE, not per executable per field).
        # dtype-aware (ISSUE 11): a signature that declared a compute
        # dtype rooflines against THAT column — int8 serving kernels
        # against the int8 peak, not the bf16 one. The latest signature
        # decides the LEGACY scalar fields; mixed-dtype executables
        # additionally get per-dtype columns below (ISSUE 14).
        peak_f, peak_h = plat.get("peak_flops"), plat.get("peak_hbm_bps")

        def dtyped_peak(dt: str):
            # dtyped columns resolve once per report via the shared
            # cache, keeping the once-per-report invariant above
            cache = dtype_peaks if dtype_peaks is not None else {}
            if dt not in cache:
                cache[dt] = platform_info(dt).get("peak_flops")
            return cache[dt]

        if latest.dtype is not None:
            out["dtype"] = latest.dtype
            if latest.dtype in ("int8", "f32"):
                dt_peak = dtyped_peak(latest.dtype)
                if dt_peak:
                    peak_f = dt_peak
                    out["peak_flops_dtype"] = dt_peak
        if rec.dtype_totals:
            # per-dtype columns (ISSUE 14 satellite): every dtype this
            # executable ran at rooflines against ITS OWN peak — a
            # mixed int8+f32 verb no longer reports only the latest
            # signature's column
            cols = {}
            for dt, (fl, sec, inv) in sorted(rec.dtype_totals.items()):
                col = {
                    "flops_total": fl,
                    "device_seconds": round(sec, 6),
                    "invocations": inv,
                }
                dt_peak = (
                    dtyped_peak(dt) if dt in ("int8", "f32")
                    else plat.get("peak_flops")
                )
                if dt_peak:
                    col["peak_flops"] = dt_peak
                    if sec > 0 and fl > 0:
                        col["mfu"] = round(
                            min(1.0, fl / sec / dt_peak), 8
                        )
                cols[dt] = col
            out["dtypes"] = cols
        if peak_f and rec.device_seconds > 0 and rec.flops_total > 0:
            out["mfu"] = round(
                min(1.0, rec.flops_total / rec.device_seconds / peak_f), 8
            )
            out["flops_per_sec"] = rec.flops_total / rec.device_seconds
        if peak_h and rec.device_seconds > 0 and rec.bytes_total > 0:
            out["hbm_fraction_of_roof"] = round(
                min(1.0, rec.bytes_total / rec.device_seconds / peak_h), 8
            )
            out["hbm_bytes_per_sec"] = rec.bytes_total / rec.device_seconds
        return out

    def report(self) -> dict:
        """The `GET /debug/profile` payload: platform + peaks, every
        profiled executable with derived roofline numbers, padding-waste
        accounting, and process totals."""
        plat = platform_info()
        dtype_peaks: dict = {}  # shared per-report dtype-column cache
        with self._lock:
            rows = [
                self._exec_dict(r, plat, dtype_peaks)
                for r in self._execs.values()
            ]
        rows.sort(key=lambda r: -r["device_seconds"])
        totals = self.snapshot()
        peak_f = plat.get("peak_flops")
        report: dict[str, Any] = {
            "platform": plat,
            "executables": rows,
            "totals": {
                "flops": totals.flops,
                "bytes": totals.bytes,
                "device_seconds": round(totals.device_seconds, 6),
                "invocations": totals.invocations,
                "mfu": (
                    min(1.0, totals.flops / totals.device_seconds / peak_f)
                    if peak_f and totals.device_seconds > 0
                    and totals.flops > 0 else None
                ),
            },
            "padding": padding_summary(),
        }
        return report

    def clear(self) -> None:
        with self._lock:
            self._execs.clear()


_profiler = DeviceProfiler()


def get_profiler() -> DeviceProfiler:
    return _profiler


def snapshot() -> ProfTotals:
    """Module-level convenience — the stage-span diff pattern."""
    return _profiler.snapshot()


def report() -> dict:
    return _profiler.report()


def _enabled() -> bool:
    return env_bool("PIO_DEVPROF")


# -- the jit-boundary hook --------------------------------------------------


class _Instrumented:
    """Callable wrapper around a jit-compiled function. Transparent when
    profiling is disabled, jax is absent, or an outer jit is tracing
    through; attribute access (`.lower`, `.clear_cache`, …) forwards to
    the wrapped function so AOT users don't notice the wrapper."""

    def __init__(self, name: str, fn: Callable,
                 scale_by: Optional[str] = None,
                 memory: bool = False,
                 dtype_of: Optional[Callable] = None):
        self.name = name
        self.__wrapped__ = fn
        self.scale_by = scale_by
        self.memory = memory
        self.dtype_of = dtype_of
        self.__doc__ = getattr(fn, "__doc__", None)

    def memory_enabled(self) -> bool:
        env = (env_raw("PIO_DEVPROF_MEMORY") or "").strip()
        if env == "0":
            return False
        if env == "1":
            return True
        return self.memory

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        # sanitizer hook (ISSUE 12): a lock held across a device
        # dispatch serializes the whole server behind it — near-zero
        # cost (one bool) when PIO_TSAN is off
        _tsan.note_blocking("device.dispatch")
        if not _enabled() or "jax" not in sys.modules or _under_trace():
            return self.__wrapped__(*args, **kwargs)
        # call() fences all its own bookkeeping: the wrapped function
        # executes exactly once and its exceptions propagate untouched
        return _profiler.call(self, args, kwargs)

    def __getattr__(self, item: str) -> Any:
        return getattr(self.__wrapped__, item)


def instrument(name: str, fn: Callable, *, scale_by: Optional[str] = None,
               memory: bool = False,
               dtype_of: Optional[Callable] = None) -> Callable:
    """Hook a top-level jit boundary into the device profiler.

    `scale_by` names a STATIC kwarg whose value multiplies the analyzed
    per-call FLOPs/bytes — the fori_loop/scan correction (XLA's HLO cost
    analysis counts loop bodies once; verified on this jax).
    `memory=True` opts into full `memory_analysis()` (a duplicate
    backend compile per signature — small serving programs only).
    `dtype_of(args, kwargs)` declares a signature's COMPUTE dtype
    ("int8"/"f32"/"bf16") so the roofline uses that dtype's peak
    (ISSUE 11); None keeps the legacy bf16 denominator — only the call
    site knows whether its MXU work is int8 or merely int8-STORED, so
    this is explicit, never inferred from argument dtypes."""
    return _Instrumented(
        name, fn, scale_by=scale_by, memory=memory, dtype_of=dtype_of
    )


# -- padding-waste accounting ----------------------------------------------


def _padding_hist(reg: MetricsRegistry):
    """Single declaration point for the padding metrics: the recorder and
    the summary reader MUST resolve identical definitions (the registry
    raises on bucket drift between re-registrations)."""
    return reg.histogram(
        "batch_padding_ratio",
        "fraction of each coalesced device batch that was padding",
        buckets=PADDING_RATIO_BUCKETS,
    )


def _padding_counters(reg: MetricsRegistry):
    return (
        reg.counter(
            "batch_rows_real_total",
            "live query rows through device batches",
        ),
        reg.counter(
            "batch_rows_padded_total",
            "total rows (live + padding) through device batches",
        ),
        reg.counter(
            "batch_padding_wasted_flops_total",
            "device FLOPs spent computing padding rows",
        ),
    )


def record_batch_padding(real_rows: int, padded_rows: int,
                         flops: float = 0.0,
                         registry: Optional[MetricsRegistry] = None) -> None:
    """Account one padded device batch: `real_rows` live queries ran in a
    `padded_rows`-shaped program (serving-shape bucketing), so
    (padded-real)/padded of the work was waste. `flops` is the executed-
    FLOPs attribution for the batch (typically a devprof snapshot diff
    across the device call at the pad site — approximate under
    concurrent batches, exact in aggregate)."""
    if padded_rows <= 0:
        return
    real_rows = max(0, min(real_rows, padded_rows))
    ratio = (padded_rows - real_rows) / padded_rows
    reg = registry if registry is not None else get_default_registry()
    _padding_hist(reg).observe(ratio)
    real_c, padded_c, wasted_c = _padding_counters(reg)
    real_c.inc(real_rows)
    padded_c.inc(padded_rows)
    if flops > 0 and ratio > 0:
        wasted_c.inc(flops * ratio)


def padding_summary(registry: Optional[MetricsRegistry] = None) -> dict:
    """The padding section of `report()` — read back off the registry the
    pad sites record into, so /metrics and /debug/profile can never
    disagree."""
    reg = registry if registry is not None else get_default_registry()
    hist = _padding_hist(reg)
    real_c, padded_c, wasted_c = _padding_counters(reg)
    return {
        "batches": hist.count,
        "mean_padding_ratio": round(hist.mean, 6),
        "p50_padding_ratio": round(hist.quantile(0.5), 6),
        "rows_real": real_c.total,
        "rows_padded": padded_c.total,
        "wasted_flops": wasted_c.total,
    }


# -- /metrics gauges --------------------------------------------------------


def install_devprof_gauges(registry: MetricsRegistry) -> None:
    """Mount the profiler's cumulative totals as scrape-time callback
    gauges (idempotent per registry, same posture as install_jax_gauges)."""
    registry.gauge_callback(
        "devprof_executables",
        "distinct profiled executables in this process",
        lambda: float(_profiler.executable_count()),
    )
    registry.gauge_callback(
        "devprof_invocations_total",
        "profiled executable invocations",
        lambda: float(_profiler.snapshot().invocations),
    )
    registry.gauge_callback(
        "devprof_device_seconds_total",
        "cumulative device seconds across profiled executables",
        lambda: _profiler.snapshot().device_seconds,
    )
    registry.gauge_callback(
        "devprof_flops_total",
        "cumulative executed FLOPs across profiled executables",
        lambda: _profiler.snapshot().flops,
    )
    registry.gauge_callback(
        "devprof_bytes_total",
        "cumulative HBM bytes accessed across profiled executables",
        lambda: _profiler.snapshot().bytes,
    )
    registry.gauge_callback(
        "devprof_compile_seconds_total",
        "cumulative XLA compile seconds attributed to profiled executables",
        _profiler.compile_seconds_total,
    )

    def _lifetime_mfu() -> float:
        totals = _profiler.snapshot()  # one snapshot: coherent num/denom
        return mfu(totals.flops, totals.device_seconds) or 0.0

    registry.gauge_callback(
        "devprof_mfu",
        "process-lifetime model FLOPs utilization (0 when unknown)",
        _lifetime_mfu,
    )


# -- on-demand XLA profiler capture ----------------------------------------

_capture_lock = threading.Lock()


def capture_trace(directory: str, seconds: float) -> dict:
    """Open a jax.profiler trace window for `seconds` and write it under
    `directory` (inspect with tensorboard/xprof/perfetto). Raises
    RuntimeError when jax is not loaded in this process or a capture is
    already running — callers map those to 409."""
    seconds = float(seconds)
    if not 0.0 < seconds <= 60.0:
        raise ValueError("capture seconds must be in (0, 60]")
    if "jax" not in sys.modules:
        raise RuntimeError(
            "jax is not loaded in this process — nothing to capture"
        )
    if not _capture_lock.acquire(blocking=False):
        raise RuntimeError("a profiler capture is already running")
    try:
        import jax

        jax.profiler.start_trace(directory)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
    finally:
        _capture_lock.release()
    return {"dir": directory, "seconds": seconds}
