"""Hierarchical span tracing with tail-based sampling (ISSUE 2).

PR 1's flat `X-Request-ID` + aggregate histograms answer "how slow is
this route on average" but not "*where* did this one slow query spend
its 400 ms" — in the micro-batch queue, the device dispatch, or a
remote-storage round trip. This module adds the Dapper-style span model
on top of the existing trace-id plumbing:

- `span(name, **attrs)` opens a hierarchical span: trace_id comes from
  the `obs.tracing` ContextVar (or is minted, establishing a trace),
  span_id is fresh, parent_span_id is the enclosing span in this
  context (or an explicit remote parent — the `X-Parent-Span` header
  carries span identity across processes, so a storage daemon's server
  span parents under the deploy server's RPC client span).
- `SpanRecorder` keeps a bounded in-memory store of *completed traces*
  with **tail-based sampling**: the keep/drop decision happens when the
  trace's local root span completes, so traces that errored or exceeded
  the slow threshold are always retained, the boring rest is sampled
  probabilistically, and the oldest kept traces are evicted beyond a
  cap. (Head-based sampling cannot do this — it must decide before
  knowing the outcome.)
- `perfetto_export()` renders retained traces as Chrome trace-event
  JSON, loadable at https://ui.perfetto.dev for a flame view.
- A metric bridge feeds the durations of a declared subset of span
  names into existing `MetricsRegistry` histograms, so `/metrics`
  aggregates and `/debug/traces` exemplars are one consistent story
  (the span IS the observation; nothing is counted twice).

Knobs (read once when the default recorder is created; also mutable
attributes on the recorder for tests/benchmarks):
  PIO_TRACE_MAX      retained-trace cap            (default 256)
  PIO_TRACE_SLOW_MS  always-keep latency threshold (default 250)
  PIO_TRACE_SAMPLE   keep probability for the rest (default 0.1)

Thread-safety: one lock guards the recorder's maps; span context lives
in ContextVars, so keep-alive handler threads and the micro-batch
dispatcher cannot leak spans across requests."""

from __future__ import annotations

import contextvars
import os
import random
import threading
import time
import uuid
from collections import OrderedDict, deque
from contextlib import contextmanager
from dataclasses import dataclass, field

from predictionio_tpu.utils import env as _env
from typing import Any, Callable, Iterator, Optional

from predictionio_tpu.obs import tracing as _tracing

_current_span_id: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "pio_span_id", default=None
)


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def current_span_id() -> Optional[str]:
    return _current_span_id.get()


def set_current_span(span_id: Optional[str]) -> contextvars.Token:
    return _current_span_id.set(span_id)


def reset_current_span(token: contextvars.Token) -> None:
    _current_span_id.reset(token)


@dataclass
class Span:
    """One completed (or in-flight, while inside the `span()` cm) span."""

    trace_id: str
    span_id: str
    name: str
    parent_span_id: Optional[str] = None
    start: float = 0.0  # wall clock, epoch seconds
    duration: float = 0.0  # seconds
    attrs: dict[str, Any] = field(default_factory=dict)
    error: bool = False

    def to_dict(self) -> dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "name": self.name,
            "start": round(self.start, 6),
            "duration_ms": round(self.duration * 1e3, 3),
            "attrs": self.attrs,
            "error": self.error,
        }


def _env_float(name: str, default: float) -> float:
    return _env.env_float(name, default)


class SpanRecorder:
    """Thread-safe span store with tail-based sampling.

    Spans accumulate per trace in `_active`; when a *local root* span
    (one opened with no enclosing span in this process) completes, the
    trace fragment is finalized: kept if any span errored or ran past
    `slow_ms`, else kept with probability `sample_rate`, else dropped.
    Kept traces merge across fragments — a storage daemon's spans and
    the calling server's spans share one trace_id, so in a single-process
    deployment (or test) the fragments reunite into one tree."""

    def __init__(
        self,
        max_traces: Optional[int] = None,
        slow_ms: Optional[float] = None,
        sample_rate: Optional[float] = None,
    ):
        self.max_traces = int(
            max_traces if max_traces is not None
            else _env_float("PIO_TRACE_MAX", 256)
        )
        self.slow_ms = (
            slow_ms if slow_ms is not None
            else _env_float("PIO_TRACE_SLOW_MS", 250.0)
        )
        self.sample_rate = (
            sample_rate if sample_rate is not None
            else _env_float("PIO_TRACE_SAMPLE", 0.1)
        )
        # per-trace span cap: trace ids are client-controlled
        # (X-Request-ID), so one id replayed forever must not grow a
        # retained trace without bound
        self.max_spans_per_trace = 512
        self._lock = threading.Lock()
        # trace_id -> spans completed but not yet sampled-on
        self._active: "OrderedDict[str, list[Span]]" = OrderedDict()  # guarded-by: _lock
        # trace_id -> {"spans": [...], "reason": keep-reason}
        self._traces: "OrderedDict[str, dict]" = OrderedDict()  # guarded-by: _lock
        self._bridges: dict[str, Callable[[Span], None]] = {}  # guarded-by: _lock
        # query-triggered capture (ISSUE 8 satellite): capture_id ->
        # {"requested", "remaining", "trace_ids", ...}; the dispatcher
        # consumes one "batch credit" per device batch and force-keeps
        # that batch's traces regardless of the sample rate
        self._captures: "OrderedDict[str, dict]" = OrderedDict()  # guarded-by: _lock
        self._forced: dict[str, str] = {}  # trace_id -> capture_id  # guarded-by: _lock
        # every completed span, pre-sampling, for the fleet trace
        # collector (ISSUE 16): cross-process stitching needs the raw
        # fragments — a fast replica-side attempt would never survive
        # LOCAL tail sampling, yet it is exactly the child the
        # assembled hedged trace must show. Bounded ring; the
        # collector dedups on span_id across overlapping polls.
        self._recent: deque[Span] = deque(maxlen=4096)  # guarded-by: _lock

    # -- recording ---------------------------------------------------------
    @contextmanager
    def span(
        self,
        name: str,
        trace_id: Optional[str] = None,
        parent_span_id: Optional[str] = None,
        **attrs: Any,
    ) -> Iterator[Span]:
        """Open a span. Yields the (mutable) Span so callers can add
        attributes mid-flight. Establishes trace + span context for
        anything nested; an exception marks the span errored (and
        re-raises). The trace fragment finalizes when a span with no
        *local* parent completes — an explicit `parent_span_id` (a
        remote parent from `X-Parent-Span`) does not suppress that."""
        ambient = _tracing.current_trace_id()
        tid = trace_id or ambient or _tracing.new_request_id()
        # establish trace context for everything nested whenever this
        # span starts (or switches) the trace — an explicit trace_id
        # must flow to children exactly like an inherited one
        trace_token = _tracing.set_trace_id(tid) if tid != ambient else None
        local_parent = _current_span_id.get()
        sp = Span(
            trace_id=tid,
            span_id=new_span_id(),
            name=name,
            parent_span_id=(
                parent_span_id if parent_span_id is not None else local_parent
            ),
            start=time.time(),
            attrs=dict(attrs),
        )
        span_token = _current_span_id.set(sp.span_id)
        t0 = time.perf_counter()
        try:
            yield sp
        except BaseException:
            sp.error = True
            raise
        finally:
            sp.duration = time.perf_counter() - t0
            _current_span_id.reset(span_token)
            if trace_token is not None:
                _tracing.reset_trace_id(trace_token)
            self.record(sp, finalize=local_parent is None)

    def record(self, sp: Span, finalize: bool = False) -> None:
        """Record a completed span. `finalize=True` marks the end of this
        process's fragment of the trace: the tail-sampling decision runs
        over everything recorded for the trace so far."""
        bridge = self._bridges.get(sp.name)
        if bridge is not None:
            try:
                bridge(sp)
            except Exception:
                pass  # a metrics hiccup must never break the request
        with self._lock:
            self._recent.append(sp)
            kept = self._traces.get(sp.trace_id)
            if kept is not None:
                # trace already deemed interesting: merge late fragments
                # (e.g. the client span completing after the remote
                # server's fragment finalized) straight in — capped, and
                # WITHOUT refreshing eviction age, so a client pinning
                # one request id can neither grow it unbounded nor keep
                # it alive forever
                if len(kept["spans"]) < self.max_spans_per_trace:
                    kept["spans"].append(sp)
                return
            frag = self._active.setdefault(sp.trace_id, [])
            if len(frag) < self.max_spans_per_trace:
                frag.append(sp)
            if not finalize:
                # orphan guard: fragments whose root never completes
                # (handler crashed pre-response) must not grow unbounded
                while len(self._active) > max(64, 4 * self.max_traces):
                    self._active.popitem(last=False)
                return
            spans = self._active.pop(sp.trace_id)
            forced_cap = self._forced.pop(sp.trace_id, None)
            reason = (
                f"capture:{forced_cap}" if forced_cap
                else self._keep_reason(spans)
            )
            if reason is None:
                if sp.parent_span_id is not None:
                    # the finalizing span has a REMOTE parent: it roots
                    # only this process's fragment, not the trace. When
                    # two servers share a process (query server +
                    # storage daemon in tests / single-box deploys), the
                    # daemon's server span completes MID-request — a
                    # definitive drop here would amputate the outer
                    # request's already-recorded queue/assemble spans
                    # from its eventual slow/error trace. Defer: leave
                    # the fragment active for the true root's finalize
                    # to re-evaluate over the union. (The orphan guard
                    # below bounds fragments whose root never comes.)
                    self._active[sp.trace_id] = spans
                    while len(self._active) > max(64, 4 * self.max_traces):
                        self._active.popitem(last=False)
                return
            self._traces[sp.trace_id] = {"spans": spans, "reason": reason}
            if forced_cap is not None:
                cap = self._captures.get(forced_cap)
                if cap is not None and sp.trace_id not in cap["trace_ids"]:
                    cap["trace_ids"].append(sp.trace_id)
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)

    def _keep_reason(self, spans: list[Span]) -> Optional[str]:
        if any(s.error for s in spans):
            return "error"
        if any(s.duration * 1e3 >= self.slow_ms for s in spans):
            return "slow"
        if random.random() < self.sample_rate:
            return "sampled"
        return None

    # -- metric bridge -----------------------------------------------------
    def bridge(self, span_name: str, observe: Callable[[Span], None]) -> None:
        """Feed every completed span named `span_name` into `observe`
        (typically `lambda sp: histogram.observe(sp.duration)`), so the
        span is the single source for both the trace and the metric.
        One callback per name — last registration wins."""
        with self._lock:
            self._bridges[span_name] = observe

    def unbridge(
        self, span_name: str,
        observe: Optional[Callable[[Span], None]] = None,
    ) -> None:
        """Remove a bridge. With `observe`, removes only if it is still
        the registered callback — a stopped server must not tear down a
        newer server's bridge."""
        # check+pop under the recorder lock: a stopping server racing
        # a newer server's registration must not observe its own bridge
        # and then pop the replacement (ISSUE 12 lock-discipline find)
        with self._lock:
            if observe is None or self._bridges.get(span_name) is observe:
                self._bridges.pop(span_name, None)

    # -- query-triggered capture (ISSUE 8 satellite) -----------------------
    def arm_capture(self, n_batches: int) -> str:
        """Arm force-sampling for the next `n_batches` device batches:
        the dispatcher calls `consume_capture()` per batch and
        `force_keep()`s that batch's trace ids, so they are retained
        with reason ``capture:<id>`` no matter what PIO_TRACE_SAMPLE
        says. Returns the capture id for `?capture=<id>`."""
        capture_id = new_span_id()[:8]
        with self._lock:
            self._captures[capture_id] = {
                "id": capture_id,
                "requested": int(n_batches),
                "remaining": int(n_batches),
                "trace_ids": [],
                "created": time.time(),
            }
            while len(self._captures) > 16:
                dropped_id, dropped = self._captures.popitem(last=False)
                # an evicted armed capture must not leave dangling arms
                self._forced = {
                    tid: cid for tid, cid in self._forced.items()
                    if cid != dropped_id
                }
        return capture_id

    def consume_capture(self) -> Optional[str]:
        """One batch credit off the oldest still-armed capture (None
        when nothing is armed — the inert fast path is one dict check)."""
        if not self._captures:
            return None
        with self._lock:
            for capture_id, cap in self._captures.items():
                if cap["remaining"] > 0:
                    cap["remaining"] -= 1
                    return capture_id
        return None

    def force_keep(self, trace_id: str, capture_id: str) -> None:
        """Mark a trace for unconditional retention under `capture_id`.
        A trace already retained joins the capture immediately."""
        with self._lock:
            cap = self._captures.get(capture_id)
            if cap is None:
                return
            kept = self._traces.get(trace_id)
            if kept is not None:
                if trace_id not in cap["trace_ids"]:
                    cap["trace_ids"].append(trace_id)
                return
            self._forced[trace_id] = capture_id
            # bound the pending map: a capture whose traces never
            # finalize (handler crash) must not grow it forever
            while len(self._forced) > 4 * self.max_spans_per_trace:
                self._forced.pop(next(iter(self._forced)))

    def capture_status(self, capture_id: str) -> Optional[dict]:
        """The `GET /debug/traces?capture=<id>` body: the capture
        record plus summaries of its retained traces."""
        with self._lock:
            cap = self._captures.get(capture_id)
            if cap is None:
                return None
            cap = dict(cap, trace_ids=list(cap["trace_ids"]))
        all_summaries = {
            s["trace_id"]: s for s in self.summaries(limit=0)
        }
        return {
            "capture": cap,
            "done": cap["remaining"] == 0,
            "traces": [
                all_summaries[tid] for tid in cap["trace_ids"]
                if tid in all_summaries
            ],
        }

    # -- reading -----------------------------------------------------------
    def recent(self, since: float = 0.0) -> list[Span]:
        """Raw completed spans (pre-sampling) whose END falls at or
        after `since` — the `/debug/traces?spans=1` dump the fleet
        trace collector polls for cross-process stitching."""
        with self._lock:
            spans = list(self._recent)
        if since <= 0.0:
            return spans
        return [s for s in spans if s.start + s.duration >= since]

    def get_trace(self, trace_id: str) -> list[Span]:
        """Spans of a retained trace, start-ordered ([] if not retained)."""
        with self._lock:
            rec = self._traces.get(trace_id)
            spans = list(rec["spans"]) if rec else []
        return sorted(spans, key=lambda s: s.start)

    def summaries(self, limit: int = 50) -> list[dict]:
        """Newest-first one-line views of the retained traces."""
        with self._lock:
            items = list(self._traces.items())
        out = []
        for tid, rec in reversed(items[-limit:] if limit else items):
            spans = rec["spans"]
            ids = {s.span_id for s in spans}
            roots = [
                s for s in spans
                if s.parent_span_id is None or s.parent_span_id not in ids
            ] or spans
            root = max(roots, key=lambda s: s.duration)
            out.append({
                "trace_id": tid,
                "root": root.name,
                "server": root.attrs.get("server"),
                "path": root.attrs.get("path"),
                "spans": len(spans),
                "duration_ms": round(root.duration * 1e3, 3),
                "error": any(s.error for s in spans),
                "kept": rec["reason"],
                "start": round(min(s.start for s in spans), 3),
            })
        return out

    def perfetto_export(self, trace_id: Optional[str] = None) -> dict:
        """Chrome trace-event JSON (the `traceEvents` array form) for one
        retained trace, or all of them. Loadable in Perfetto / chrome
        ://tracing: spans become complete ("X") events; each originating
        server gets a named process row, span depth maps to the thread
        row so children nest under parents."""
        with self._lock:
            if trace_id is not None:
                rec = self._traces.get(trace_id)
                spans = list(rec["spans"]) if rec else []
            else:
                spans = [
                    s for rec in self._traces.values() for s in rec["spans"]
                ]
        procs: dict[str, int] = {}
        events: list[dict] = []
        by_id = {s.span_id: s for s in spans}

        def depth(s: Span, hops: int = 0) -> int:
            parent = by_id.get(s.parent_span_id or "")
            if parent is None or hops > 32:  # missing/remote parent or cycle
                return 0
            return 1 + depth(parent, hops + 1)

        for s in sorted(spans, key=lambda x: x.start):
            proc = str(s.attrs.get("server") or s.name.split(".")[0])
            pid = procs.setdefault(proc, len(procs) + 1)
            events.append({
                "ph": "X",
                "name": s.name,
                "cat": "pio",
                "ts": round(s.start * 1e6, 3),
                "dur": round(s.duration * 1e6, 3),
                "pid": pid,
                "tid": depth(s),
                "args": {
                    "trace_id": s.trace_id,
                    "span_id": s.span_id,
                    "parent_span_id": s.parent_span_id,
                    "error": s.error,
                    **{k: str(v) for k, v in s.attrs.items()},
                },
            })
        meta = [
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "args": {"name": proc},
            }
            for proc, pid in procs.items()
        ]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def config(self) -> dict:
        return {
            "max_traces": self.max_traces,
            "slow_ms": self.slow_ms,
            "sample_rate": self.sample_rate,
        }

    def clear(self) -> None:
        with self._lock:
            self._active.clear()
            self._traces.clear()
            self._captures.clear()
            self._forced.clear()
            self._recent.clear()


_default_recorder: Optional[SpanRecorder] = None
_default_lock = threading.Lock()


def get_default_recorder() -> SpanRecorder:
    """The process-wide recorder every server and workflow records into
    (lazy so env knobs set before first use are honored)."""
    global _default_recorder
    with _default_lock:
        if _default_recorder is None:
            _default_recorder = SpanRecorder()
        return _default_recorder


def span(name: str, **kwargs: Any):
    """`with span("stage", key=val):` on the default recorder."""
    return get_default_recorder().span(name, **kwargs)
