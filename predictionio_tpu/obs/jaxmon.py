"""JAX runtime gauges: jit compile activity + live device buffers.

Compile events come from jax's monitoring hooks (a process-global
duration listener accumulates `/jax/core/compile/*` events — notably
`backend_compile_duration`, one per XLA compile). Live-buffer gauges are
callback gauges sampled at scrape time via `jax.live_arrays()`, so a
`GET /metrics` shows the device-memory footprint *now*, not at some
earlier sampling tick. Everything degrades to 0 when jax is absent or
its private monitoring API moves — observability must never break
serving."""

from __future__ import annotations

import threading

from predictionio_tpu.obs.registry import MetricsRegistry

_lock = threading.Lock()
_compile_count = 0
_compile_seconds = 0.0
_listener_installed = False


def _on_duration(event: str, duration: float, **_kw) -> None:
    global _compile_count, _compile_seconds
    if "/jax/core/compile" not in event:
        return
    with _lock:
        _compile_seconds += duration
        if event.endswith("backend_compile_duration"):
            _compile_count += 1


def ensure_compile_listener() -> None:
    """Hook jax's monitoring events (idempotent). Importing jax costs
    ~2 s, so ONLY call this from paths that are jax-bound anyway — the
    train workflow and deploy-runtime construction call it before their
    first compile; data-plane processes (event server, storage daemon,
    dashboard) never pay the import and read compile gauges as 0."""
    global _listener_installed
    with _lock:
        if _listener_installed:
            return
        _listener_installed = True
    try:
        from jax._src import monitoring as _monitoring

        _monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        pass  # private API drift: compile gauges stay at 0


def compile_snapshot() -> tuple[int, float]:
    """(compiles seen, seconds spent) so far — train-stage spans diff
    this across a stage to attribute XLA compile time to the stage that
    paid it."""
    with _lock:
        return _compile_count, _compile_seconds


def _rearm_if_jax_loaded() -> None:
    """Late-import gap fix: a process that wires /metrics BEFORE its
    first jax import used to scrape compile gauges stuck at 0 forever
    (install_jax_gauges only armed the listener if jax was already in
    sys.modules). Re-checking at scrape time arms the listener the first
    time a scrape observes jax loaded — compiles before that scrape are
    missed, every one after is counted. Still never IMPORTS jax."""
    import sys

    if "jax" in sys.modules and not _listener_installed:
        ensure_compile_listener()


def _compile_count_now() -> float:
    _rearm_if_jax_loaded()
    with _lock:
        return float(_compile_count)


def _compile_seconds_now() -> float:
    _rearm_if_jax_loaded()
    with _lock:
        return _compile_seconds


def _live_arrays() -> list:
    import sys

    if "jax" not in sys.modules:
        # data-plane processes (event server, storage daemon, dashboard)
        # must not pay the multi-second jax import on their first scrape;
        # no jax loaded ⇒ no live buffers, truthfully
        return []
    try:
        import jax

        return list(jax.live_arrays())
    except Exception:
        return []


def install_jax_gauges(registry: MetricsRegistry) -> None:
    """Register the JAX runtime gauges on `registry` (idempotent)."""
    import sys

    if "jax" in sys.modules:  # hook compiles, but never IMPORT jax here
        ensure_compile_listener()
    registry.gauge_callback(
        "jax_jit_compile_count",
        "XLA backend compiles observed in this process",
        _compile_count_now,
    )
    registry.gauge_callback(
        "jax_jit_compile_seconds_total",
        "seconds spent in jax trace/lower/compile in this process",
        _compile_seconds_now,
    )
    registry.gauge_callback(
        "jax_live_buffer_count",
        "live jax arrays (sampled at scrape)",
        lambda: float(len(_live_arrays())),
    )
    registry.gauge_callback(
        "jax_live_buffer_bytes",
        "bytes held by live jax arrays (sampled at scrape)",
        lambda: float(
            sum(getattr(a, "nbytes", 0) or 0 for a in _live_arrays())
        ),
    )
