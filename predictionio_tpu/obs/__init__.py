"""Unified observability: metrics registry, Prometheus exposition,
request tracing, hierarchical span tracing, device profiling. See
registry.py, spans.py and devprof.py for the design rationale."""

from predictionio_tpu.obs.devprof import install_devprof_gauges
from predictionio_tpu.obs.jaxmon import install_jax_gauges
from predictionio_tpu.obs.registry import (
    BATCH_SIZE_BUCKETS,
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
    get_default_registry,
    render_merged,
)
from predictionio_tpu.obs.spans import (
    Span,
    SpanRecorder,
    current_span_id,
    get_default_recorder,
    span,
)
from predictionio_tpu.obs.tracing import (
    current_trace_id,
    log_access,
    new_request_id,
    trace_context,
)

__all__ = [
    "BATCH_SIZE_BUCKETS",
    "DEFAULT_LATENCY_BUCKETS",
    "MetricsRegistry",
    "Span",
    "SpanRecorder",
    "current_span_id",
    "current_trace_id",
    "get_default_recorder",
    "get_default_registry",
    "install_devprof_gauges",
    "install_jax_gauges",
    "log_access",
    "new_request_id",
    "render_merged",
    "server_registry",
    "span",
    "trace_context",
]


def server_registry() -> MetricsRegistry:
    """A fresh per-server registry with the JAX runtime and device-profile
    gauges mounted — what every server process binds to its
    `GET /metrics`."""
    reg = MetricsRegistry()
    install_jax_gauges(reg)
    install_devprof_gauges(reg)
    return reg
