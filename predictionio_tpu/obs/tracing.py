"""Request tracing: X-Request-ID propagation + structured JSON access logs.

A request's trace id is either taken from its `X-Request-ID` header or
generated at parse time, stored in a ContextVar for the duration of the
handler (each connection runs on its own thread, so the var is
effectively request-scoped), echoed back in the response headers, and
stamped onto the structured access-log record. Anything that logs while
handling the request — including `RemoteLogHandler` shipping records to
a collector — can pick the id up via `current_trace_id()` and correlate
across processes."""

from __future__ import annotations

import contextvars
import json
import logging
import time
import uuid
from contextlib import contextmanager
from typing import Iterator, Optional

_trace_id: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "pio_trace_id", default=None
)

# one logger for all servers' access lines; records are single JSON
# objects so a collector ingests them without a parse grammar
access_log = logging.getLogger("predictionio_tpu.access")


def new_request_id() -> str:
    return uuid.uuid4().hex


def current_trace_id() -> Optional[str]:
    return _trace_id.get()


def set_trace_id(trace_id: Optional[str]) -> contextvars.Token:
    return _trace_id.set(trace_id)


def reset_trace_id(token: contextvars.Token) -> None:
    _trace_id.reset(token)


@contextmanager
def trace_context(trace_id: Optional[str] = None) -> Iterator[str]:
    """Scope a trace id over a block (non-HTTP entry points: CLI, tests)."""
    tid = trace_id or new_request_id()
    token = set_trace_id(tid)
    try:
        yield tid
    finally:
        reset_trace_id(token)


def log_access(
    server: str,
    method: str,
    path: str,
    status: int,
    duration_s: float,
    trace_id: Optional[str] = None,
) -> None:
    """Emit one structured access-log record for a completed request."""
    if not access_log.isEnabledFor(logging.INFO):
        return
    record = {
        "ts": round(time.time(), 3),
        "server": server,
        "method": method,
        "path": path,
        "status": status,
        "duration_ms": round(duration_s * 1e3, 3),
        "trace_id": trace_id or current_trace_id(),
    }
    access_log.info(json.dumps(record, separators=(",", ":")))
