"""In-process time-series history: a fixed-capacity ring-buffer TSDB.

PR 1-3 made every server scrapeable (`/metrics`, `/debug/traces`,
`/debug/profile`) but all of it is point-in-time: nobody can answer
"when did p99 start degrading" without an external Prometheus, which
the reference deployment story never assumes. This module keeps a
bounded window of history IN the process:

- :class:`TSDB` — thread-safe map of (name, sorted label pairs) →
  ring buffer of ``(epoch_seconds, value)`` points (a deque; O(1)
  append, oldest point falls off at capacity). Series cardinality is
  bounded by ``max_series`` — the same guard discipline as the metric
  route labels: past the cap, NEW series are dropped and counted
  (`dropped_series`) instead of growing without bound.
- :class:`MetricsSampler` — a background thread that snapshots metric
  families every ``interval_s``: counters and gauges land as their
  cumulative/current values; histograms land as `_count`/`_sum`,
  per-bucket cumulative `_bucket{le=}` series (the SLO engine's
  latency math needs the exact bucket counters), and point-in-time
  p50/p95/p99 gauges under a ``quantile`` label (the sparkline/CLI
  view). Counter RATES are derived at query time, not sample time —
  `rate()`/`increase()` walk the ring counter-reset-aware, so a
  restarted server's counters don't produce negative spikes.

The query API is deliberately tiny (range / rate / increase /
quantile_over_time / latest); `GET /debug/tsdb` is a direct window
onto it. Everything here is stdlib-only — the monitor plane must be
importable by data-plane processes that never pay the jax import.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional

from predictionio_tpu.obs.registry import (
    CounterFamily,
    GaugeFamily,
    HistogramFamily,
    MetricFamily,
)
from predictionio_tpu.utils.env import env_str

LabelPairs = tuple[tuple[str, str], ...]


def _label_key(labels: Optional[dict]) -> LabelPairs:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Series:
    """One named+labeled series' ring of (t, value) points."""

    __slots__ = ("name", "labels", "kind", "points")

    def __init__(self, name: str, labels: LabelPairs, kind: str,
                 capacity: int):
        self.name = name
        self.labels = labels
        self.kind = kind
        self.points: deque[tuple[float, float]] = deque(maxlen=capacity)

    def labels_dict(self) -> dict[str, str]:
        return dict(self.labels)


def increase_of(points: Iterable[tuple[float, float]]) -> float:
    """Counter increase across `points`, reset-aware: a drop between
    consecutive samples means the process restarted and the counter
    began again from zero, so the post-reset value IS the delta (the
    standard Prometheus semantic). Gauge series shouldn't come here."""
    total = 0.0
    prev: Optional[float] = None
    for _t, v in points:
        if prev is not None:
            total += (v - prev) if v >= prev else v
        prev = v
    return total


def quantile_of(values: list[float], q: float) -> Optional[float]:
    if not values:
        return None
    vs = sorted(values)
    if len(vs) == 1:
        return vs[0]
    pos = min(max(q, 0.0), 1.0) * (len(vs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(vs) - 1)
    frac = pos - lo
    return vs[lo] + (vs[hi] - vs[lo]) * frac


class TSDB:
    """Thread-safe fixed-capacity ring-buffer time-series store."""

    def __init__(self, capacity: int = 720, max_series: int = 4096):
        self.capacity = max(2, int(capacity))
        self.max_series = max(1, int(max_series))
        self._lock = threading.Lock()
        self._series: "OrderedDict[tuple[str, LabelPairs], Series]" = (  # guarded-by: _lock
            OrderedDict()
        )
        self.dropped_series = 0  # adds refused at the cardinality cap  # guarded-by: _lock

    # -- writing -----------------------------------------------------------
    def add(self, name: str, labels: Optional[dict], value: float,
            kind: str = "gauge", t: Optional[float] = None) -> bool:
        """Append one point; returns False when the series would exceed
        the cardinality cap (dropped + counted, never raises).

        Points are kept in TIME order even when they arrive out of
        order — a snapshot restored after live sampling already began,
        or a pushed spool payload backfilling a dead worker's history.
        `increase()`/`rate()` walk the ring in sequence assuming
        monotone timestamps; an interleaved restore used to read a
        counter reset where none happened and double-count the window."""
        key = (name, _label_key(labels))
        now = time.time() if t is None else t
        with self._lock:
            series = self._series.get(key)
            if series is None:
                if len(self._series) >= self.max_series:
                    self.dropped_series += 1
                    return False
                series = self._series[key] = Series(
                    name, key[1], kind, self.capacity
                )
            pts = series.points
            if pts and now < pts[-1][0]:
                # out-of-order arrival (rare): rebuild with the point in
                # its time slot; the deque maxlen still drops oldest
                ordered = list(pts)
                idx = len(ordered)
                while idx > 0 and ordered[idx - 1][0] > now:
                    idx -= 1
                ordered.insert(idx, (now, float(value)))
                series.points = deque(ordered, maxlen=self.capacity)
            else:
                pts.append((now, float(value)))
        return True

    # -- reading -----------------------------------------------------------
    def _match_locked(self, name: str,
                      match: Optional[dict]) -> list[Series]:
        want = None if match is None else _label_key(match)
        out = []
        for (n, lbls), series in self._series.items():
            if n != name:
                continue
            if want is not None and not set(want) <= set(lbls):
                continue
            out.append(series)
        return out

    def matching(self, name: str,
                 match: Optional[dict] = None) -> list[Series]:
        """Series named `name` whose labels are a superset of `match`."""
        with self._lock:
            return list(self._match_locked(name, match))

    def points(self, series: Series, window_s: Optional[float] = None,
               now: Optional[float] = None) -> list[tuple[float, float]]:
        now = time.time() if now is None else now
        with self._lock:
            pts = list(series.points)
        if window_s is None:
            return pts
        cutoff = now - window_s
        return [(t, v) for t, v in pts if t >= cutoff]

    def range(self, name: str, match: Optional[dict] = None,
              window_s: Optional[float] = None,
              now: Optional[float] = None) -> list[dict[str, Any]]:
        """The `GET /debug/tsdb?name=` payload: every matching series
        with its in-window points."""
        return [
            {
                "name": s.name,
                "labels": s.labels_dict(),
                "kind": s.kind,
                "points": [
                    [round(t, 3), v]
                    for t, v in self.points(s, window_s, now)
                ],
            }
            for s in self.matching(name, match)
        ]

    def series_increase(self, series: Series,
                        window_s: Optional[float] = None,
                        now: Optional[float] = None) -> float:
        """Counter-reset-aware increase of ONE series over the window.
        The last sample BEFORE the window is the baseline: the counter's
        value at the window edge is unobservable between ticks, and
        without the baseline a window holding a single sample would
        always read as zero increase (sparse-sample window-edge bug)."""
        now = time.time() if now is None else now
        with self._lock:
            pts = list(series.points)
        if window_s is None:
            return increase_of(pts)
        cutoff = now - window_s
        idx = 0
        for idx, (t, _v) in enumerate(pts):
            if t >= cutoff:
                break
        else:
            return 0.0  # nothing in-window: no observable activity
        windowed = pts[idx:]
        if idx > 0:
            windowed = [pts[idx - 1]] + windowed
        return increase_of(windowed)

    def increase(self, name: str, match: Optional[dict] = None,
                 window_s: Optional[float] = None,
                 now: Optional[float] = None) -> float:
        """Counter-reset-aware increase summed over matching series."""
        return sum(
            self.series_increase(s, window_s, now)
            for s in self.matching(name, match)
        )

    def rate(self, name: str, match: Optional[dict] = None,
             window_s: float = 300.0,
             now: Optional[float] = None) -> float:
        """Per-second rate over the window (increase / window)."""
        if window_s <= 0:
            return 0.0
        return self.increase(name, match, window_s, now) / window_s

    def quantile_over_time(self, name: str, q: float,
                           match: Optional[dict] = None,
                           window_s: Optional[float] = None,
                           now: Optional[float] = None) -> Optional[float]:
        """Quantile of the sampled VALUES across the window (gauge
        series — e.g. 'what was the p99-of-p99 over the last hour')."""
        values: list[float] = []
        for s in self.matching(name, match):
            values.extend(v for _t, v in self.points(s, window_s, now))
        return quantile_of(values, q)

    def latest(self, name: str, match: Optional[dict] = None
               ) -> Optional[float]:
        pt = self.latest_point(name, match)
        return None if pt is None else pt[1]

    def latest_point(self, name: str, match: Optional[dict] = None
                     ) -> Optional[tuple[float, float]]:
        """Newest (t, value) across matching series — readers that need
        FRESHNESS (the SLO engine's recorded-ratio fast path) check the
        timestamp, not just the value."""
        best: Optional[tuple[float, float]] = None
        for s in self.matching(name, match):
            with self._lock:
                pt = s.points[-1] if s.points else None
            if pt is not None and (best is None or pt[0] > best[0]):
                best = pt
        return best

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def summary(self, limit: int = 0) -> dict[str, Any]:
        """The parameterless `GET /debug/tsdb` payload: one line per
        series, no points (those come per-name)."""
        with self._lock:
            rows = [
                {
                    "name": s.name,
                    "labels": s.labels_dict(),
                    "kind": s.kind,
                    "points": len(s.points),
                    "last": s.points[-1][1] if s.points else None,
                    "last_t": (
                        round(s.points[-1][0], 3) if s.points else None
                    ),
                }
                for s in self._series.values()
            ]
        rows.sort(key=lambda r: (r["name"], sorted(r["labels"].items())))
        if limit:
            rows = rows[:limit]
        return {
            "series": rows,
            "series_count": self.series_count(),
            "capacity": self.capacity,
            "max_series": self.max_series,
            "dropped_series": self.dropped_series,
        }

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self.dropped_series = 0


# -- recording rules (ISSUE 16) ----------------------------------------------
#
# Declarative DERIVED series, evaluated once per sampler tick and
# stored as first-class points: a rate, an error ratio, or a bucket
# quantile computed from the raw counter/histogram rings. Consumers
# (the SLO engine, dashboard sparklines, `pio monitor`) then read one
# precomputed point instead of rescanning hundreds of raw bucket
# points per pass. Rules parse from PIO_RECORDING_RULES (a JSON array
# or ``@/path.json``) — per-SLO ratio rules are auto-derived on top by
# the Monitor (see slo.record_slo_ratios).

RULE_KINDS = ("rate", "error_ratio", "quantile", "expr")


def bucket_quantile(tsdb: TSDB, name: str, q: float,
                    match: Optional[dict] = None,
                    window_s: Optional[float] = None,
                    now: Optional[float] = None) -> Optional[float]:
    """histogram_quantile over raw cumulative ``<name>_bucket`` rings:
    per-le increase across the window, then linear interpolation inside
    the target bucket (None on zero traffic)."""
    inc_by_le: dict[float, float] = {}
    for s in tsdb.matching(name + "_bucket", match):
        le_s = s.labels_dict().get("le", "")
        try:
            le = float("inf") if le_s == "+Inf" else float(le_s)
        except ValueError:
            continue
        inc_by_le[le] = (
            inc_by_le.get(le, 0.0)
            + tsdb.series_increase(s, window_s, now)
        )
    if not inc_by_le:
        return None
    edges = sorted(inc_by_le)
    total = inc_by_le.get(float("inf"), max(inc_by_le.values()))
    if total <= 0:
        return None
    target = min(max(q, 0.0), 1.0) * total
    prev_edge = 0.0
    prev_cum = 0.0
    for le in edges:
        cum = inc_by_le[le]
        if cum >= target:
            if le == float("inf"):
                # fell past the finite edges: the highest finite edge
                # is the best bounded estimate (same as the registry)
                finite = [e for e in edges if e != float("inf")]
                return finite[-1] if finite else None
            n = cum - prev_cum
            frac = (target - prev_cum) / n if n > 0 else 0.0
            return prev_edge + (le - prev_edge) * frac
        prev_edge = 0.0 if le == float("inf") else le
        prev_cum = cum
    finite = [e for e in edges if e != float("inf")]
    return finite[-1] if finite else None


@dataclass(frozen=True)
class RecordingRule:
    """One derived-series rule.

    record    output series name (stored as a gauge)
    kind      "rate" | "error_ratio" | "quantile" | "expr"
    source    raw family name (base name — no _bucket/_total suffix
              stripping is attempted; pass the counter name for rate/
              error_ratio and the histogram base name for quantile;
              unused by expr rules)
    expr      expr rules: a series-algebra expression (obs.monitor.expr)
              — may evaluate to a VECTOR, writing one point per label
              set with the expression's labels merged under `labels`
    match     label matcher on the source series
    labels    labels stamped on the derived series
    window_s  evaluation window (default 300)
    q         quantile rules: the quantile (default 0.99)
    bad_label error_ratio rules: which label marks badness
    bad_min   numeric threshold: bad when int(label) >= bad_min
    bad_values exact-match alternative to bad_min
    """

    record: str
    kind: str
    source: str = ""
    expr: str = ""
    match: tuple = ()
    labels: tuple = ()
    window_s: float = 300.0
    q: float = 0.99
    bad_label: str = "status"
    bad_min: Optional[float] = 500.0
    bad_values: tuple = ()

    def __post_init__(self):
        if self.kind not in RULE_KINDS:
            raise ValueError(
                f"rule {self.record!r}: unknown kind {self.kind!r} "
                f"(known: {', '.join(RULE_KINDS)})"
            )
        if self.kind == "expr":
            if not self.record or not self.expr:
                raise ValueError(
                    "expr recording rule needs 'record' and 'expr'"
                )
            # parse eagerly: a typo fails at load time (logged by
            # load_recording_rules), not silently every sampler tick
            from predictionio_tpu.obs.monitor.expr import parse

            parse(self.expr)
        elif not self.record or not self.source:
            raise ValueError("recording rule needs 'record' and 'source'")
        if self.window_s <= 0:
            raise ValueError(f"rule {self.record!r}: window_s must be > 0")

    @classmethod
    def from_dict(cls, d: dict) -> "RecordingRule":
        known = {
            k: d[k] for k in (
                "record", "kind", "source", "expr", "match", "labels",
                "window_s", "q", "bad_label", "bad_min", "bad_values",
            ) if k in d
        }
        unknown = set(d) - set(known)
        if unknown:
            raise ValueError(
                "recording rule has unknown field(s): "
                + ", ".join(sorted(unknown))
            )
        for key in ("match", "labels"):
            if key in known and isinstance(known[key], dict):
                known[key] = tuple(sorted(
                    (str(k), str(v)) for k, v in known[key].items()
                ))
        if "bad_values" in known:
            known["bad_values"] = tuple(
                str(v) for v in known["bad_values"]
            )
        return cls(**known)

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "record": self.record, "kind": self.kind,
            "source": self.source, "window_s": self.window_s,
            "match": dict(self.match), "labels": dict(self.labels),
        }
        if self.kind == "expr":
            out["expr"] = self.expr
            out.pop("source")
            out.pop("match")
        if self.kind == "quantile":
            out["q"] = self.q
        if self.kind == "error_ratio":
            out["bad_label"] = self.bad_label
            if self.bad_values:
                out["bad_values"] = list(self.bad_values)
            else:
                out["bad_min"] = self.bad_min
        return out

    def evaluate(self, tsdb: TSDB,
                 now: Optional[float] = None) -> Optional[float]:
        """Compute this rule's current value (None on no traffic —
        nothing is written for an empty window, so readers can tell
        'quiet' from 'zero')."""
        now = time.time() if now is None else now
        if self.kind == "expr":
            rows = self.evaluate_vector(tsdb, now)
            return rows[0][1] if len(rows) == 1 else None
        match = dict(self.match) or None
        if self.kind == "rate":
            if not tsdb.matching(self.source, match):
                return None
            return tsdb.rate(self.source, match, self.window_s, now)
        if self.kind == "quantile":
            return bucket_quantile(
                tsdb, self.source, self.q, match, self.window_s, now
            )
        # error_ratio
        total = bad = 0.0
        for s in tsdb.matching(self.source, match):
            inc = tsdb.series_increase(s, self.window_s, now)
            total += inc
            lbl = s.labels_dict().get(self.bad_label, "")
            if self.bad_values:
                is_bad = lbl in self.bad_values
            else:
                try:
                    is_bad = float(int(lbl)) >= float(self.bad_min or 0.0)
                except (TypeError, ValueError):
                    is_bad = False
            if is_bad:
                bad += inc
        if total <= 0:
            return None
        return bad / total

    def evaluate_vector(
        self, tsdb: TSDB, now: Optional[float] = None
    ) -> list[tuple[dict, float]]:
        """Evaluate to [(labels, value), ...] — expr rules may produce a
        whole vector (one point per label set, e.g. `sum by (instance)`);
        the fixed kinds produce at most one sample under the rule's
        static labels. Empty list on no traffic."""
        now = time.time() if now is None else now
        if self.kind != "expr":
            value = self.evaluate(tsdb, now)
            if value is None:
                return []
            return [(dict(self.labels), value)]
        from predictionio_tpu.obs.monitor import expr as _expr

        val = _expr.evaluate(tsdb, self.expr, now,
                             default_window_s=self.window_s)
        if val is None:
            return []
        if isinstance(val, float):
            return [(dict(self.labels), val)]
        return [
            # rule labels win on collision: the operator's stamp is the
            # contract consumers match on
            ({**dict(labels), **dict(self.labels)}, v)
            for labels, v in val
        ]


def load_recording_rules(
    text: Optional[str] = None,
) -> list[RecordingRule]:
    """Parse ``PIO_RECORDING_RULES`` (or an explicit string): a JSON
    array of rule objects, or ``@/path.json``. Malformed input logs
    and yields [] — same grammar discipline as PIO_SLOS."""
    import json as _json
    import logging as _logging

    raw = text if text is not None else env_str("PIO_RECORDING_RULES")
    raw = (raw or "").strip()
    if not raw:
        return []
    try:
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        data = _json.loads(raw)
        if isinstance(data, dict):
            data = [data]
        return [RecordingRule.from_dict(d) for d in data]
    except (OSError, ValueError, TypeError) as e:
        _logging.getLogger(__name__).warning(
            "ignoring malformed PIO_RECORDING_RULES (%s)", e
        )
        return []


def evaluate_rules(tsdb: TSDB, rules: Iterable[RecordingRule],
                   now: Optional[float] = None) -> int:
    """One recording pass: evaluate every rule, store the results as
    first-class gauge points. Returns points written."""
    now = time.time() if now is None else now
    written = 0
    for rule in rules:
        try:
            rows = rule.evaluate_vector(tsdb, now)
        except Exception:
            import logging as _logging

            _logging.getLogger(__name__).debug(
                "recording rule %s failed", rule.record, exc_info=True,
            )
            continue
        for labels, value in rows:
            if tsdb.add(rule.record, labels, value, "gauge", now):
                written += 1
    return written


# -- snapshot persistence (ISSUE 15 satellite) -------------------------------
#
# The rings are process memory: a monitor (or gateway) restart used to
# forget every up{instance} / burn-rate point it ever saw — the SLO
# engine's slow window went blind for an hour and the gateway's health
# history reset to zero exactly when an operator most needs it. Like
# the event WAL, the fix is a bounded on-disk image: periodically
# serialize the rings (atomic tmp+rename, size-capped by dropping the
# OLDEST points per series first), reload on start, and tolerate a
# corrupt/truncated file by starting empty — history is an
# observability aid, never worth refusing to boot over.

SNAPSHOT_VERSION = 1


def save_snapshot(tsdb: TSDB, path: str,
                  max_bytes: int = 8 * 1024 * 1024) -> int:
    """Write the TSDB's rings to `path` (atomic replace). Returns the
    bytes written. The file is bounded: per-series points shrink
    (newest kept) until the serialized image fits `max_bytes`."""
    import json
    import os

    with tsdb._lock:
        rows = [
            {
                "name": s.name,
                "labels": s.labels_dict(),
                "kind": s.kind,
                "points": [[round(t, 3), v] for t, v in s.points],
            }
            for s in tsdb._series.values()
        ]
    cap = max((len(r["points"]) for r in rows), default=0)
    while True:
        data = json.dumps({
            "v": SNAPSHOT_VERSION,
            "saved_at": time.time(),
            "capacity": tsdb.capacity,
            "series": rows,
        }, separators=(",", ":")).encode()
        if len(data) <= max_bytes or cap <= 2:
            break
        cap = max(2, cap // 2)
        rows = [
            dict(r, points=r["points"][-cap:]) for r in rows
        ]
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return len(data)


def load_snapshot(tsdb: TSDB, path: str) -> int:
    """Reload a snapshot into `tsdb`; returns series restored. A
    missing, corrupt, or future-versioned file loads nothing (warned,
    never raised) — a bad snapshot must not take the process down."""
    import json
    import logging

    log = logging.getLogger(__name__)
    try:
        with open(path, "rb") as f:
            payload = json.loads(f.read())
        if payload.get("v") != SNAPSHOT_VERSION:
            log.warning(
                "ignoring TSDB snapshot %s: unknown version %r",
                path, payload.get("v"),
            )
            return 0
        loaded = 0
        for row in payload["series"]:
            name, labels = row["name"], row["labels"]
            kind = row.get("kind", "gauge")
            ok = True
            for t, v in row["points"]:
                ok = tsdb.add(name, labels, float(v), kind, float(t))
                if not ok:
                    break  # cardinality cap: counted by add()
            if ok:
                loaded += 1
        return loaded
    except FileNotFoundError:
        return 0
    except Exception:
        log.warning(
            "ignoring corrupt TSDB snapshot %s (starting with empty "
            "history)", path, exc_info=True,
        )
        return 0


class SnapshotWriter:
    """Background thread persisting the rings every `interval_s`; a
    final snapshot lands on stop() (which joins — the no-leaked-threads
    contract every monitor thread follows)."""

    thread_name = "tsdb-snapshot"

    def __init__(self, tsdb: TSDB, path: str, interval_s: float = 60.0,
                 max_bytes: int = 8 * 1024 * 1024):
        self.tsdb = tsdb
        self.path = path
        self.interval_s = max(0.05, float(interval_s))
        self.max_bytes = int(max_bytes)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def write_once(self) -> int:
        try:
            return save_snapshot(self.tsdb, self.path, self.max_bytes)
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "TSDB snapshot write failed; history continues "
                "in-memory", exc_info=True,
            )
            return 0

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=self.thread_name, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
            self.write_once()  # final image so a clean stop loses nothing

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.write_once()


# -- the in-process sampler --------------------------------------------------

#: quantiles materialized per histogram child at each sample tick
SAMPLED_QUANTILES: tuple[tuple[float, str], ...] = (
    (0.5, "p50"), (0.95, "p95"), (0.99, "p99"),
)


def sample_families(tsdb: TSDB, families: Iterable[MetricFamily],
                    extra_labels: Optional[dict] = None,
                    now: Optional[float] = None) -> int:
    """Snapshot metric families into the TSDB; returns points written.
    Shared by the in-process sampler (extra_labels None) and anything
    that wants to stamp a whole registry at once (tests, bench).

    Duplicate (name, labels) series within one pass write ONCE (first
    family wins): several servers in a process each mount the same
    unlabeled jax/devprof gauges over one global source, and letting
    each write per tick would interleave near-duplicate points."""
    now = time.time() if now is None else now
    extra = dict(extra_labels or {})
    written = 0
    seen: set[tuple[str, LabelPairs]] = set()

    def put(name: str, labels: dict, value: float, kind: str) -> None:
        nonlocal written
        merged = {**labels, **extra}
        key = (name, _label_key(merged))
        if key in seen:
            return
        seen.add(key)
        if tsdb.add(name, merged, value, kind, now):
            written += 1

    for fam in families:
        if isinstance(fam, HistogramFamily):
            with fam._lock:
                items = [
                    (dict(zip(fam.labelnames, lv)),
                     list(c.bucket_counts), c.sum, c.count)
                    for lv, c in fam._children.items()
                ]
            for labels, bucket_counts, total_sum, count in items:
                put(fam.name + "_count", labels, count, "counter")
                put(fam.name + "_sum", labels, total_sum, "counter")
                cum = 0
                for edge, n in zip(fam.buckets, bucket_counts):
                    cum += n
                    put(
                        fam.name + "_bucket",
                        {**labels, "le": repr(float(edge))},
                        cum, "counter",
                    )
                put(
                    fam.name + "_bucket",
                    {**labels, "le": "+Inf"}, count, "counter",
                )
                for q, qname in SAMPLED_QUANTILES:
                    put(
                        fam.name,
                        {**labels, "quantile": qname},
                        fam.quantile(q, **labels), "gauge",
                    )
        elif isinstance(fam, GaugeFamily):
            if fam.callback is not None:
                put(fam.name, {}, fam.value(), "gauge")
                continue
            with fam._lock:
                items = [
                    (dict(zip(fam.labelnames, lv)), c.value)
                    for lv, c in fam._children.items()
                ]
            for labels, value in items:
                put(fam.name, labels, value, "gauge")
        elif isinstance(fam, CounterFamily):
            with fam._lock:
                items = [
                    (dict(zip(fam.labelnames, lv)), c.value)
                    for lv, c in fam._children.items()
                ]
            for labels, value in items:
                put(fam.name, labels, value, "counter")
    return written


class MetricsSampler:
    """Background thread snapshotting `provider()`'s metric families
    into the TSDB every `interval_s`. `stop()` joins the thread — the
    no-leaked-threads contract every monitor thread follows."""

    thread_name = "tsdb-sampler"

    def __init__(self, tsdb: TSDB,
                 provider: Callable[[], list[MetricFamily]],
                 interval_s: float = 5.0,
                 post_sample: Optional[Callable[[TSDB, float], None]] = None):
        self.tsdb = tsdb
        self.provider = provider
        self.interval_s = max(0.05, float(interval_s))
        # runs on the sampler thread after each snapshot — recording
        # rules piggyback here so derived series share the raw series'
        # tick timestamps and no extra thread joins the leak budget
        self.post_sample = post_sample
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample_once(self, now: Optional[float] = None) -> int:
        try:
            families = self.provider()
        except Exception:
            return 0
        now = time.time() if now is None else now
        written = sample_families(self.tsdb, families, now=now)
        if self.post_sample is not None:
            try:
                self.post_sample(self.tsdb, now)
            except Exception:
                pass  # derived series must never take down raw sampling
        return written

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=self.thread_name, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        # sample immediately so short-lived processes still get history
        while True:
            try:
                self.sample_once()
            except Exception:
                pass  # a sampling hiccup must never kill the thread
            if self._stop.wait(self.interval_s):
                return
