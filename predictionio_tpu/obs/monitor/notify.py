"""Alert notification sinks (ISSUE 9 satellite, ROADMAP monitoring
follow-up): alerts were pull-only (`GET /alerts`), which is useless for
a drift-pause at 3am. On a pending→firing transition (and on resolve)
the notifier pushes the alert out through two optional sinks:

  PIO_ALERT_WEBHOOK   POST the alert JSON to this URL
  PIO_ALERT_EXEC      run this command; the alert JSON arrives on stdin
                      AND in $PIO_ALERT_JSON (shell-free argv split)

Delivery is best-effort and off the evaluation path: each notification
runs on a short-lived daemon thread, bounded by a semaphore so a hung
webhook cannot pile threads up behind it, and outcomes land in
`alert_notifications_total{sink,outcome}`.
"""

from __future__ import annotations

import json
import logging
import os
import shlex
import threading
from typing import Any, Optional

from predictionio_tpu.analysis import tsan as _tsan
from predictionio_tpu.utils.env import env_str

log = logging.getLogger(__name__)

MAX_INFLIGHT = 4
TIMEOUT_S = 10.0


class AlertNotifier:
    def __init__(
        self,
        webhook_url: Optional[str] = None,
        exec_cmd: Optional[str] = None,
        registry=None,
    ):
        self.webhook_url = webhook_url
        self.exec_cmd = exec_cmd
        self._inflight = threading.Semaphore(MAX_INFLIGHT)
        # in-flight delivery threads, so close() can JOIN them (ISSUE 12
        # thread-lifecycle: the old fire-and-forget spawn could outlive
        # the SLO engine that pushed the alert)
        self._threads_lock = threading.Lock()
        self._threads: set[threading.Thread] = set()  # guarded-by: _threads_lock
        if registry is None:
            from predictionio_tpu.obs.registry import get_default_registry

            registry = get_default_registry()
        self._counter = registry.counter(
            "alert_notifications_total",
            "alert notifications pushed, by sink and outcome",
            ("sink", "outcome"),  # label-bound: literal sink/outcome sets
        )

    @staticmethod
    def from_env(env: Optional[dict] = None) -> "AlertNotifier":
        return AlertNotifier(
            webhook_url=env_str("PIO_ALERT_WEBHOOK", env=env).strip() or None,
            exec_cmd=env_str("PIO_ALERT_EXEC", env=env).strip() or None,
        )

    def active(self) -> bool:
        return bool(self.webhook_url or self.exec_cmd)

    # -- dispatch -----------------------------------------------------------
    def notify(self, alert: dict[str, Any]) -> None:
        """Fire-and-forget push of one alert transition. Dropped (and
        counted) when MAX_INFLIGHT notifications are already in flight —
        a wedged sink must not back up the SLO engine."""
        if not self.active():
            return
        if not self._inflight.acquire(blocking=False):
            self._counter.inc(sink="(any)", outcome="dropped_inflight")
            return
        t = threading.Thread(
            target=self._deliver, args=(dict(alert),),
            name="alert-notify", daemon=True,
        )
        with self._threads_lock:
            self._threads.add(t)
        t.start()

    def _deliver(self, alert: dict[str, Any]) -> None:
        try:
            payload = json.dumps(alert, default=str)
            if self.webhook_url:
                self._post(payload)
            if self.exec_cmd:
                self._exec(payload)
        finally:
            self._inflight.release()
            with self._threads_lock:
                self._threads.discard(threading.current_thread())

    def close(self, timeout: float = TIMEOUT_S) -> None:
        """Join in-flight deliveries — the owner (Monitor/SLO engine)
        calls this on stop so no notification thread outlives it."""
        with self._threads_lock:
            pending = list(self._threads)
        for t in pending:
            t.join(timeout=timeout)
        with self._threads_lock:
            self._threads.difference_update(
                t for t in pending if not t.is_alive()
            )

    def _post(self, payload: str) -> None:
        import urllib.request

        # blocking point (ISSUE 15 satellite): webhook delivery is a
        # network wait — a caller's lock held into notify() delivery
        # would serialize alerting behind a wedged sink
        _tsan.note_blocking("alert.sink")
        try:
            req = urllib.request.Request(
                self.webhook_url,
                data=payload.encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=TIMEOUT_S):
                pass
            self._counter.inc(sink="webhook", outcome="ok")
        except Exception as e:
            self._counter.inc(sink="webhook", outcome="error")
            log.warning("alert webhook delivery failed: %s", e)

    def _exec(self, payload: str) -> None:
        import subprocess

        _tsan.note_blocking("alert.sink")
        try:
            argv = shlex.split(self.exec_cmd)
            proc = subprocess.run(
                argv,
                input=payload.encode(),
                env=dict(os.environ, PIO_ALERT_JSON=payload),
                timeout=TIMEOUT_S,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                check=False,
            )
            if proc.returncode == 0:
                self._counter.inc(sink="exec", outcome="ok")
            else:
                # a pager script exiting nonzero means the page did NOT
                # go out — the delivery metric must say so
                self._counter.inc(sink="exec", outcome="error")
                log.warning(
                    "alert exec sink exited %d", proc.returncode
                )
        except Exception as e:
            self._counter.inc(sink="exec", outcome="error")
            log.warning("alert exec sink failed: %s", e)
