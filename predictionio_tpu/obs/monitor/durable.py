"""Durable long-horizon tier under the in-memory TSDB ring (ISSUE 18).

The ring (tsdb.py) is the speed layer: ~12 minutes of history at a 1s
tick, gone on restart. This module is the batch record underneath it —
the same Lambda split PAPER.md applies to events, and the same
WAL→sealed-segment lifecycle segmentfs (PR 13) gives the event store,
re-applied to telemetry points:

- every accepted ``add()`` also lands in an fsync'd write-ahead log
  (JSON lines, one segment file per seal window, batched fsync on the
  flusher tick — ``tsdb-wal`` thread);
- a full-enough / old-enough segment seals into an immutable columnar
  block: per-series delta-of-delta varint timestamps (millisecond
  resolution) + float64 value columns, with a JSON footer index keyed
  by (name, sorted label pairs) so a query touches only its series'
  byte range. Blocks are written tmp→fsync→rename and never modified;
- the compactor (compact.py) rolls raw blocks into 5m and 1h
  downsampled tiers — per bucket: count/sum/min/max/first/last plus a
  reset-aware in-bucket counter increase (``inc``) so ``increase()``
  and ``rate()`` stay EXACT over full buckets — and enforces per-tier
  retention (PIO_TSDB_RETENTION_{RAW,5M,1H});
- queries stitch transparently: the window's disk prefix (points older
  than the ring's floor) comes from the coarsest tier that can answer
  at adequate resolution, joined reset-aware onto the memory suffix,
  so `/debug/tsdb`, the expr evaluator, and the SLO engine's 6h/3d
  burn windows all see week-scale history without knowing tiers exist;
- on construction the durable tail (WAL segments + newest raw blocks)
  REPLAYS into the ring, so a kill -9'd monitor restarts with its
  pre-restart history and counters continue across the boundary
  without a phantom reset (the PR 17 time-ordered-insert fix is what
  makes the interleaved replay safe).

Downsampled-tier error bound (documented contract): ``increase`` over
a window is exact except at the two edge buckets, where a partial
bucket contributes its whole in-bucket increase — at most one
``bucket_s`` of slop per edge. ``quantile_over_time`` answers from one
representative value per bucket (``last``), so its error is bounded by
the in-bucket value range [min, max]. Raw-tier answers carry no bound.

Stdlib-only, like everything under obs/monitor — data-plane processes
import this without paying for jax.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
import time
from typing import Any, Iterable, Optional

from predictionio_tpu.obs.monitor.tsdb import (
    LabelPairs,
    Series,
    TSDB,
    _label_key,
    increase_of,
)

log = logging.getLogger(__name__)

#: tier name → bucket seconds (0 = raw resolution)
TIER_BUCKETS: dict[str, float] = {"raw": 0.0, "5m": 300.0, "1h": 3600.0}
#: coarse→fine stitch preference
TIER_ORDER: tuple[str, ...] = ("1h", "5m", "raw")
#: downsampled-block column order (raw blocks carry a single "v" column)
DS_COLS: tuple[str, ...] = (
    "count", "sum", "min", "max", "first", "last", "inc",
)

BLOCK_SUFFIX = ".blk"
BLOCK_MAGIC = b"PTSB1\x00"
BLOCK_TAIL = b"PTSE1\x00"
WAL_SUFFIX = ".log"
#: replay checkpoint cursor (ISSUE 19 satellite): ring snapshot + WAL
#: high-water mark, so attach parses only the bytes past the mark
CKPT_NAME = "ckpt.json"

#: a stitch tier must offer at least this many buckets per window
MIN_BUCKETS_PER_WINDOW = 4


# -- varint / zigzag ---------------------------------------------------------

def _uvarint(out: bytearray, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_uvarint(buf: bytes, pos: int) -> tuple[int, int]:
    n = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7


def _zigzag(n: int) -> int:
    return (n << 1) if n >= 0 else ((-n << 1) - 1)


def _unzigzag(u: int) -> int:
    return (u >> 1) if not (u & 1) else -((u + 1) >> 1)


def _encode_times(ts_ms: list[int]) -> bytes:
    """Delta-of-delta varint encoding: absolute first stamp, first
    delta, then the (usually tiny) second differences."""
    out = bytearray()
    _uvarint(out, ts_ms[0])
    prev_delta = 0
    prev = ts_ms[0]
    for t in ts_ms[1:]:
        delta = t - prev
        _uvarint(out, _zigzag(delta - prev_delta))
        prev_delta = delta
        prev = t
    return bytes(out)


def _decode_times(buf: bytes, pos: int, count: int) -> tuple[list[int], int]:
    first, pos = _read_uvarint(buf, pos)
    out = [first]
    prev = first
    delta = 0
    for _ in range(count - 1):
        dod, pos = _read_uvarint(buf, pos)
        delta += _unzigzag(dod)
        prev += delta
        out.append(prev)
    return out, pos


# -- block write / read ------------------------------------------------------

def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds: rename alone must do
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_block(path: str, tier: str,
                rows: Iterable[tuple[str, LabelPairs, str,
                                     list[int], dict[str, list[float]]]],
                ) -> Optional[dict]:
    """Write one immutable columnar block (tmp→fsync→rename). Each row
    is (name, label_pairs, kind, sorted ts_ms, columns); raw rows carry
    a single "v" column, downsampled rows the full DS_COLS set.
    Returns the footer dict, or None for an empty row set."""
    payload = bytearray(BLOCK_MAGIC)
    index: list[dict[str, Any]] = []
    min_t: Optional[float] = None
    max_t: Optional[float] = None
    cols_order = ("v",) if TIER_BUCKETS[tier] == 0 else DS_COLS
    for name, labels, kind, ts_ms, cols in rows:
        if not ts_ms:
            continue
        off = len(payload)
        payload += _encode_times(ts_ms)
        for col in cols_order:
            vals = cols[col]
            payload += struct.pack(f"<{len(vals)}d", *vals)
        lo, hi = ts_ms[0] / 1000.0, ts_ms[-1] / 1000.0
        min_t = lo if min_t is None else min(min_t, lo)
        max_t = hi if max_t is None else max(max_t, hi)
        index.append({
            "n": name, "l": [list(p) for p in labels], "k": kind,
            "off": off, "len": len(payload) - off, "count": len(ts_ms),
            "min_t": lo, "max_t": hi,
        })
    if not index:
        return None
    footer = {
        "v": 1, "tier": tier, "bucket_s": TIER_BUCKETS[tier],
        "min_t": min_t, "max_t": max_t, "series": index,
    }
    fbytes = json.dumps(footer, separators=(",", ":")).encode()
    payload += fbytes
    payload += struct.pack("<Q", len(fbytes))
    payload += BLOCK_TAIL
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))
    return footer


class BlockHandle:
    """One sealed block's footer index + on-demand series decode."""

    __slots__ = ("path", "tier", "bucket_s", "min_t", "max_t", "size",
                 "series")

    def __init__(self, path: str, footer: dict, size: int):
        self.path = path
        self.tier = footer["tier"]
        self.bucket_s = float(footer["bucket_s"])
        self.min_t = float(footer["min_t"])
        self.max_t = float(footer["max_t"])
        self.size = size
        self.series: dict[tuple[str, LabelPairs], dict] = {}
        for entry in footer["series"]:
            key = (entry["n"], tuple((k, v) for k, v in entry["l"]))
            self.series[key] = entry

    @classmethod
    def load(cls, path: str) -> "BlockHandle":
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            tail = struct.calcsize("<Q") + len(BLOCK_TAIL)
            if size < len(BLOCK_MAGIC) + tail:
                raise ValueError("truncated block")
            f.seek(size - tail)
            flen_raw = f.read(struct.calcsize("<Q"))
            if f.read(len(BLOCK_TAIL)) != BLOCK_TAIL:
                raise ValueError("bad tail magic")
            (flen,) = struct.unpack("<Q", flen_raw)
            f.seek(size - tail - flen)
            footer = json.loads(f.read(flen))
            f.seek(0)
            if f.read(len(BLOCK_MAGIC)) != BLOCK_MAGIC:
                raise ValueError("bad magic")
        if footer.get("v") != 1:
            raise ValueError(f"unknown block version {footer.get('v')!r}")
        return cls(path, footer, size)

    def read_series(self, key: tuple[str, LabelPairs]
                    ) -> Optional[tuple[list[float], dict[str, list[float]]]]:
        """(timestamps_s, columns) for one series, or None when the
        block does not carry it."""
        entry = self.series.get(key)
        if entry is None:
            return None
        with open(self.path, "rb") as f:
            f.seek(entry["off"])
            buf = f.read(entry["len"])
        count = entry["count"]
        ts_ms, pos = _decode_times(buf, 0, count)
        cols_order = ("v",) if self.bucket_s == 0 else DS_COLS
        cols: dict[str, list[float]] = {}
        for col in cols_order:
            width = 8 * count
            cols[col] = list(struct.unpack(f"<{count}d", buf[pos:pos + width]))
            pos += width
        return [t / 1000.0 for t in ts_ms], cols


class TierIndex:
    """Footer index over one tier directory's sealed blocks."""

    def __init__(self, root: str, tier: str):
        self.root = root
        self.tier = tier
        self.bucket_s = TIER_BUCKETS[tier]
        self._lock = threading.Lock()
        self._handles: dict[str, BlockHandle] = {}  # guarded-by: _lock
        self._dirty = True  # guarded-by: _lock
        os.makedirs(root, exist_ok=True)

    def invalidate(self) -> None:
        with self._lock:
            self._dirty = True

    def _rescan_locked(self) -> None:  # lint: holds=_lock
        try:
            names = {
                n for n in os.listdir(self.root)
                if n.endswith(BLOCK_SUFFIX)
            }
        except OSError:
            names = set()
        for gone in set(self._handles) - names:
            del self._handles[gone]
        # blocks are immutable once sealed: a size change means the
        # file was truncated/corrupted underneath us — reload it (and
        # let the footer parse decide whether it is still readable)
        for name, h in list(self._handles.items()):
            try:
                if os.path.getsize(h.path) != h.size:
                    del self._handles[name]
            except OSError:
                del self._handles[name]
        for name in sorted(names - set(self._handles)):
            path = os.path.join(self.root, name)
            try:
                self._handles[name] = BlockHandle.load(path)
            except (OSError, ValueError, KeyError, json.JSONDecodeError):
                log.warning("ignoring unreadable TSDB block %s", path,
                            exc_info=True)
        self._dirty = False

    def blocks(self, lo: Optional[float] = None,
               hi: Optional[float] = None) -> list[BlockHandle]:
        """Handles overlapping [lo, hi), sorted by min_t."""
        with self._lock:
            if self._dirty:
                self._rescan_locked()
            out = list(self._handles.values())
        if lo is not None:
            out = [b for b in out if b.max_t >= lo]
        if hi is not None:
            out = [b for b in out if b.min_t < hi]
        out.sort(key=lambda b: (b.min_t, b.path))
        return out

    def series_keys(self) -> dict[tuple[str, LabelPairs], str]:
        """(name, labels) → kind across every block footer."""
        out: dict[tuple[str, LabelPairs], str] = {}
        for b in self.blocks():
            for key, entry in b.series.items():
                out.setdefault(key, entry.get("k", "gauge"))
        return out

    def min_time(self) -> Optional[float]:
        bs = self.blocks()
        return bs[0].min_t if bs else None

    def remove_blocks(self, paths: Iterable[str]) -> int:
        removed = 0
        for path in paths:
            try:
                os.remove(path)
                removed += 1
            except OSError:
                pass
        if removed:
            self.invalidate()
            _fsync_dir(self.root)
        return removed

    def stats(self) -> dict[str, Any]:
        bs = self.blocks()
        return {
            "blocks": len(bs),
            "bytes": sum(b.size for b in bs),
            "series": len(self.series_keys()),
            "min_t": round(bs[0].min_t, 3) if bs else None,
            "max_t": round(max(b.max_t for b in bs), 3) if bs else None,
        }


# -- the durable store -------------------------------------------------------

def _merge_series(blocks: list[BlockHandle], key: tuple[str, LabelPairs],
                  lo: float, hi: float,
                  ) -> tuple[list[float], dict[str, list[float]]]:
    """One series' (ts, columns) merged across blocks, time-sorted and
    clipped to [lo, hi)."""
    rows: list[tuple[float, tuple[float, ...]]] = []
    cols_order: tuple[str, ...] = ("v",)
    for b in blocks:
        got = b.read_series(key)
        if got is None:
            continue
        ts, cols = got
        cols_order = ("v",) if b.bucket_s == 0 else DS_COLS
        series_cols = [cols[c] for c in cols_order]
        for i, t in enumerate(ts):
            if lo <= t < hi:
                rows.append((t, tuple(col[i] for col in series_cols)))
    rows.sort(key=lambda r: r[0])
    ts_out = [t for t, _ in rows]
    cols_out = {
        c: [vals[j] for _, vals in rows]
        for j, c in enumerate(cols_order)
    }
    return ts_out, cols_out


def _join_delta(prev_last: Optional[float], first: float) -> float:
    """Reset-aware increase between two adjacent counter observations:
    a drop means the counter restarted, so the later value IS the
    delta (the increase_of semantic, applied across a bucket/tier
    boundary)."""
    if prev_last is None:
        return 0.0
    return (first - prev_last) if first >= prev_last else first


class DurableTSDB(TSDB):
    """TSDB whose rings are backed by a WAL + sealed-block disk tier.

    ``add()`` is the only write path: accepted points also queue for
    the WAL. The ``tsdb-wal`` flusher thread batches them to the active
    segment (fsync per flush) and seals full/old segments into raw
    columnar blocks named ``b-<min_ms>-<max_ms>-w<seq>.blk`` — the
    ``w<seq>`` ties a block to the WAL segment it sealed, which is what
    makes seal crash-consistent: a segment whose block already exists
    is deleted (not replayed) at startup.
    """

    thread_name = "tsdb-wal"

    def __init__(self, directory: str, capacity: int = 720,
                 max_series: int = 4096, flush_interval_s: float = 2.0,
                 seal_points: int = 50000, seal_age_s: float = 300.0,
                 replay: bool = True, ckpt_points: Optional[int] = None):
        super().__init__(capacity, max_series)
        self.dir = directory
        self.flush_interval_s = max(0.05, float(flush_interval_s))
        self.seal_points = max(1, int(seal_points))
        self.seal_age_s = max(0.1, float(seal_age_s))
        if ckpt_points is None:
            from predictionio_tpu.utils.env import env_int

            ckpt_points = env_int("PIO_TSDB_CKPT_POINTS")
        self.ckpt_points = max(0, int(ckpt_points))
        self.wal_dir = os.path.join(directory, "wal")
        os.makedirs(self.wal_dir, exist_ok=True)
        self.tiers: dict[str, TierIndex] = {
            name: TierIndex(os.path.join(directory, name), name)
            for name in TIER_BUCKETS
        }
        self._dlock = threading.Lock()
        self._pending: list[tuple[float, str, LabelPairs, str, float]] = []  # guarded-by: _dlock
        self._wal_f: Optional[Any] = None  # guarded-by: _dlock
        self._wal_seq = self._next_wal_seq()  # guarded-by: _dlock
        self._wal_points = 0  # guarded-by: _dlock
        self._wal_opened_at = 0.0  # guarded-by: _dlock
        self.wal_flushed_points = 0  # guarded-by: _dlock
        self._ckpt_flushed = 0  # points flushed since last ckpt, guarded-by: _dlock
        self.ckpt_written = 0
        self.ckpt_seeded_points = 0
        self.replayed_points = 0
        self.replayed_series = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._drop_sealed_wal_segments()
        if replay:
            self._replay()

    # -- WAL write path ------------------------------------------------------

    def add(self, name: str, labels: Optional[dict], value: float,
            kind: str = "gauge", t: Optional[float] = None) -> bool:
        now = time.time() if t is None else t
        if not super().add(name, labels, value, kind, now):
            return False
        with self._dlock:
            self._pending.append(
                (now, name, _label_key(labels), kind, float(value))
            )
        return True

    def _wal_segments(self) -> list[tuple[int, str]]:
        """(seq, path) of every on-disk WAL segment, oldest first."""
        out = []
        try:
            names = os.listdir(self.wal_dir)
        except OSError:
            names = []
        for n in names:
            if n.startswith("w-") and n.endswith(WAL_SUFFIX):
                try:
                    seq = int(n[2:-len(WAL_SUFFIX)])
                except ValueError:
                    continue
                out.append((seq, os.path.join(self.wal_dir, n)))
        out.sort()
        return out

    def _next_wal_seq(self) -> int:
        segs = self._wal_segments()
        blocks = self.tiers["raw"].blocks()
        sealed = [
            int(b.path.rsplit("-w", 1)[1][:-len(BLOCK_SUFFIX)])
            for b in blocks if "-w" in os.path.basename(b.path)
        ]
        return max(
            [s for s, _ in segs] + sealed + [0]
        ) + 1

    def _drop_sealed_wal_segments(self) -> None:
        """Crash between block rename and segment unlink leaves both on
        disk; the block's w<seq> name identifies the stale segment."""
        sealed = set()
        for b in self.tiers["raw"].blocks():
            base = os.path.basename(b.path)
            if "-w" in base:
                try:
                    sealed.add(int(base.rsplit("-w", 1)[1][:-len(BLOCK_SUFFIX)]))
                except ValueError:
                    pass
        for seq, path in self._wal_segments():
            if seq in sealed:
                try:
                    os.remove(path)
                except OSError:
                    pass

    @staticmethod
    def _read_wal_segment(path: str, offset: int = 0
                          ) -> list[tuple[float, str, LabelPairs, str, float]]:
        points = []
        try:
            with open(path, "rb") as f:
                if offset:
                    f.seek(offset)
                for line in f:
                    try:
                        rec = json.loads(line)
                        points.append((
                            float(rec["t"]), str(rec["n"]),
                            tuple((str(k), str(v)) for k, v in rec["l"]),
                            str(rec.get("k", "gauge")), float(rec["v"]),
                        ))
                    except (ValueError, KeyError, TypeError):
                        continue  # torn tail line after a crash
        except OSError:
            pass
        return points

    def flush_once(self, now: Optional[float] = None,
                   seal: Optional[bool] = None) -> int:
        """Drain pending points to the active WAL segment (one fsync),
        then seal full/old segments. `seal=True` forces a seal of
        everything buffered (tests, clean shutdown); `seal=False`
        skips seal checks. Returns points flushed."""
        now = time.time() if now is None else now
        with self._dlock:
            batch, self._pending = self._pending, []
            if batch:
                if self._wal_f is None:
                    path = os.path.join(
                        self.wal_dir, f"w-{self._wal_seq:08d}{WAL_SUFFIX}"
                    )
                    self._wal_f = open(path, "ab")
                    self._wal_opened_at = now
                lines = [
                    json.dumps(
                        {"t": t, "n": n, "l": [list(p) for p in lbls],
                         "k": k, "v": v},
                        separators=(",", ":"),
                    )
                    for t, n, lbls, k, v in batch
                ]
                self._wal_f.write(("\n".join(lines) + "\n").encode())
                self._wal_f.flush()
                os.fsync(self._wal_f.fileno())
                self._wal_points += len(batch)
                self.wal_flushed_points += len(batch)
                self._ckpt_flushed += len(batch)
            want_ckpt = (
                self.ckpt_points > 0
                and self._ckpt_flushed >= self.ckpt_points
            )
            want_seal = seal is True or (
                seal is None
                and self._wal_points > 0
                and (self._wal_points >= self.seal_points
                     or now - self._wal_opened_at >= self.seal_age_s)
            )
            if want_seal and self._wal_f is not None:
                self._wal_f.close()
                self._wal_f = None
                self._wal_points = 0
                self._wal_seq += 1
        if want_ckpt:
            self._write_checkpoint()
        if seal is not False and self._seal_closed_segments():
            self.tiers["raw"].invalidate()
        return len(batch)

    # -- replay checkpoint cursor (ISSUE 19 satellite) -----------------------

    def _ckpt_path(self) -> str:
        return os.path.join(self.wal_dir, CKPT_NAME)

    def checkpoint_once(self) -> dict:
        """Flush pending points, then persist a replay cursor: the full
        ring snapshot plus the WAL (segment seq, byte offset) high-water
        mark it covers. The next attach seeds the rings from the
        snapshot and parses only WAL bytes past the mark instead of the
        whole unsealed tail. Returns the written cursor's position."""
        self.flush_once(seal=False)
        return self._write_checkpoint()

    def _write_checkpoint(self) -> dict:
        # position FIRST, snapshot second: a point racing in between is
        # in both the snapshot and the post-mark WAL bytes — replay sees
        # it twice, a harmless identical-sample dup (delta 0 for
        # counters). The opposite order could LOSE the point.
        with self._dlock:
            seq = self._wal_seq
            if self._wal_f is not None:
                off = self._wal_f.tell()
            else:
                # stop() closes the active file without bumping seq —
                # cover what is already on disk instead of re-reading it
                try:
                    off = os.path.getsize(
                        os.path.join(
                            self.wal_dir, f"w-{seq:08d}{WAL_SUFFIX}"
                        )
                    )
                except OSError:
                    off = 0
            self._ckpt_flushed = 0
        series_out = []
        with self._lock:
            rows = [
                (s.name, s.labels, s.kind, list(s.points))
                for s in self._series.values()
            ]
        for name, labels, kind, pts in rows:
            if not pts:
                continue
            series_out.append({
                "n": name, "l": [list(p) for p in labels], "k": kind,
                "pts": [[t, v] for t, v in pts],
            })
        doc = {"v": 1, "seq": seq, "off": off, "t": time.time(),
               "series": series_out}
        tmp = self._ckpt_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._ckpt_path())
        _fsync_dir(self.wal_dir)
        self.ckpt_written += 1
        return {"seq": seq, "off": off}

    def _load_checkpoint(self) -> Optional[dict]:
        try:
            with open(self._ckpt_path()) as f:
                doc = json.load(f)
            if doc.get("v") != 1:
                return None
            int(doc["seq"]); int(doc["off"])
            return doc
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _seal_closed_segments(self) -> int:
        """Convert every non-active WAL segment into a raw block, then
        unlink the segment (block first — a crash in between is healed
        by _drop_sealed_wal_segments)."""
        with self._dlock:
            active = self._wal_seq if self._wal_f is not None else None
        sealed = 0
        for seq, path in self._wal_segments():
            if seq == active:
                continue
            points = self._read_wal_segment(path)
            if points:
                per: dict[tuple[str, LabelPairs], list] = {}
                kinds: dict[tuple[str, LabelPairs], str] = {}
                for t, n, lbls, k, v in points:
                    key = (n, lbls)
                    per.setdefault(key, []).append((t, v))
                    kinds[key] = k
                rows = []
                lo = hi = None
                for key, pts in sorted(per.items()):
                    pts.sort()
                    ts_ms = [int(round(t * 1000.0)) for t, _ in pts]
                    # millisecond quantization can tie adjacent stamps;
                    # dod decoding needs monotone non-decreasing times
                    for i in range(1, len(ts_ms)):
                        if ts_ms[i] < ts_ms[i - 1]:
                            ts_ms[i] = ts_ms[i - 1]
                    rows.append((
                        key[0], key[1], kinds[key], ts_ms,
                        {"v": [v for _, v in pts]},
                    ))
                    lo = ts_ms[0] if lo is None else min(lo, ts_ms[0])
                    hi = ts_ms[-1] if hi is None else max(hi, ts_ms[-1])
                block_path = os.path.join(
                    self.tiers["raw"].root,
                    f"b-{lo}-{hi}-w{seq:08d}{BLOCK_SUFFIX}",
                )
                write_block(block_path, "raw", rows)
            try:
                os.remove(path)
            except OSError:
                pass
            sealed += 1
        if sealed:
            _fsync_dir(self.wal_dir)
        return sealed

    # -- flusher thread ------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=self.thread_name, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # final drain so a clean stop loses nothing (seal left to the
        # next process: its replay reads the segment directly)
        self.flush_once(seal=False)
        with self._dlock:
            if self._wal_f is not None:
                self._wal_f.close()
                self._wal_f = None

    def _loop(self) -> None:
        while not self._stop.wait(self.flush_interval_s):
            try:
                self.flush_once()
            except Exception:
                log.warning("TSDB WAL flush failed; points stay queued",
                            exc_info=True)

    # -- replay --------------------------------------------------------------

    def _replay(self, max_blocks: int = 64) -> None:
        """Reload the durable tail into the memory rings — at most
        `capacity` newest points per series, added oldest-first via the
        time-ordered insert path. With a checkpoint cursor present
        (ISSUE 19 satellite) the rings seed from its snapshot and only
        WAL bytes past the (seq, offset) high-water mark are parsed;
        without one, every WAL segment is read in full."""
        per: dict[tuple[str, LabelPairs], list[tuple[float, float]]] = {}
        kinds: dict[tuple[str, LabelPairs], str] = {}
        ck = self._load_checkpoint()
        ck_seq, ck_off = -1, 0
        # newest snapshotted stamp per series: the block-backfill filter
        # (a block may hold a pre-mark segment whose points the snapshot
        # already carries)
        ck_last: dict[tuple[str, LabelPairs], float] = {}
        if ck is not None:
            ck_seq, ck_off = int(ck["seq"]), int(ck["off"])
            for s in ck.get("series", ()):
                try:
                    key = (str(s["n"]),
                           tuple((str(k), str(v)) for k, v in s["l"]))
                    pts = [(float(t), float(v)) for t, v in s["pts"]]
                except (ValueError, KeyError, TypeError):
                    continue
                if not pts:
                    continue
                per[key] = pts
                ck_last[key] = pts[-1][0]
                kinds[key] = str(s.get("k", "gauge"))
                self.ckpt_seeded_points += len(pts)
        for seq, path in self._wal_segments():
            if seq < ck_seq:
                continue  # fully covered by the snapshot
            off = ck_off if seq == ck_seq else 0
            for t, n, lbls, k, v in self._read_wal_segment(path, off):
                key = (n, lbls)
                per.setdefault(key, []).append((t, v))
                kinds.setdefault(key, k)
        raw_blocks = self.tiers["raw"].blocks()
        for b in sorted(raw_blocks, key=lambda b: -b.max_t)[:max_blocks]:
            for key, entry in b.series.items():
                have = per.get(key)
                full = have is not None and len(have) >= self.capacity
                if full and (
                    key not in ck_last or b.max_t <= ck_last[key]
                ):
                    continue
                got = b.read_series(key)
                if got is None:
                    continue
                ts, cols = got
                pts = zip(ts, cols["v"])
                if key in ck_last:
                    # only what the snapshot has not seen (a segment
                    # sealed after the ckpt holds post-snapshot points)
                    pts = ((t, v) for t, v in pts if t > ck_last[key])
                per.setdefault(key, []).extend(pts)
                kinds.setdefault(key, entry.get("k", "gauge"))
        for key, pts in per.items():
            pts.sort()
            labels = dict(key[1])
            ok = True
            for t, v in pts[-self.capacity:]:
                ok = TSDB.add(self, key[0], labels, v, kinds[key], t)
                if not ok:
                    break  # cardinality cap: counted by add()
                self.replayed_points += 1
            if ok:
                self.replayed_series += 1

    # -- tier-stitched reads -------------------------------------------------

    def _disk_series_map(self) -> dict[tuple[str, LabelPairs], str]:
        out: dict[tuple[str, LabelPairs], str] = {}
        for name in TIER_ORDER:
            for key, kind in self.tiers[name].series_keys().items():
                out.setdefault(key, kind)
        return out

    def _pick_tier(self, window_s: float, cutoff: float) -> str:
        """The coarsest tier that can answer the window: adequate
        resolution (>= MIN_BUCKETS_PER_WINDOW buckets per window) and
        coverage reaching the window start, else the adequate tier
        that reaches back furthest."""
        adequate = [
            name for name in TIER_ORDER
            if TIER_BUCKETS[name] == 0
            or TIER_BUCKETS[name] * MIN_BUCKETS_PER_WINDOW <= window_s
        ] or ["raw"]
        best = None
        best_min = None
        for name in adequate:
            lo = self.tiers[name].min_time()
            if lo is None:
                continue
            if lo <= cutoff:
                return name
            if best_min is None or lo < best_min:
                best, best_min = name, lo
        return best or "raw"

    def _disk_points(self, key: tuple[str, LabelPairs], lo: float,
                     hi: float, window_s: float,
                     tier: Optional[str] = None) -> list[tuple[float, float]]:
        """Value points for [lo, hi) from the chosen tier; downsampled
        buckets surface as (bucket_t, last)."""
        tier = tier or self._pick_tier(window_s, lo)
        idx = self.tiers[tier]
        blocks = idx.blocks(lo, hi)
        if not blocks:
            return []
        ts, cols = _merge_series(blocks, key, lo, hi)
        vals = cols.get("v") if idx.bucket_s == 0 else cols.get("last")
        if not ts or vals is None:
            return []
        return list(zip(ts, vals))

    def _disk_values(self, key: tuple[str, LabelPairs], lo: float,
                     hi: float, window_s: float,
                     tier: Optional[str] = None) -> list[float]:
        return [v for _t, v in self._disk_points(key, lo, hi, window_s, tier)]

    def _disk_increase(self, key: tuple[str, LabelPairs], cutoff: float,
                       edge: float, window_s: float,
                       tier: Optional[str] = None,
                       edge_complete: bool = False,
                       ) -> tuple[float, Optional[float]]:
        """Reset-aware counter increase over the disk span
        [cutoff, edge), baselined like TSDB.series_increase (the last
        observation before the window seeds the first delta). Returns
        (increase, last_value) — last_value joins onto the memory
        suffix."""
        tier = tier or self._pick_tier(window_s, cutoff)
        idx = self.tiers[tier]
        # reach one bucket (or a retention-bounded slice) behind the
        # cutoff so the pre-window baseline sample is in range
        back = idx.bucket_s if idx.bucket_s else window_s
        blocks = idx.blocks(cutoff - back, edge)
        if not blocks:
            return 0.0, None
        ts, cols = _merge_series(blocks, key, cutoff - back, edge)
        if not ts:
            return 0.0, None
        if idx.bucket_s == 0:
            pts = list(zip(ts, cols["v"]))
            idx0 = 0
            for idx0, (t, _v) in enumerate(pts):
                if t >= cutoff:
                    break
            else:
                return 0.0, pts[-1][1]
            windowed = pts[idx0:]
            if idx0 > 0:
                windowed = [pts[idx0 - 1]] + windowed
            return increase_of(windowed), pts[-1][1]
        # downsampled: whole buckets overlapping the window, joined
        # reset-aware on first/last continuity; the bucket straddling
        # the cutoff contributes wholly (documented edge bound). When
        # the span ends at the memory floor (`edge_complete`), a bucket
        # straddling the edge is EXCLUDED — its `last` was observed
        # inside the memory window and would read as a phantom reset at
        # the join; the join itself covers the resulting gap exactly.
        total = 0.0
        prev_last: Optional[float] = None
        last_val: Optional[float] = None
        for i, bt in enumerate(ts):
            in_window = bt + idx.bucket_s > cutoff and bt < edge
            if edge_complete and bt + idx.bucket_s > edge:
                in_window = False
            if in_window:
                total += _join_delta(prev_last, cols["first"][i])
                total += cols["inc"][i]
                last_val = cols["last"][i]
            prev_last = cols["last"][i]
        return total, last_val

    def _key_of(self, series: Series) -> tuple[str, LabelPairs]:
        return (series.name, series.labels)

    def matching(self, name: str,
                 match: Optional[dict] = None) -> list[Series]:
        """Memory series plus synthetic (empty-ring) handles for series
        that now live only on disk — a long SLO window must see a dead
        instance's counters."""
        out = super().matching(name, match)
        have = {self._key_of(s) for s in out}
        want = None if match is None else _label_key(match)
        with self._lock:
            in_memory = {
                k for k in self._series if k[0] == name
            }
        for key, kind in self._disk_series_map().items():
            if key[0] != name or key in have or key in in_memory:
                continue
            if want is not None and not set(want) <= set(key[1]):
                continue
            out.append(Series(name, key[1], kind, capacity=2))
        return out

    def points(self, series: Series, window_s: Optional[float] = None,
               now: Optional[float] = None) -> list[tuple[float, float]]:
        now = time.time() if now is None else now
        mem = super().points(series, None, now)
        if window_s is None:
            if mem:
                return mem
            # disk-only series with no window bound: the newest ring's
            # worth from the finest tier that has it
            key = self._key_of(series)
            for tier in reversed(TIER_ORDER):
                lo = self.tiers[tier].min_time()
                if lo is None:
                    continue
                pts = self._disk_points(key, lo, now + 1.0, 0.0, tier)
                if pts:
                    return pts[-self.capacity:]
            return []
        cutoff = now - window_s
        mem_floor = mem[0][0] if mem else None
        mem_win = [p for p in mem if p[0] >= cutoff]
        if mem_floor is not None and mem_floor <= cutoff:
            return mem_win
        edge = mem_floor if mem_floor is not None else now + 1.0
        disk = self._disk_points(self._key_of(series), cutoff, edge,
                                 window_s)
        return disk + mem_win

    def series_increase(self, series: Series,
                        window_s: Optional[float] = None,
                        now: Optional[float] = None) -> float:
        if window_s is None:
            return super().series_increase(series, None, now)
        now = time.time() if now is None else now
        cutoff = now - window_s
        mem = super().points(series, None, now)
        mem_floor = mem[0][0] if mem else None
        if mem_floor is not None and mem_floor <= cutoff:
            return super().series_increase(series, window_s, now)
        edge = mem_floor if mem_floor is not None else now + 1.0
        disk_inc, disk_last = self._disk_increase(
            self._key_of(series), cutoff, edge, window_s,
            edge_complete=bool(mem),
        )
        if not mem:
            return disk_inc
        total = disk_inc + _join_delta(disk_last, mem[0][1])
        return total + increase_of(mem)

    def quantile_over_time(self, name: str, q: float,
                           match: Optional[dict] = None,
                           window_s: Optional[float] = None,
                           now: Optional[float] = None) -> Optional[float]:
        # base implementation reads through self.matching/self.points,
        # both stitched here — inherit it unchanged
        return super().quantile_over_time(name, q, match, window_s, now)

    def latest_point(self, name: str, match: Optional[dict] = None
                     ) -> Optional[tuple[float, float]]:
        best = super().latest_point(name, match)
        if best is not None:
            return best
        want = None if match is None else _label_key(match)
        now = time.time()
        for tier in reversed(TIER_ORDER):
            idx = self.tiers[tier]
            lo = idx.min_time()
            if lo is None:
                continue
            for key in idx.series_keys():
                if key[0] != name:
                    continue
                if want is not None and not set(want) <= set(key[1]):
                    continue
                pts = self._disk_points(key, lo, now + 1.0, 0.0, tier)
                if pts and (best is None or pts[-1][0] > best[0]):
                    best = pts[-1]
            if best is not None:
                return best
        return best

    # -- introspection -------------------------------------------------------

    def durable_stats(self) -> dict[str, Any]:
        with self._dlock:
            wal = {
                "segments": len(self._wal_segments()),
                "pending": len(self._pending),
                "active_points": self._wal_points,
                "flushed_points": self.wal_flushed_points,
                "ckpt_pending_points": self._ckpt_flushed,
            }
        return {
            "dir": self.dir,
            "wal": wal,
            "tiers": {name: self.tiers[name].stats() for name in TIER_ORDER},
            "replayed_points": self.replayed_points,
            "replayed_series": self.replayed_series,
            "ckpt_written": self.ckpt_written,
            "ckpt_seeded_points": self.ckpt_seeded_points,
        }

    def summary(self, limit: int = 0) -> dict[str, Any]:
        out = super().summary(limit)
        out["durable"] = self.durable_stats()
        return out
