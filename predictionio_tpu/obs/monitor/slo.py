"""SLO burn-rate alerting over the in-process TSDB.

Declarative SLO specs — availability and latency objectives per route
(and per tenant) — evaluated as **multi-window burn-rate rules** (the
Google SRE workbook shape: a fast window catches cliffs, a slow window
suppresses blips; an alert needs BOTH over threshold), driving a
pending → firing → resolved state machine surfaced at ``GET /alerts``
and as an ``alerts_firing{slo}`` gauge.

Spec fields (JSON; `PIO_SLOS` holds a JSON array or ``@/path.json``):

  name          unique id (becomes the metric label — keep it small)
  kind          "availability" | "latency" | "up"
  objective     e.g. 0.99  (error budget = 1 - objective)
  server        metrics `server` label (default "query")
  route         metric route label (default "/queries.json")
  tenant        scope to one tenant's series instead of the route
  instance      kind "up" only: the scrape target to watch
  threshold_ms  latency only: the "good request" bound (default 250)
  window_s      slow window (default 3600)
  fast_window_s fast window (default 300)
  burn_threshold  both windows must burn ≥ this (default 14.4 — the
                  page-worthy rate; 1.0 = "exactly eating the budget")
  for_s         seconds a breach must persist in `pending` before
                `firing` (default 0 → fires on the second consecutive
                breached evaluation)
  resolve_s     hysteresis: seconds of clean evaluations a firing
                alert needs before `resolved` (default 0 → next clean
                evaluation resolves)
  min_samples   requests the fast window must contain before the rule
                is judged at all — the zero-traffic guard: an idle
                route neither divides by zero nor flaps its alert
  extra_pairs   additional (fast, slow) burn-rate window pairs judged
                alongside the primary one (ISSUE 18): a list of
                {"fast_window_s", "window_s", "burn_threshold"}
                objects, e.g. the SRE-workbook 30m/6h@6 and 6h/3d@1
                ladder. A breach on ANY pair (both its windows over
                its threshold) trips the alert; long windows answer
                from the durable disk tier when PIO_TSDB_DIR is set,
                so a restarted process still alerts on pre-restart
                burn
  aggregate     fleet scope (ISSUE 16): judge the scraper's
                `instance`-tagged series instead of this process's own.
                "sum" pools bad/total across every instance; "mean"
                averages the per-instance error fractions (a single
                unhealthy replica shows up even when the pooled fleet
                total still looks fine). Kind "up" + aggregate watches
                every scrape target, so `instance` becomes optional.

Error-rate sources (all counter series the sampler already records):

  availability  http_requests_total{server,path,status} — 5xx / all;
                with `tenant`: tenant_requests_total{tenant,outcome}
  latency       http_request_seconds_bucket{server,path,le} — the
                fraction of requests over `threshold_ms`; with
                `tenant`: tenant_serve_seconds_bucket{tenant,le}
  up            1 - mean(up{instance}) — a dead scrape target burns
                its availability budget directly
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

from predictionio_tpu.obs.monitor.tsdb import TSDB
from predictionio_tpu.obs.registry import MetricsRegistry

log = logging.getLogger(__name__)
from predictionio_tpu.utils.env import env_str

KINDS = ("availability", "latency", "up", "expr")

# alert states
INACTIVE = "inactive"
PENDING = "pending"
FIRING = "firing"
RESOLVED = "resolved"


@dataclass(frozen=True)
class SLOSpec:
    name: str
    kind: str = "availability"
    objective: float = 0.99
    server: str = "query"
    route: str = "/queries.json"
    tenant: Optional[str] = None
    instance: Optional[str] = None
    threshold_ms: float = 250.0
    window_s: float = 3600.0
    fast_window_s: float = 300.0
    burn_threshold: float = 14.4
    for_s: float = 0.0
    resolve_s: float = 0.0
    min_samples: int = 1
    aggregate: Optional[str] = None
    # kind "expr" (ISSUE 17): the error fraction IS this series-algebra
    # expression (obs.monitor.expr), evaluated per window with $window
    # substituted (e.g. "sum(increase(errs[$window])) /
    # sum(increase(reqs[$window]))"). min_samples does not apply — an
    # expression with no data holds state exactly like no-traffic.
    expr: Optional[str] = None
    # multi-window ladder (ISSUE 18): extra (fast_s, slow_s, threshold)
    # triples judged alongside the primary pair; canonicalized by
    # __post_init__ from dicts/sequences
    extra_pairs: tuple = ()

    def __post_init__(self):
        if self.extra_pairs:
            norm = []
            for p in self.extra_pairs:
                if isinstance(p, dict):
                    unknown = set(p) - {
                        "fast_window_s", "window_s", "burn_threshold"
                    }
                    if unknown:
                        raise ValueError(
                            f"SLO {self.name!r}: extra_pairs entry has "
                            f"unknown field(s): {', '.join(sorted(unknown))}"
                        )
                    fast = float(p.get("fast_window_s", 0.0))
                    slow = float(p.get("window_s", 0.0))
                    thr = float(p.get("burn_threshold", 1.0))
                else:
                    seq = tuple(p)
                    if len(seq) != 3:
                        raise ValueError(
                            f"SLO {self.name!r}: extra_pairs entries are "
                            "(fast_window_s, window_s, burn_threshold)"
                        )
                    fast, slow, thr = (float(x) for x in seq)
                if fast <= 0 or slow <= 0 or thr <= 0:
                    raise ValueError(
                        f"SLO {self.name!r}: extra pair windows and "
                        "threshold must be > 0"
                    )
                if fast > slow:
                    raise ValueError(
                        f"SLO {self.name!r}: extra pair fast window must "
                        "not exceed its slow window"
                    )
                norm.append((fast, slow, thr))
            object.__setattr__(self, "extra_pairs", tuple(norm))
        if not self.name:
            raise ValueError("SLO spec needs a name")
        if self.kind not in KINDS:
            raise ValueError(
                f"SLO {self.name!r}: unknown kind {self.kind!r} "
                f"(known: {', '.join(KINDS)})"
            )
        if self.kind == "expr":
            if not self.expr:
                raise ValueError(
                    f"SLO {self.name!r}: kind 'expr' needs an 'expr'"
                )
            # parse eagerly (with a dummy window) so a typo fails at
            # spec-load time, not silently on every evaluation
            from predictionio_tpu.obs.monitor.expr import parse

            parse(self.expr.replace("$window", "300s"))
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SLO {self.name!r}: objective must be in (0, 1), got "
                f"{self.objective}"
            )
        if self.fast_window_s <= 0 or self.window_s <= 0:
            raise ValueError(f"SLO {self.name!r}: windows must be > 0")
        if self.fast_window_s > self.window_s:
            raise ValueError(
                f"SLO {self.name!r}: fast window must not exceed the "
                "slow window"
            )
        if self.aggregate not in (None, "sum", "mean"):
            raise ValueError(
                f"SLO {self.name!r}: aggregate must be 'sum' or 'mean', "
                f"got {self.aggregate!r}"
            )
        if self.kind == "up" and not self.instance and not self.aggregate:
            raise ValueError(
                f"SLO {self.name!r}: kind 'up' needs an 'instance' "
                "(or an 'aggregate' to watch every scrape target)"
            )

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    @property
    def burn_pairs(self) -> tuple[tuple[float, float, float], ...]:
        """Every (fast_s, slow_s, threshold) pair, primary first."""
        return (
            (self.fast_window_s, self.window_s, self.burn_threshold),
        ) + self.extra_pairs

    @classmethod
    def from_dict(cls, d: dict) -> "SLOSpec":
        known = {
            k: d[k] for k in (
                "name", "kind", "objective", "server", "route", "tenant",
                "instance", "threshold_ms", "window_s", "fast_window_s",
                "burn_threshold", "for_s", "resolve_s", "min_samples",
                "aggregate", "expr", "extra_pairs",
            ) if k in d
        }
        unknown = set(d) - set(known)
        if unknown:
            raise ValueError(
                f"SLO spec has unknown field(s): {', '.join(sorted(unknown))}"
            )
        return cls(**known)

    def to_dict(self) -> dict[str, Any]:
        out = {
            "name": self.name, "kind": self.kind,
            "objective": self.objective, "window_s": self.window_s,
            "fast_window_s": self.fast_window_s,
            "burn_threshold": self.burn_threshold,
            "for_s": self.for_s, "resolve_s": self.resolve_s,
            "min_samples": self.min_samples,
        }
        if self.kind == "up":
            if self.instance:
                out["instance"] = self.instance
        elif self.kind == "expr":
            out["expr"] = self.expr
        else:
            out["server"] = self.server
            if self.tenant:
                out["tenant"] = self.tenant
            else:
                out["route"] = self.route
        if self.kind == "latency":
            out["threshold_ms"] = self.threshold_ms
        if self.aggregate:
            out["aggregate"] = self.aggregate
        if self.extra_pairs:
            out["extra_pairs"] = [
                {"fast_window_s": f, "window_s": w, "burn_threshold": t}
                for f, w, t in self.extra_pairs
            ]
        return out


def load_slos(text: Optional[str] = None) -> list[SLOSpec]:
    """Parse `PIO_SLOS` (or an explicit string): a JSON array of spec
    objects, or ``@/path/to/slos.json``. Malformed input logs and
    yields [] — a typo'd spec must not take a server down."""
    raw = text if text is not None else env_str("PIO_SLOS")
    raw = (raw or "").strip()
    if not raw:
        return []
    try:
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        data = json.loads(raw)
        if isinstance(data, dict):
            data = [data]
        return [SLOSpec.from_dict(d) for d in data]
    except (OSError, ValueError, TypeError) as e:
        log.warning("ignoring malformed PIO_SLOS (%s)", e)
        return []


def tenant_slo_presets(tenant_ids) -> list[SLOSpec]:
    """Per-tenant SLO presets, auto-derived from the tenant records at
    mux attach (PIO_TENANT_SLO_PRESETS): a 99% availability objective
    plus a 95% sub-500ms latency objective per tenant, judged only once
    the tenant shows real traffic (min_samples guards quiet tenants)."""
    specs: list[SLOSpec] = []
    for tid in sorted(set(tenant_ids)):
        specs.append(SLOSpec(
            name=f"tenant:{tid}:availability", kind="availability",
            objective=0.99, tenant=str(tid), min_samples=10,
        ))
        specs.append(SLOSpec(
            name=f"tenant:{tid}:latency", kind="latency",
            objective=0.95, tenant=str(tid), threshold_ms=500.0,
            min_samples=10,
        ))
    return specs


def replication_slo_presets(
    max_lag_revisions: Optional[int] = None,
) -> list[SLOSpec]:
    """Replication-lag SLO preset (ISSUE 19): an expression objective on
    the `replication_lag_revisions` gauge the SegmentShipper maintains
    per follower namespace. The error fraction is p99 lag over the
    window as a fraction of the `PIO_REPL_MAX_LAG_REVISIONS` budget, so
    with objective 0.5 and burn_threshold 2.0 the alert fires exactly
    when sustained lag reaches the configured ceiling (fraction ≥ 1.0 ⇔
    burn ≥ 2.0 × the 0.5 budget) on both the fast and slow windows."""
    if max_lag_revisions is None:
        from predictionio_tpu.utils.env import env_int

        max_lag_revisions = env_int("PIO_REPL_MAX_LAG_REVISIONS")
    budget = max(1, int(max_lag_revisions))
    return [SLOSpec(
        name="replication:lag", kind="expr",
        objective=0.5, burn_threshold=2.0,
        expr=(
            "max(quantile_over_time(0.99, "
            f"replication_lag_revisions[$window])) / {budget}"
        ),
    )]


# -- error-rate math ---------------------------------------------------------
#
# Module-level so the engine's per-evaluation path and the sampler-tick
# recording pass (record_slo_ratios) share ONE implementation of the
# raw-window math — two copies would drift on exactly the edge cases
# (counter resets, window baselines, min_samples) that matter.


def _availability_source(spec: SLOSpec):
    """(series name, label match, is_bad predicate) for one spec."""
    if spec.tenant:
        def is_bad(lbls: dict) -> bool:
            return lbls.get("outcome") == "error"

        return "tenant_requests_total", {"tenant": spec.tenant}, is_bad

    def is_bad(lbls: dict) -> bool:
        try:
            return int(lbls.get("status", "0")) >= 500
        except ValueError:
            return False

    return (
        "http_requests_total",
        {"server": spec.server, "path": spec.route},
        is_bad,
    )


def _pool_latency_fraction(
    tsdb: TSDB, buckets: list, counts: list, threshold_s: float,
    window_s: float, now: float,
) -> tuple[Optional[float], float]:
    """(bad fraction, total) over one pool of bucket/count series; the
    smallest le ≥ threshold is the good-bucket (PromQL's conservative
    rounding). None fraction = no traffic or no usable bucket."""
    total = sum(tsdb.series_increase(s, window_s, now) for s in counts)
    if total <= 0:
        return None, 0.0
    best_le: Optional[float] = None
    by_le: dict[float, list] = {}
    for s in buckets:
        le_s = s.labels_dict().get("le", "")
        try:
            le = float("inf") if le_s == "+Inf" else float(le_s)
        except ValueError:
            continue
        by_le.setdefault(le, []).append(s)
        if le >= threshold_s and (best_le is None or le < best_le):
            best_le = le
    if best_le is None:
        return None, total
    good = sum(
        tsdb.series_increase(s, window_s, now) for s in by_le[best_le]
    )
    return max(0.0, 1.0 - good / total), total


def error_fraction(
    tsdb: TSDB, spec: SLOSpec, window_s: float, now: float
) -> tuple[Optional[float], float]:
    """(bad/total over the window, total). total < min_samples →
    (None, total): not enough traffic to judge — callers hold state
    instead of flapping (and never divide by zero).

    With `spec.aggregate` set, only series carrying an `instance`
    label (the fleet scraper's stamp) are judged: "sum" pools
    bad/total across the fleet, "mean" averages the per-instance
    fractions (zero-traffic instances are skipped)."""
    floor = max(1, spec.min_samples)
    if spec.kind == "expr":
        from predictionio_tpu.obs.monitor import expr as _expr

        text = (spec.expr or "").replace("$window", f"{window_s:g}s")
        try:
            val = _expr.evaluate(
                tsdb, text, now, default_window_s=window_s
            )
        except _expr.ExprError:
            return None, 0.0
        if val is None:
            return None, 0.0
        if isinstance(val, list):
            if not val:
                return None, 0.0
            # a vector result averages across label sets — the scalar
            # shape burn_rate needs; write the expression with sum()/
            # ratios if a different pooling is wanted
            val = sum(v for _l, v in val) / len(val)
        # it IS an error fraction by contract: clamp to the unit range
        # so a mis-scaled expression can't produce a negative budget
        frac = min(max(float(val), 0.0), 1.0)
        return frac, float(floor)
    if spec.kind == "up":
        match = {"instance": spec.instance} if spec.instance else None
        if spec.aggregate == "mean":
            per: dict[str, list[float]] = {}
            for s in tsdb.matching("up", match):
                inst = s.labels_dict().get("instance")
                if inst is None:
                    continue
                per.setdefault(inst, []).extend(
                    v for _t, v in tsdb.points(s, window_s, now)
                )
            n = float(sum(len(p) for p in per.values()))
            fracs = [
                1.0 - sum(p) / len(p) for p in per.values() if p
            ]
            if n < floor or not fracs:
                return None, n
            return sum(fracs) / len(fracs), n
        pts: list[float] = []
        for s in tsdb.matching("up", match):
            if spec.aggregate and "instance" not in s.labels_dict():
                continue
            pts.extend(v for _t, v in tsdb.points(s, window_s, now))
        if len(pts) < floor:
            return None, float(len(pts))
        return 1.0 - sum(pts) / len(pts), float(len(pts))
    if spec.kind == "availability":
        name, match, is_bad = _availability_source(spec)
        series = tsdb.matching(name, match)
        if spec.aggregate:
            series = [
                s for s in series if "instance" in s.labels_dict()
            ]
        if spec.aggregate == "mean":
            per_tot: dict[str, float] = {}
            per_bad: dict[str, float] = {}
            for s in series:
                inst = s.labels_dict()["instance"]
                inc = tsdb.series_increase(s, window_s, now)
                per_tot[inst] = per_tot.get(inst, 0.0) + inc
                if is_bad(s.labels_dict()):
                    per_bad[inst] = per_bad.get(inst, 0.0) + inc
            grand = sum(per_tot.values())
            fracs = [
                per_bad.get(i, 0.0) / t
                for i, t in per_tot.items() if t > 0
            ]
            if grand < floor or not fracs:
                return None, grand
            return sum(fracs) / len(fracs), grand
        total = bad = 0.0
        for s in series:
            inc = tsdb.series_increase(s, window_s, now)
            total += inc
            if is_bad(s.labels_dict()):
                bad += inc
        if total < floor:
            return None, total
        return bad / total, total
    # latency: good = requests under the threshold, via the sampled
    # cumulative bucket counters
    if spec.tenant:
        name = "tenant_serve_seconds_bucket"
        cname = "tenant_serve_seconds_count"
        match = {"tenant": spec.tenant}
    else:
        name = "http_request_seconds_bucket"
        cname = "http_request_seconds_count"
        match = {"server": spec.server, "path": spec.route}
    threshold_s = spec.threshold_ms / 1000.0
    buckets = tsdb.matching(name, match)
    counts = tsdb.matching(cname, match)
    if spec.aggregate:
        buckets = [s for s in buckets if "instance" in s.labels_dict()]
        counts = [s for s in counts if "instance" in s.labels_dict()]
    if spec.aggregate == "mean":
        pools: dict[str, tuple[list, list]] = {}
        for s in counts:
            pools.setdefault(
                s.labels_dict()["instance"], ([], [])
            )[1].append(s)
        for s in buckets:
            inst = s.labels_dict()["instance"]
            if inst in pools:
                pools[inst][0].append(s)
        grand = 0.0
        fracs = []
        for bs, cs in pools.values():
            frac, total = _pool_latency_fraction(
                tsdb, bs, cs, threshold_s, window_s, now
            )
            grand += total
            if frac is not None:
                fracs.append(frac)
        if grand < floor or not fracs:
            return None, grand
        return sum(fracs) / len(fracs), grand
    frac, total = _pool_latency_fraction(
        tsdb, buckets, counts, threshold_s, window_s, now
    )
    if total < floor or frac is None:
        return None, total
    return frac, total


# -- recorded ratios (ISSUE 16) ----------------------------------------------
#
# record_slo_ratios runs on the SAMPLER tick (MetricsSampler's
# post_sample hook — no extra thread): one raw-window rescan per tick
# stores `slo_error_ratio{slo,window}` and `slo_samples{slo,window}` as
# first-class series, and the engine's burn_rate then reads one
# precomputed point per window instead of rescanning every raw bucket
# ring on every evaluation. Freshness-gated: a recorded point older
# than `recorded_max_age_s` (sampler wedged, rules disabled) silently
# falls back to the raw math, so recording can never make alerting
# WRONG — only cheap.

RECORDED_RATIO = "slo_error_ratio"
RECORDED_SAMPLES = "slo_samples"


def record_slo_ratios(
    tsdb: TSDB, specs: list[SLOSpec], now: Optional[float] = None
) -> int:
    """One recording pass over every spec × (fast, slow) window.
    Samples are always written (the engine needs 'quiet' to be
    observable); the ratio only when there is enough traffic to judge.
    Returns points written."""
    now = time.time() if now is None else now
    written = 0
    for spec in specs:
        for tag, window_s in (
            ("fast", spec.fast_window_s), ("slow", spec.window_s)
        ):
            try:
                frac, samples = error_fraction(tsdb, spec, window_s, now)
            except Exception:
                log.debug(
                    "recording ratios for %s failed", spec.name,
                    exc_info=True,
                )
                continue
            labels = {"slo": spec.name, "window": tag}
            if tsdb.add(RECORDED_SAMPLES, labels, samples, "gauge", now):
                written += 1
            if frac is not None and tsdb.add(
                RECORDED_RATIO, labels, frac, "gauge", now
            ):
                written += 1
    return written


@dataclass
class AlertStatus:
    """One spec's live alert state + the numbers behind it."""

    spec: SLOSpec
    state: str = INACTIVE
    since: Optional[float] = None        # entered current state at
    pending_since: Optional[float] = None
    clear_since: Optional[float] = None  # firing + non-breach streak start
    fast_burn: Optional[float] = None
    slow_burn: Optional[float] = None
    fast_samples: float = 0.0
    last_eval: Optional[float] = None
    transitions: int = 0
    # per-pair burn numbers of the last evaluation (ISSUE 18):
    # primary-first, same order as spec.burn_pairs
    pair_burns: list = field(default_factory=list)
    # (t, fast_burn) ring for the dashboard sparkline
    history: deque = field(default_factory=lambda: deque(maxlen=120))

    def to_dict(self) -> dict[str, Any]:
        return {
            "slo": self.spec.name,
            "state": self.state,
            "since": self.since,
            "fast_burn": (
                None if self.fast_burn is None
                else round(self.fast_burn, 4)
            ),
            "slow_burn": (
                None if self.slow_burn is None
                else round(self.slow_burn, 4)
            ),
            "fast_samples": self.fast_samples,
            "burn_threshold": self.spec.burn_threshold,
            "error_budget": round(self.spec.budget, 6),
            "transitions": self.transitions,
            "last_eval": self.last_eval,
            "pairs": [dict(p) for p in self.pair_burns],
            "spec": self.spec.to_dict(),
        }


class SLOEngine:
    """Evaluates every spec against the TSDB on a fixed interval and
    drives the alert state machines. `stop()` joins the thread."""

    thread_name = "slo-engine"

    def __init__(self, tsdb: TSDB, specs: list[SLOSpec],
                 interval_s: float = 15.0,
                 registry: Optional[MetricsRegistry] = None,
                 on_transition=None):
        self.tsdb = tsdb
        self.interval_s = max(0.05, float(interval_s))
        # recorded-ratio fast path (ISSUE 16): points no older than this
        # are trusted over a raw rescan; 0 disables the fast path (the
        # Monitor sets ~2 sampler intervals when recording is on)
        self.recorded_max_age_s = 0.0
        # notification hook (ISSUE 9 satellite): called OUTSIDE the lock
        # as (status_dict, old_state, new_state) on every state change —
        # the Monitor wires the webhook/exec sinks through it
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._status: dict[str, AlertStatus] = {
            s.name: AlertStatus(spec=s) for s in specs
        }
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if registry is None:
            from predictionio_tpu.obs.registry import get_default_registry

            registry = get_default_registry()
        self._firing_gauge = registry.gauge(
            "alerts_firing", "SLO alerts currently firing (1) or not (0)",
            ("slo",),  # label-bound: operator-declared SLO spec names
        )

    # -- spec management ---------------------------------------------------
    def set_specs(self, specs: list[SLOSpec]) -> None:
        with self._lock:
            old = self._status
            self._status = {
                s.name: old.get(s.name) or AlertStatus(spec=s)
                for s in specs
            }
            for name, st in self._status.items():
                st.spec = next(s for s in specs if s.name == name)

    def specs(self) -> list[SLOSpec]:
        with self._lock:
            return [st.spec for st in self._status.values()]

    # -- error-rate math ---------------------------------------------------
    def _error_fraction(
        self, spec: SLOSpec, window_s: float, now: float
    ) -> tuple[Optional[float], float]:
        """Raw-window math — see the module-level error_fraction (one
        shared implementation with the recording pass)."""
        return error_fraction(self.tsdb, spec, window_s, now)

    def _recorded_fraction(
        self, spec: SLOSpec, window_tag: str, now: float
    ) -> Optional[tuple[Optional[float], float]]:
        """The recorded fast path: read the precomputed
        slo_error_ratio/slo_samples point for this spec+window. None =
        MISS (no point, or staler than recorded_max_age_s) — caller
        falls back to the raw rescan. (None, samples) = a fresh HIT
        that says 'not enough traffic to judge' — the hold-state
        signal, same as the raw path's."""
        if self.recorded_max_age_s <= 0:
            return None
        match = {"slo": spec.name, "window": window_tag}
        spt = self.tsdb.latest_point(RECORDED_SAMPLES, match)
        if spt is None or now - spt[0] > self.recorded_max_age_s:
            return None
        samples = spt[1]
        if samples < max(1, spec.min_samples):
            return None, samples
        rpt = self.tsdb.latest_point(RECORDED_RATIO, match)
        if rpt is None or now - rpt[0] > self.recorded_max_age_s:
            return None
        return rpt[1], samples

    def burn_rate(
        self, spec: SLOSpec, window_s: float, now: Optional[float] = None
    ) -> tuple[Optional[float], float]:
        """(error_fraction / budget, samples) over the window — via the
        recorded fast path when a fresh precomputed ratio exists,
        rescanning the raw rings otherwise."""
        now = time.time() if now is None else now
        tag = (
            "fast" if window_s == spec.fast_window_s
            else "slow" if window_s == spec.window_s
            else None
        )
        frac: Optional[float] = None
        samples = 0.0
        hit = (
            self._recorded_fraction(spec, tag, now)
            if tag is not None else None
        )
        if hit is not None:
            frac, samples = hit
        else:
            frac, samples = self._error_fraction(spec, window_s, now)
        if frac is None:
            return None, samples
        return frac / spec.budget, samples

    # -- evaluation --------------------------------------------------------
    def evaluate_once(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        with self._lock:
            statuses = list(self._status.values())
        transitions: list[tuple[dict, str, str]] = []
        for st in statuses:
            spec = st.spec
            # every pair evaluates (primary first); a breach on ANY
            # complete pair trips — the 6h/3d ladder pairs read through
            # the durable disk tier when one is configured
            pair_rows: list[dict] = []
            for fast_w, slow_w, thr in spec.burn_pairs:
                p_fast, p_n = self.burn_rate(spec, fast_w, now)
                p_slow, _ = self.burn_rate(spec, slow_w, now)
                pair_rows.append({
                    "fast_window_s": fast_w, "window_s": slow_w,
                    "burn_threshold": thr,
                    "fast_burn": (
                        None if p_fast is None else round(p_fast, 4)
                    ),
                    "slow_burn": (
                        None if p_slow is None else round(p_slow, 4)
                    ),
                    "fast_samples": p_n,
                })
            fast = pair_rows[0]["fast_burn"]
            slow = pair_rows[0]["slow_burn"]
            fast_n = pair_rows[0]["fast_samples"]
            complete = [
                r for r in pair_rows
                if r["fast_burn"] is not None
                and r["slow_burn"] is not None
            ]
            with self._lock:
                st.fast_burn, st.slow_burn = fast, slow
                st.fast_samples = fast_n
                st.pair_burns = pair_rows
                st.last_eval = now
                st.history.append(
                    (round(now, 3), None if fast is None else fast)
                )
                if not complete:
                    # zero-traffic window: hold state (no flap), freeze
                    # the resolve streak — silence is not health
                    st.clear_since = None if st.state == FIRING else (
                        st.clear_since
                    )
                    self._export_locked(st)
                    continue
                breach = any(
                    r["fast_burn"] >= r["burn_threshold"]
                    and r["slow_burn"] >= r["burn_threshold"]
                    for r in complete
                )
                old_state = st.state
                self._step_locked(st, breach, now)
                self._export_locked(st)
                if st.state != old_state:
                    transitions.append((st.to_dict(), old_state, st.state))
        # notification sinks fire OUTSIDE the lock: a slow webhook must
        # not serialize alert evaluation
        if self.on_transition is not None:
            for payload, old_state, new_state in transitions:
                try:
                    self.on_transition(payload, old_state, new_state)
                except Exception:
                    log.exception("alert transition hook failed")

    def _step_locked(self, st: AlertStatus, breach: bool,
                     now: float) -> None:
        spec = st.spec

        def goto(state: str) -> None:
            st.state = state
            st.since = now
            st.transitions += 1

        if st.state in (INACTIVE, RESOLVED):
            if breach:
                st.pending_since = now
                goto(PENDING)
        elif st.state == PENDING:
            if not breach:
                goto(INACTIVE)
                st.pending_since = None
            elif now - (st.pending_since or now) >= spec.for_s:
                goto(FIRING)
                st.clear_since = None
        elif st.state == FIRING:
            if breach:
                st.clear_since = None
            else:
                if st.clear_since is None:
                    st.clear_since = now
                if now - st.clear_since >= spec.resolve_s:
                    goto(RESOLVED)
                    st.clear_since = None

    def _export_locked(self, st: AlertStatus) -> None:
        try:
            self._firing_gauge.set(
                1.0 if st.state == FIRING else 0.0, slo=st.spec.name
            )
        except Exception:
            pass

    # -- reading -----------------------------------------------------------
    def status(self, name: str) -> Optional[AlertStatus]:
        with self._lock:
            return self._status.get(name)

    def payload(self) -> dict[str, Any]:
        """The `GET /alerts` body."""
        with self._lock:
            rows = [st.to_dict() for st in self._status.values()]
        return {
            "interval_s": self.interval_s,
            "slos": rows,
            "alerts": [
                r for r in rows if r["state"] != INACTIVE
            ],
            "firing": [r["slo"] for r in rows if r["state"] == FIRING],
        }

    def history(self, name: str) -> list[tuple[float, Optional[float]]]:
        with self._lock:
            st = self._status.get(name)
            return list(st.history) if st else []

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=self.thread_name, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception:
                log.exception("SLO evaluation pass failed; will retry")
