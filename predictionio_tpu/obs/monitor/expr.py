"""Series algebra over the TSDB: a small expression parser/evaluator.

The recording rules PR 16 shipped are fixed shapes (rate / error_ratio /
quantile) — useful, but the moment an operator wants "5xx increase over
total increase, per instance" they are back to hand math over
`/debug/tsdb` JSON. This module closes that gap with a PromQL-flavored
expression language evaluated directly against the ring buffers:

- instant selectors        ``up{instance="gw-1"}``
- range functions          ``rate(http_requests_total[5m])``,
                           ``increase(x[300s])``,
                           ``quantile_over_time(0.99, p99_ms[1h])``
                           (all evaluated PER SERIES, unlike the TSDB's
                           summing convenience methods)
- aggregation              ``sum by (instance) (...)``, also
                           ``mean|avg|max|min|count``, bare ``sum(...)``
- binary arithmetic        ``+ - * /`` with exact-label-set matching
                           between vectors and broadcast against scalars
- offset modifier          ``rate(http_requests_total[5m] offset 1h)``
                           shifts a selector's evaluation time into the
                           past, so binary ops can compare the same
                           window across two points in time (now vs an
                           hour ago). With a durable tier attached the
                           shifted window reads through to disk; an
                           offset range is computed from the selected
                           tier's points (reset-aware, bucket-`last`
                           granularity on downsampled tiers).

Values are *vectors* — lists of ``(labels, value)`` samples — or plain
scalars. Division by zero drops the sample (a ratio with no denominator
traffic reads as "no data", never as a spike), mirroring the recording
rules' None-on-no-traffic discipline.

Consumers: ``RecordingRule(kind="expr")``, expression-based SLO specs,
``pio tsdb query '<expr>'``, ``pio monitor --expr``, the dashboard TSDB
explorer, and ``GET /debug/tsdb?expr=``. Stdlib-only, like everything
under obs/monitor — data-plane processes never pay a jax import here.
"""

from __future__ import annotations

import functools
import re
import time
from typing import Any, Optional, Union

from predictionio_tpu.obs.monitor.tsdb import (
    TSDB,
    LabelPairs,
    increase_of,
    quantile_of,
)

__all__ = [
    "ExprError",
    "parse",
    "evaluate",
    "evaluate_rows",
    "DEFAULT_WINDOW_S",
]

DEFAULT_WINDOW_S = 300.0

#: aggregation operators usable as ``<agg> [by (l1, ...)] (expr)``
AGG_OPS = ("sum", "mean", "avg", "max", "min", "count")

#: range functions usable as ``<fn>(selector[window])``
RANGE_FNS = ("rate", "increase", "quantile_over_time")

# result model: a scalar float, or a vector of (label-pairs, value)
Vector = list[tuple[LabelPairs, float]]
Value = Union[float, Vector]


class ExprError(ValueError):
    """Raised on syntax or type errors in a series expression."""


# -- tokenizer ---------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<num>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
  | (?P<ident>[A-Za-z_:][A-Za-z0-9_:.]*)
  | (?P<str>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<op>[+\-*/(){}\[\],=])
  | (?P<ws>\s+)
    """,
    re.VERBOSE,
)

_DURATION_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m|h|d)?$")
_DURATION_UNITS = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0,
                   "d": 86400.0, None: 1.0}


def _tokenize(text: str) -> list[tuple[str, str]]:
    tokens: list[tuple[str, str]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise ExprError(
                f"unexpected character {text[pos]!r} at position {pos}"
            )
        pos = m.end()
        kind = m.lastgroup or ""
        if kind == "ws":
            continue
        tokens.append((kind, m.group()))
    return tokens


# -- AST ---------------------------------------------------------------------


class _Node:
    def eval(self, ctx: "_Ctx") -> Optional[Value]:
        raise NotImplementedError


class _Number(_Node):
    def __init__(self, value: float):
        self.value = value

    def eval(self, ctx: "_Ctx") -> Optional[Value]:
        return self.value


class _Selector(_Node):
    """``name{k="v",...}`` with an optional ``[window]`` range suffix
    and an optional ``offset <duration>`` modifier (ISSUE 18)."""

    def __init__(self, name: str, match: dict[str, str],
                 window_s: Optional[float], offset_s: float = 0.0):
        self.name = name
        self.match = match
        self.window_s = window_s
        self.offset_s = offset_s

    def eval(self, ctx: "_Ctx") -> Optional[Value]:
        if self.window_s is not None:
            raise ExprError(
                f"range selector {self.name}[...] needs a function "
                f"(rate/increase/quantile_over_time) around it"
            )
        out: Vector = []
        for s in ctx.tsdb.matching(self.name, self.match or None):
            if self.offset_s <= 0:
                pts = ctx.tsdb.points(s)
                if pts:
                    out.append((s.labels, pts[-1][1]))
                continue
            # shifted instant: the last sample at or before now-offset,
            # looked up within one default window of it
            shifted = ctx.now - self.offset_s
            pts = [
                (t, v) for t, v in ctx.tsdb.points(
                    s, ctx.default_window_s + self.offset_s, ctx.now
                ) if t <= shifted
            ]
            if pts:
                out.append((s.labels, pts[-1][1]))
        return out


def _offset_window(ctx: "_Ctx", s: Any, window_s: float,
                   offset_s: float) -> tuple[Optional[tuple[float, float]],
                                             list[tuple[float, float]]]:
    """(baseline, points) for the shifted window
    [now-offset-window, now-offset]: the in-window samples plus the
    last sample before the window (searched one extra window back) —
    the reset-aware seed `series_increase` would use."""
    shifted = ctx.now - offset_s
    cutoff = shifted - window_s
    pts = ctx.tsdb.points(s, 2.0 * window_s + offset_s, ctx.now)
    windowed = [(t, v) for t, v in pts if cutoff <= t <= shifted]
    baseline = None
    for t, v in pts:
        if t < cutoff:
            baseline = (t, v)
        else:
            break
    return baseline, windowed


class _RangeFn(_Node):
    def __init__(self, fn: str, sel: _Selector, q: Optional[float]):
        self.fn = fn
        self.sel = sel
        self.q = q

    def eval(self, ctx: "_Ctx") -> Optional[Value]:
        window = self.sel.window_s or ctx.default_window_s
        offset = self.sel.offset_s
        out: Vector = []
        for s in ctx.tsdb.matching(self.sel.name, self.sel.match or None):
            if self.fn == "quantile_over_time":
                if offset > 0:
                    _base, win = _offset_window(ctx, s, window, offset)
                    vals = [v for _t, v in win]
                else:
                    vals = [
                        v for _t, v in ctx.tsdb.points(s, window, ctx.now)
                    ]
                qv = quantile_of(vals, self.q if self.q is not None else 0.99)
                if qv is not None:
                    out.append((s.labels, qv))
                continue
            if offset > 0:
                base, win = _offset_window(ctx, s, window, offset)
                inc = increase_of(([base] if base is not None else []) + win)
            else:
                inc = ctx.tsdb.series_increase(s, window, ctx.now)
            if self.fn == "rate":
                inc = inc / window if window > 0 else 0.0
            out.append((s.labels, inc))
        return out


class _Agg(_Node):
    def __init__(self, op: str, by: tuple[str, ...], arg: _Node):
        self.op = op
        self.by = by
        self.arg = arg

    def eval(self, ctx: "_Ctx") -> Optional[Value]:
        val = self.arg.eval(ctx)
        if val is None:
            return None
        if isinstance(val, float):
            val = [((), val)]
        groups: dict[LabelPairs, list[float]] = {}
        for labels, v in val:
            ld = dict(labels)
            key: LabelPairs = tuple(
                (name, ld.get(name, "")) for name in self.by
            )
            groups.setdefault(key, []).append(v)
        out: Vector = []
        for key, vs in groups.items():
            if self.op == "sum":
                agg = sum(vs)
            elif self.op in ("mean", "avg"):
                agg = sum(vs) / len(vs)
            elif self.op == "max":
                agg = max(vs)
            elif self.op == "min":
                agg = min(vs)
            else:  # count
                agg = float(len(vs))
            out.append((key, agg))
        if not self.by:
            # bare sum(...) collapses to a scalar-like single sample
            return out[0][1] if out else []
        return out


class _BinOp(_Node):
    def __init__(self, op: str, lhs: _Node, rhs: _Node):
        self.op = op
        self.lhs = lhs
        self.rhs = rhs

    def _apply(self, a: float, b: float) -> Optional[float]:
        if self.op == "+":
            return a + b
        if self.op == "-":
            return a - b
        if self.op == "*":
            return a * b
        if b == 0:
            return None  # dropped: no-denominator reads as no-data
        return a / b

    def eval(self, ctx: "_Ctx") -> Optional[Value]:
        lv = self.lhs.eval(ctx)
        rv = self.rhs.eval(ctx)
        if lv is None or rv is None:
            return None
        if isinstance(lv, float) and isinstance(rv, float):
            return self._apply(lv, rv)
        if isinstance(lv, float):
            assert isinstance(rv, list)
            out = [
                (labels, r) for labels, v in rv
                if (r := self._apply(lv, v)) is not None
            ]
            return out
        if isinstance(rv, float):
            out = [
                (labels, r) for labels, v in lv
                if (r := self._apply(v, rv)) is not None
            ]
            return out
        # vector ∘ vector: one-to-one on the exact label set — aggregate
        # both sides with the same `by (...)` clause to line them up
        rhs_by_labels = dict(rv)
        out = []
        for labels, v in lv:
            other = rhs_by_labels.get(labels)
            if other is None:
                continue
            r = self._apply(v, other)
            if r is not None:
                out.append((labels, r))
        return out


class _Neg(_Node):
    def __init__(self, arg: _Node):
        self.arg = arg

    def eval(self, ctx: "_Ctx") -> Optional[Value]:
        val = self.arg.eval(ctx)
        if val is None:
            return None
        if isinstance(val, float):
            return -val
        return [(labels, -v) for labels, v in val]


class _Ctx:
    __slots__ = ("tsdb", "now", "default_window_s")

    def __init__(self, tsdb: TSDB, now: float, default_window_s: float):
        self.tsdb = tsdb
        self.now = now
        self.default_window_s = default_window_s


# -- parser ------------------------------------------------------------------


class _Parser:
    def __init__(self, text: str):
        self.text = text
        self.tokens = _tokenize(text)
        self.pos = 0

    def _peek(self) -> Optional[tuple[str, str]]:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def _next(self) -> tuple[str, str]:
        tok = self._peek()
        if tok is None:
            raise ExprError(f"unexpected end of expression: {self.text!r}")
        self.pos += 1
        return tok

    def _expect(self, value: str) -> None:
        tok = self._next()
        if tok[1] != value:
            raise ExprError(
                f"expected {value!r}, got {tok[1]!r} in {self.text!r}"
            )

    def parse(self) -> _Node:
        node = self._additive()
        if self._peek() is not None:
            raise ExprError(
                f"trailing input after expression: {self._peek()[1]!r}"
            )
        return node

    def _additive(self) -> _Node:
        node = self._multiplicative()
        while (tok := self._peek()) is not None and tok[1] in ("+", "-"):
            self._next()
            node = _BinOp(tok[1], node, self._multiplicative())
        return node

    def _multiplicative(self) -> _Node:
        node = self._unary()
        while (tok := self._peek()) is not None and tok[1] in ("*", "/"):
            self._next()
            node = _BinOp(tok[1], node, self._unary())
        return node

    def _unary(self) -> _Node:
        tok = self._peek()
        if tok is not None and tok[1] == "-":
            self._next()
            return _Neg(self._unary())
        return self._primary()

    def _primary(self) -> _Node:
        tok = self._next()
        kind, text = tok
        if kind == "num":
            return _Number(float(text))
        if text == "(":
            node = self._additive()
            self._expect(")")
            return node
        if kind != "ident":
            raise ExprError(f"unexpected token {text!r} in {self.text!r}")
        if text in AGG_OPS:
            return self._aggregation(text)
        if text in RANGE_FNS:
            return self._range_fn(text)
        return self._selector(text)

    def _aggregation(self, op: str) -> _Node:
        by: tuple[str, ...] = ()
        tok = self._peek()
        if tok is not None and tok[1] == "by":
            self._next()
            self._expect("(")
            names: list[str] = []
            while True:
                t = self._next()
                if t[0] != "ident":
                    raise ExprError(f"bad label name {t[1]!r} in by (...)")
                names.append(t[1])
                t = self._next()
                if t[1] == ")":
                    break
                if t[1] != ",":
                    raise ExprError(
                        f"expected ',' or ')' in by (...), got {t[1]!r}"
                    )
            by = tuple(names)
        self._expect("(")
        arg = self._additive()
        self._expect(")")
        return _Agg(op, by, arg)

    def _range_fn(self, fn: str) -> _Node:
        self._expect("(")
        q: Optional[float] = None
        if fn == "quantile_over_time":
            t = self._next()
            if t[0] != "num":
                raise ExprError(
                    "quantile_over_time needs a numeric quantile first"
                )
            q = float(t[1])
            self._expect(",")
        t = self._next()
        if t[0] != "ident" or t[1] in AGG_OPS or t[1] in RANGE_FNS:
            raise ExprError(
                f"{fn}() takes a range selector like name{{...}}[5m], "
                f"got {t[1]!r}"
            )
        sel = self._selector(t[1])
        self._expect(")")
        return _RangeFn(fn, sel, q)

    def _selector(self, name: str) -> _Selector:
        match: dict[str, str] = {}
        tok = self._peek()
        if tok is not None and tok[1] == "{":
            self._next()
            while True:
                t = self._next()
                if t[1] == "}":
                    break
                if t[0] != "ident":
                    raise ExprError(
                        f"bad label matcher near {t[1]!r} in {name}{{...}}"
                    )
                label = t[1]
                self._expect("=")
                vt = self._next()
                if vt[0] != "str":
                    raise ExprError(
                        f'label {label!r} needs a quoted value '
                        f'({label}="...")'
                    )
                raw = vt[1][1:-1]
                match[label] = re.sub(r"\\(.)", r"\1", raw)
                t = self._peek()
                if t is not None and t[1] == ",":
                    self._next()
        window_s: Optional[float] = None
        tok = self._peek()
        if tok is not None and tok[1] == "[":
            self._next()
            parts: list[str] = []
            while (t := self._next())[1] != "]":
                parts.append(t[1])
            window_s = _parse_duration("".join(parts))
        offset_s = 0.0
        tok = self._peek()
        if tok is not None and tok == ("ident", "offset"):
            self._next()
            t = self._next()
            if t[0] != "num":
                raise ExprError(
                    f"offset needs a duration (e.g. offset 1h), got "
                    f"{t[1]!r}"
                )
            dur = t[1]
            unit = self._peek()
            if unit is not None and unit[0] == "ident" \
                    and unit[1] in _DURATION_UNITS:
                self._next()
                dur += unit[1]
            offset_s = _parse_duration(dur)
            if offset_s < 0:
                raise ExprError("offset must be >= 0")
        return _Selector(name, match, window_s, offset_s)


def _parse_duration(text: str) -> float:
    m = _DURATION_RE.match(text.strip())
    if m is None:
        raise ExprError(f"bad duration {text!r} (want e.g. 300s, 5m, 1h)")
    return float(m.group(1)) * _DURATION_UNITS[m.group(2)]


@functools.lru_cache(maxsize=256)
def parse(text: str) -> _Node:
    """Parse an expression to its AST (cached — rules re-evaluate the
    same text every sampler tick). Raises :class:`ExprError`."""
    if not text or not text.strip():
        raise ExprError("empty expression")
    return _Parser(text.strip()).parse()


def evaluate(tsdb: TSDB, text: str, now: Optional[float] = None,
             default_window_s: float = DEFAULT_WINDOW_S) -> Optional[Value]:
    """Evaluate `text` against `tsdb` at `now`. Returns a scalar float,
    a vector ``[(label_pairs, value), ...]``, or None (no data)."""
    node = parse(text)
    ctx = _Ctx(tsdb, time.time() if now is None else now,
               default_window_s)
    return node.eval(ctx)


def evaluate_rows(tsdb: TSDB, text: str, now: Optional[float] = None,
                  default_window_s: float = DEFAULT_WINDOW_S
                  ) -> list[dict[str, Any]]:
    """JSON-able evaluation: ``[{"labels": {...}, "value": v}, ...]``
    (a scalar result is one row with empty labels). This is the shape
    `GET /debug/tsdb?expr=`, `pio tsdb query` and the dashboard render."""
    val = evaluate(tsdb, text, now, default_window_s)
    if val is None:
        return []
    if isinstance(val, float):
        return [{"labels": {}, "value": val}]
    rows = [
        {"labels": dict(labels), "value": v} for labels, v in val
    ]
    rows.sort(key=lambda r: sorted(r["labels"].items()))
    return rows
