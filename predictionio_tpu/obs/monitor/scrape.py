"""Fleet-wide scrape aggregation: many servers' /metrics → one TSDB.

The reference dashboard shows one server's point-in-time state; a real
deployment runs an event server, several query servers, a storage
daemon, admin and dashboard — and "what does the fleet look like" has
no answer without aggregating them. The :class:`FleetScraper` polls a
configured target list's `/metrics` (the Prometheus text exposition
the registry already emits), tags every parsed series with an
``instance`` label, and feeds the SAME in-process TSDB the local
sampler uses — so the dashboard (or a standalone ``pio monitor``
process) sees the whole deployment through one query API.

Per-target meta-series make a dead server itself an alertable signal:

- ``up{instance=}``            1 scrape ok / 0 unreachable
- ``scrape_duration_seconds{instance=}``  scrape wall time

Targets parse from ``PIO_MONITOR_TARGETS`` (or a CLI/constructor arg):
``instance=url`` pairs, comma-separated —
``query=http://host:8000,event=http://host:7070``. A bare url gets its
``host:port`` as the instance name.

With a durable tier attached (``PIO_TSDB_DIR``, ISSUE 18) scraped
series write through :class:`~.durable.DurableTSDB` like every other
writer — fleet history, including ``up``, survives a monitor restart
and ages through the 5m/1h downsampled tiers, so multi-window
burn-rate SLOs over scraped fleet metrics keep working across
restarts with no scraper-side changes.
"""

from __future__ import annotations

import logging
import threading
import time
import urllib.request
from typing import Optional
from urllib.parse import urlsplit

from predictionio_tpu.obs.monitor.tsdb import TSDB

log = logging.getLogger(__name__)


def parse_targets(text: str) -> list[tuple[str, str]]:
    """``name=url,name=url`` (or bare urls) → [(instance, base_url)]."""
    out: list[tuple[str, str]] = []
    for part in (text or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part.split("://", 1)[0]:
            name, _, url = part.partition("=")
            name = name.strip()
        else:
            name, url = "", part
        url = url.strip().rstrip("/")
        if not name:
            name = urlsplit(url).netloc or url
        out.append((name, url))
    return out


def parse_exemplar_lines(
    text: str,
) -> list[tuple[str, str, float, float, dict]]:
    """Parse the registry's ``# EXEMPLAR <family> <trace_id> <value>
    <ts> [<labels-json>]`` comment lines →
    [(family, trace_id, value, ts, labels)]. The trailing compact-JSON
    token is the observing label set (ISSUE 17 per-route indexing);
    legacy 6-token lines parse with empty labels. JSON label values may
    contain spaces, so the line is split at most 6 times and the
    remainder JSON-decoded. Plain Prometheus parsers skip all of it as
    comments; the fleet scraper feeds these into the Monitor's exemplar
    index so a firing alert can link straight to the slowest traces
    anywhere in the fleet."""
    import json as _json

    out: list[tuple[str, str, float, float, dict]] = []
    for line in text.splitlines():
        parts = line.strip().split(None, 6)
        if len(parts) < 6 or parts[0] != "#" or parts[1] != "EXEMPLAR":
            continue
        labels: dict = {}
        if len(parts) == 7:
            try:
                decoded = _json.loads(parts[6])
                if isinstance(decoded, dict):
                    labels = {str(k): str(v) for k, v in decoded.items()}
            except ValueError:
                continue
        try:
            out.append(
                (parts[2], parts[3], float(parts[4]), float(parts[5]),
                 labels)
            )
        except ValueError:
            continue
    return out


def parse_prometheus_text(text: str) -> list[tuple[str, dict, float]]:
    """Parse exposition-format samples → [(name, labels, value)].

    Handles exactly what `obs.registry.render_families` emits (v0.0.4
    text: HELP/TYPE comments, ``name{k="v",...} value`` lines with
    backslash-escaped label values). Unparseable lines are skipped —
    a half-broken peer must not kill the scrape pass."""
    samples: list[tuple[str, dict, float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                labels_s, _, value_s = rest.rpartition("}")
                labels = _parse_labels(labels_s)
            else:
                name, _, value_s = line.rpartition(" ")
                labels = {}
            value_s = value_s.strip()
            value = float(
                "inf" if value_s == "+Inf"
                else "-inf" if value_s == "-Inf" else value_s
            )
            samples.append((name.strip(), labels, value))
        except (ValueError, IndexError):
            continue
    return samples


def _parse_labels(s: str) -> dict[str, str]:
    labels: dict[str, str] = {}
    i = 0
    n = len(s)
    while i < n:
        eq = s.index("=", i)
        key = s[i:eq].strip().lstrip(",").strip()
        # value is a double-quoted string with \\ \" \n escapes
        j = s.index('"', eq) + 1
        buf: list[str] = []
        while j < n:
            ch = s[j]
            if ch == "\\" and j + 1 < n:
                nxt = s[j + 1]
                buf.append("\n" if nxt == "n" else nxt)
                j += 2
                continue
            if ch == '"':
                break
            buf.append(ch)
            j += 1
        labels[key] = "".join(buf)
        i = j + 1
    return labels


class FleetScraper:
    """Background scrape loop over a fixed target list, feeding `tsdb`.
    `stop()` joins the thread (the no-leaked-threads contract)."""

    thread_name = "fleet-scraper"

    def __init__(self, tsdb: TSDB, targets: list[tuple[str, str]],
                 interval_s: float = 10.0, timeout_s: float = 5.0,
                 backoff_max_s: Optional[float] = None):
        from predictionio_tpu.utils.env import env_float

        self.tsdb = tsdb
        self.targets = list(targets)
        self.interval_s = max(0.05, float(interval_s))
        self.timeout_s = float(timeout_s)
        # ISSUE 17 satellite: a down target is NOT re-polled every
        # interval — each consecutive failure doubles the wait (capped),
        # so a dead replica doesn't eat a connect timeout per tick. The
        # up{instance}=0 point still lands every logical tick below, so
        # alerting freshness is unaffected by the backoff.
        self.backoff_max_s = float(
            backoff_max_s if backoff_max_s is not None
            else env_float("PIO_SCRAPE_BACKOFF_MAX_S")
        )
        self._fails: dict[str, int] = {}       # consecutive failures
        self._not_before: dict[str, float] = {}  # next attempt (epoch s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def backoff_remaining(self, instance: str,
                          now: Optional[float] = None) -> float:
        """Seconds until the next real attempt at `instance` (0 when it
        is due — or healthy)."""
        now = time.time() if now is None else now
        return max(0.0, self._not_before.get(instance, 0.0) - now)

    # -- one pass ----------------------------------------------------------
    def scrape_once(self, now: Optional[float] = None) -> dict[str, bool]:
        """Scrape every target once; returns {instance: up}. Targets
        inside their failure backoff are skipped (no HTTP), but still
        write up=0 for the tick."""
        results: dict[str, bool] = {}
        for instance, base in self.targets:
            now_t = time.time() if now is None else now
            if now_t < self._not_before.get(instance, 0.0):
                self.tsdb.add(
                    "up", {"instance": instance}, 0.0, "gauge", now_t,
                )
                results[instance] = False
                continue
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(
                    base + "/metrics", timeout=self.timeout_s
                ) as r:
                    body = r.read().decode(errors="replace")
                up = True
            except Exception as e:
                body = ""
                up = False
                log.debug("scrape of %s (%s) failed: %s", instance, base, e)
            dur = time.perf_counter() - t0
            if up:
                self._fails.pop(instance, None)
                self._not_before.pop(instance, None)
            else:
                n = self._fails.get(instance, 0) + 1
                self._fails[instance] = n
                self._not_before[instance] = now_t + min(
                    self.interval_s * (2.0 ** n), self.backoff_max_s
                )
            self.tsdb.add(
                "up", {"instance": instance}, 1.0 if up else 0.0,
                "gauge", now_t,
            )
            self.tsdb.add(
                "scrape_duration_seconds", {"instance": instance}, dur,
                "gauge", now_t,
            )
            if up:
                written = 0
                for name, labels, value in parse_prometheus_text(body):
                    kind = (
                        "counter" if name.endswith(
                            ("_total", "_count", "_sum", "_bucket")
                        ) else "gauge"
                    )
                    if self.tsdb.add(
                        name, {**labels, "instance": instance}, value,
                        kind, now_t,
                    ):
                        written += 1
                self.tsdb.add(
                    "scrape_samples_stored", {"instance": instance},
                    written, "gauge", now_t,
                )
                self._index_exemplars(body)
            results[instance] = up
        return results

    def _index_exemplars(self, body: str) -> None:
        """Feed scraped `# EXEMPLAR` lines to the process monitor's
        index (late import: obs.monitor imports this module)."""
        try:
            from predictionio_tpu.obs.monitor import get_monitor

            note = get_monitor().note_exemplar
            for family, tid, value, ts, labels in parse_exemplar_lines(
                body
            ):
                note(family, tid, value, ts, labels=labels)
        except Exception:
            log.debug("exemplar indexing failed", exc_info=True)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=self.thread_name, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s + 5)
            self._thread = None

    def _loop(self) -> None:
        while True:
            try:
                self.scrape_once()
            except Exception:
                log.exception("fleet scrape pass failed; will retry")
            if self._stop.wait(self.interval_s):
                return

    def status(self) -> list[dict]:
        """Per-target latest up/latency, read back off the TSDB (one
        source of truth for the dashboard panel and `pio monitor`)."""
        out = []
        for instance, base in self.targets:
            match = {"instance": instance}
            up = self.tsdb.latest("up", match)
            dur = self.tsdb.latest("scrape_duration_seconds", match)
            row = {
                "instance": instance,
                "url": base,
                "up": None if up is None else bool(up),
                "scrape_seconds": dur,
            }
            backoff = self.backoff_remaining(instance)
            if backoff > 0:
                row["backoff_s"] = round(backoff, 1)
            out.append(row)
        return out
