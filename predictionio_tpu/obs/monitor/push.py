"""Push telemetry for ephemeral processes (ISSUE 17 tentpole, part 1).

The PR-16 observability plane only *polls*: the FleetScraper hits
`/metrics`, the TraceCollector hits `/debug/traces`. A train worker that
lives for eight seconds — or a drained gateway replica, or a CAS fleet
worker — usually dies between polls, taking its devprof MFU numbers,
`train.*` spans, and final counters with it. This module is the push
half:

- :class:`TelemetryShipper` — embedded in the ephemeral process. Every
  ``interval_s`` it snapshots the process's metric families, recent
  spans, and (if available) the devprof report into a **local fsync'd
  spool file**, then ships every spooled file to the configured ingest
  URL (``POST /telemetry/push``) with `resilience.retry` backoff inside
  a wall-clock deadline. ``stop()`` (wired to atexit and the owner's
  finally) spools+ships one last time, so a clean exit loses nothing;
  the periodic spool means even a ``kill -9`` leaves a durable spool
  directory behind for the supervisor to ship (:func:`ship_spool` —
  the TrainScheduler calls it over orphaned ``<job>.spool`` dirs).
- :func:`ingest` — the server side: tag every pushed series with
  ``instance``/``job_id``, write them into the monitor TSDB at their
  *sampled* timestamps (the TSDB's ordered insert keeps late backfill
  correct), hand span batches to the TraceCollector, stash the devprof
  report, and refresh ``telemetry_last_push_age_seconds{instance}`` —
  the pushgateway-style freshness series that makes a silent worker
  alertable, symmetric with ``up{instance}``.

Stdlib-only on import, like all of obs/monitor: the processes that
embed the shipper are exactly the ones that must not pay a jax import
for telemetry.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import logging
import os
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Optional

from predictionio_tpu.obs import spans as _spans
from predictionio_tpu.obs.monitor.tsdb import TSDB, sample_families
from predictionio_tpu.resilience.retry import RetryPolicy
from predictionio_tpu.utils.env import (
    env_float,
    env_int,
    env_path,
    env_str,
)

log = logging.getLogger(__name__)

__all__ = [
    "PushAuthError",
    "PushError",
    "TelemetryShipper",
    "build_payload",
    "ingest",
    "issue_push_token",
    "ship_spool",
    "spool_payload",
    "verify_push_token",
]

PAYLOAD_VERSION = 1

#: the ingest route, relative to the push base URL
PUSH_ROUTE = "/telemetry/push"

#: header carrying the per-instance push token (ISSUE 18)
TOKEN_HEADER = "X-PIO-Push-Token"


class PushError(ValueError):
    """A malformed push payload (ingest side → HTTP 400)."""


class PushAuthError(PushError):
    """A missing/invalid push token (ingest side → HTTP 403)."""


# -- per-instance push auth (ISSUE 18) ---------------------------------------
#
# PIO_PUSH_TOKEN is a shared secret between the receiver and the
# processes allowed to push. The wire token is HMAC-SHA256(secret,
# instance) — bound to the payload's `instance` label, so a token
# captured from instance A cannot be replayed to write series labeled
# instance B, and a sender without the secret cannot fabricate series
# at all. The TrainScheduler passes the secret to its workers via the
# injected child env; the shipper derives the wire token itself.


def issue_push_token(instance: str, secret: str) -> str:
    """The wire token authorizing pushes labeled `instance`."""
    return hmac.new(
        secret.encode(), str(instance).encode(), hashlib.sha256
    ).hexdigest()


def verify_push_token(instance: str, token: Optional[str],
                      secret: str) -> bool:
    """Constant-time check of a presented wire token."""
    if not token:
        return False
    return hmac.compare_digest(
        issue_push_token(instance, secret), str(token)
    )


# -- payload construction (the ephemeral process side) -----------------------


def build_payload(
    instance: str,
    job_id: Optional[str] = None,
    registries: Optional[list] = None,
    recorder: Optional[_spans.SpanRecorder] = None,
    span_since: float = 0.0,
    now: Optional[float] = None,
    include_devprof: bool = True,
) -> dict:
    """One self-contained push payload: a point-in-time snapshot of the
    given registries' families (default registry included), spans ended
    since `span_since`, and the devprof report when one exists."""
    from predictionio_tpu.obs.registry import get_default_registry

    now = time.time() if now is None else now
    seen: set[int] = set()
    families = []
    for reg in list(registries or []) + [get_default_registry()]:
        for fam in reg.families():
            if id(fam) not in seen:
                seen.add(id(fam))
                families.append(fam)
    # reuse the sampler's exact flattening (histograms → _count/_sum/
    # _bucket/quantile gauges, first-writer dedup) via a throwaway TSDB
    tmp = TSDB(capacity=2, max_series=1 << 17)
    sample_families(tmp, families, now=now)
    series = []
    with tmp._lock:
        for s in tmp._series.values():
            if s.points:
                series.append({
                    "name": s.name,
                    "labels": s.labels_dict(),
                    "value": s.points[-1][1],
                    "kind": s.kind,
                })
    recorder = recorder if recorder is not None else (
        _spans.get_default_recorder()
    )
    spans = [sp.to_dict() for sp in recorder.recent(since=span_since)]
    payload: dict[str, Any] = {
        "v": PAYLOAD_VERSION,
        "instance": instance,
        "sampled_at": round(now, 3),
        "series": series,
        "spans": spans,
    }
    if job_id:
        payload["job_id"] = str(job_id)
    if include_devprof:
        try:
            from predictionio_tpu.obs import devprof as _devprof

            report = _devprof.report()
            if report.get("executables"):
                payload["devprof"] = report
        except Exception:
            pass  # profiling is best-effort; the payload stays valid
    return payload


def spool_payload(spool_dir: str, payload: dict, seq: int = 0) -> str:
    """Write one payload to the spool, durably: tmp + fsync + atomic
    rename + directory fsync. Filenames sort in ship order."""
    os.makedirs(spool_dir, exist_ok=True)
    name = f"{int(payload.get('sampled_at', time.time()) * 1000):015d}" \
           f"-{os.getpid()}-{seq:04d}.json"
    path = os.path.join(spool_dir, name)
    tmp = path + ".tmp"
    data = json.dumps(payload, separators=(",", ":")).encode()
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        dfd = os.open(spool_dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # platform without dir fsync: rename durability is best-effort
    return path


def _spool_files(spool_dir: str) -> list[str]:
    try:
        names = os.listdir(spool_dir)
    except OSError:
        return []
    return sorted(
        os.path.join(spool_dir, n) for n in names
        if n.endswith(".json") and not n.endswith(".tmp")
    )


def trim_spool(spool_dir: str, max_bytes: int) -> int:
    """Drop oldest spool files until the directory fits `max_bytes`
    (the shipper calls this after each spool write). Returns dropped."""
    files = _spool_files(spool_dir)
    sizes = {}
    for p in files:
        try:
            sizes[p] = os.path.getsize(p)
        except OSError:
            sizes[p] = 0
    total = sum(sizes.values())
    dropped = 0
    for p in files:
        if total <= max_bytes:
            break
        try:
            os.unlink(p)
        except OSError:
            pass
        total -= sizes[p]
        dropped += 1
    return dropped


def _post(url: str, data: bytes, timeout_s: float,
          headers: Optional[dict[str, str]] = None) -> None:
    req = urllib.request.Request(
        url, data=data, method="POST",
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        r.read()


def ship_spool(
    spool_dir: str,
    url: str,
    deadline_s: float = 5.0,
    timeout_s: float = 3.0,
    retry: Optional[RetryPolicy] = None,
) -> int:
    """Ship every spooled payload to `url` + /telemetry/push, oldest
    first, with retry/backoff inside one wall-clock `deadline_s` budget
    for the whole pass. Shipped files are unlinked; files that could
    not be shipped stay spooled for the next pass (or for the
    supervisor's orphan sweep). Returns files shipped."""
    url = (url or "").rstrip("/")
    if not url or not spool_dir:
        return 0
    # PIO_PUSH_URL is documented as the receiver's BASE url, but a full
    # endpoint url must not double the route
    endpoint = url if url.endswith(PUSH_ROUTE) else url + PUSH_ROUTE
    retry = retry or RetryPolicy(max_attempts=4, base_delay=0.05)
    deadline = time.monotonic() + max(0.1, float(deadline_s))
    secret = env_str("PIO_PUSH_TOKEN")
    shipped = 0
    for path in _spool_files(spool_dir):
        try:
            with open(path, "rb") as f:
                data = f.read()
            # poison guard: never retry an unparsable file (and the
            # orphan sweep ships spools from MANY instances — the
            # token must be derived per file, from its own label)
            parsed = json.loads(data)
        except (OSError, ValueError):
            try:
                os.unlink(path)
            except OSError:
                pass
            continue
        headers = None
        if secret and isinstance(parsed, dict):
            headers = {TOKEN_HEADER: issue_push_token(
                str(parsed.get("instance") or "") or "(unknown)", secret
            )}
        if time.monotonic() >= deadline:
            break
        try:
            retry.call(
                lambda _a: _post(endpoint, data, timeout_s, headers),
                retry_on=(OSError, urllib.error.URLError),
                deadline=deadline,
            )
        except Exception as e:
            log.debug("telemetry ship of %s to %s failed: %s",
                      path, endpoint, e)
            break  # keep this and newer files spooled; order preserved
        try:
            os.unlink(path)
        except OSError:
            pass
        shipped += 1
    return shipped


class TelemetryShipper:
    """Spool-then-ship telemetry out of an ephemeral process.

    ``start()`` runs the spool+ship loop on a background thread (named
    ``telemetry-shipper``; ``stop()`` joins it and flushes one final
    snapshot — the atexit/finally path). A process that never reaches
    ``stop()`` (kill -9, OOM) still leaves its periodic spool files for
    :func:`ship_spool` from the supervisor."""

    thread_name = "telemetry-shipper"

    def __init__(
        self,
        spool_dir: str,
        url: str = "",
        instance: Optional[str] = None,
        job_id: Optional[str] = None,
        interval_s: Optional[float] = None,
        deadline_s: Optional[float] = None,
        timeout_s: float = 3.0,
        spool_max_bytes: Optional[int] = None,
        registries: Optional[list] = None,
        recorder: Optional[_spans.SpanRecorder] = None,
    ):
        if not spool_dir:
            raise ValueError("TelemetryShipper needs a spool directory")
        self.spool_dir = spool_dir
        self.url = (url or "").rstrip("/")
        self.instance = instance or (
            f"{socket.gethostname()}:{os.getpid()}"
        )
        self.job_id = job_id
        self.interval_s = max(0.05, float(
            interval_s if interval_s is not None
            else env_float("PIO_PUSH_INTERVAL_S")
        ))
        self.deadline_s = float(
            deadline_s if deadline_s is not None
            else env_float("PIO_PUSH_DEADLINE_S")
        )
        self.timeout_s = float(timeout_s)
        self.spool_max_bytes = int(
            spool_max_bytes if spool_max_bytes is not None
            else env_int("PIO_PUSH_SPOOL_MAX_BYTES")
        )
        self.registries = list(registries or [])
        self.recorder = recorder
        self.spooled = 0
        self.shipped = 0
        self._span_cursor = 0.0
        self._seq = 0
        self._flush_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def from_env(
        cls,
        instance: Optional[str] = None,
        job_id: Optional[str] = None,
        registries: Optional[list] = None,
    ) -> Optional["TelemetryShipper"]:
        """Build from PIO_PUSH_* knobs; None when pushing is not
        configured (no URL and no spool) — the caller just skips it."""
        url = env_str("PIO_PUSH_URL")
        spool = env_path("PIO_PUSH_SPOOL")
        if not url and not spool:
            return None
        if not spool:
            import tempfile

            spool = os.path.join(
                tempfile.gettempdir(), f"pio-push-{os.getpid()}"
            )
        return cls(
            spool, url=url, instance=instance, job_id=job_id,
            registries=registries,
        )

    # -- one pass ----------------------------------------------------------
    def spool_once(self, now: Optional[float] = None) -> Optional[str]:
        """Snapshot → durable spool file (never raises; None on error)."""
        now = time.time() if now is None else now
        try:
            payload = build_payload(
                self.instance, job_id=self.job_id,
                registries=self.registries, recorder=self.recorder,
                span_since=self._span_cursor, now=now,
            )
            # one interval of span overlap; the collector's span_id
            # dedup makes the overlap free and clock skew harmless
            self._span_cursor = max(0.0, now - self.interval_s)
            self._seq += 1
            path = spool_payload(self.spool_dir, payload, self._seq)
            self.spooled += 1
            trim_spool(self.spool_dir, self.spool_max_bytes)
            return path
        except Exception:
            log.debug("telemetry spool failed", exc_info=True)
            return None

    def ship(self, deadline_s: Optional[float] = None) -> int:
        n = ship_spool(
            self.spool_dir, self.url,
            deadline_s if deadline_s is not None else self.deadline_s,
            self.timeout_s,
        )
        self.shipped += n
        return n

    def flush(self) -> int:
        """Spool a final snapshot and ship everything pending — the
        clean-exit path (atexit / the owner's finally). Reentrant and
        safe to call multiple times."""
        with self._flush_lock:
            self.spool_once()
            return self.ship()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=self.thread_name, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        """Join the loop and run the final flush. Idempotent."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.deadline_s + 10)
            self._thread = None
        self.flush()

    def _loop(self) -> None:
        while True:
            try:
                with self._flush_lock:
                    self.spool_once()
                    self.ship()
            except Exception:
                log.debug("telemetry ship pass failed", exc_info=True)
            if self._stop.wait(self.interval_s):
                return


# -- the ingest side ---------------------------------------------------------


# per-instance span token buckets: instance → (tokens, last_refill_ts)
_span_buckets: dict[str, tuple[float, float]] = {}  # guarded-by: _span_lock
_span_lock = threading.Lock()
_dropped_family = None  # guarded-by: _span_lock (lazy, import-cheap)


def _dropped_counter():
    global _dropped_family
    if _dropped_family is None:
        from predictionio_tpu.obs.registry import get_default_registry

        _dropped_family = get_default_registry().counter(
            "telemetry_push_dropped_total",
            "Pushed telemetry discarded at ingest, by kind",
            ("kind",),  # label-bound: literal ingest drop kinds
        )
    return _dropped_family


def _admit_spans(instance: str, n: int, now: float) -> int:
    """Token-bucket admission for pushed spans: how many of `n` this
    instance may ingest right now (PIO_PUSH_SPAN_RATE refill/s, burst
    PIO_PUSH_SPAN_BURST). Rate <= 0 disables the limiter."""
    rate = env_float("PIO_PUSH_SPAN_RATE")
    if rate <= 0 or n <= 0:
        return n
    burst = max(1.0, env_float("PIO_PUSH_SPAN_BURST"))
    with _span_lock:
        tokens, last = _span_buckets.get(instance, (burst, now))
        tokens = min(burst, tokens + max(0.0, now - last) * rate)
        allowed = int(min(float(n), tokens))
        _span_buckets[instance] = (tokens - allowed, now)
        if len(_span_buckets) > 4096:  # shed: idle instances refill anyway
            _span_buckets.pop(next(iter(_span_buckets)))
    return allowed


def ingest(payload: Any, monitor: Any = None,
           now: Optional[float] = None,
           token: Optional[str] = None) -> dict:
    """Land one pushed payload in the process monitor: series into the
    TSDB (tagged instance/job_id, at their *sampled* timestamps), spans
    into the TraceCollector, devprof report + freshness bookkeeping
    onto the Monitor. Raises :class:`PushError` on malformed input
    (the HTTP handler maps it to 400) and :class:`PushAuthError` when
    PIO_PUSH_TOKEN is set on this receiver and `token` is not the
    HMAC for the payload's `instance` (→ 403)."""
    from predictionio_tpu.obs.monitor import get_monitor

    if not isinstance(payload, dict):
        raise PushError("push payload must be a JSON object")
    if payload.get("v") != PAYLOAD_VERSION:
        raise PushError(
            f"unknown push payload version {payload.get('v')!r}"
        )
    series = payload.get("series") or []
    spans = payload.get("spans") or []
    if not isinstance(series, list) or not isinstance(spans, list):
        raise PushError("'series' and 'spans' must be arrays")
    monitor = monitor if monitor is not None else get_monitor()
    now = time.time() if now is None else now
    instance = str(payload.get("instance") or "") or "(unknown)"
    secret = env_str("PIO_PUSH_TOKEN")
    if secret and not verify_push_token(instance, token, secret):
        raise PushAuthError(
            f"push token missing or not valid for instance "
            f"{instance!r}"
        )
    job_id = payload.get("job_id")
    extra: dict[str, str] = {"instance": instance}
    if job_id:
        extra["job_id"] = str(job_id)
    try:
        sampled_at = float(payload.get("sampled_at") or now)
    except (TypeError, ValueError):
        sampled_at = now
    # a skewed producer clock must not write points from the future
    sampled_at = min(sampled_at, now + 1.0)
    written = 0
    for row in series:
        if not isinstance(row, dict):
            continue
        name = row.get("name")
        if not name:
            continue
        try:
            value = float(row.get("value", 0.0))
        except (TypeError, ValueError):
            continue
        labels = {**(row.get("labels") or {}), **extra}
        if monitor.tsdb.add(
            str(name), labels, value,
            str(row.get("kind") or "gauge"), sampled_at,
        ):
            written += 1
    ingested = 0
    dropped_spans = 0
    collector = monitor.collector
    if collector is not None and spans:
        allowed = _admit_spans(instance, len(spans), now)
        dropped_spans = len(spans) - allowed
        if dropped_spans:
            _dropped_counter().inc(dropped_spans, kind="span")
        if allowed:
            ingested = collector.ingest_spans(spans[:allowed], now)
    devprof = payload.get("devprof")
    monitor.note_push(
        instance,
        sampled_at,
        devprof if isinstance(devprof, dict) else None,
        now=now,
    )
    return {
        "ok": True,
        "instance": instance,
        "series_written": written,
        "spans_ingested": ingested,
        "spans_dropped": dropped_spans,
    }
