"""Monitoring plane (ISSUE 8): in-process time-series history, fleet
scrape aggregation, and SLO burn-rate alerting.

The process-global :class:`Monitor` owns one TSDB and the background
threads over it. Servers attach on start and detach on stop; the
sampler (and the SLO engine, when specs are configured) runs while at
least one server is attached and **joins on the last detach** — no
leaked threads, same discipline as the dispatcher/WAL/mux threads.

Knobs (read when the monitor is created; mutable attributes after):

  PIO_TSDB=0             disable the monitoring plane wholesale
  PIO_TSDB_INTERVAL_S    sampler period           (default 5)
  PIO_TSDB_POINTS        ring capacity per series (default 720 → 1 h)
  PIO_TSDB_MAX_SERIES    series cardinality cap   (default 4096)
  PIO_SLO_INTERVAL_S     SLO evaluation period    (default 15)
  PIO_SLOS               JSON SLO spec array, or @/path.json
  PIO_MONITOR_TARGETS    fleet scrape targets (dashboard / pio monitor)
  PIO_RECORDING_RULES    derived-series recording rules (ISSUE 16)
  PIO_TENANT_SLO_PRESETS auto-derive per-tenant SLOs at mux attach
  PIO_PUSH_*             push-telemetry shipper/ingest (ISSUE 17 —
                         see obs.monitor.push)
  PIO_TSDB_DIR           durable on-disk tier: WAL + sealed blocks +
                         5m/1h downsampled tiers, replayed on start
                         (ISSUE 18 — see obs.monitor.durable/compact;
                         PIO_TSDB_{FLUSH_S,SEAL_*,COMPACT_S,
                         RETENTION_*} tune it)
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

from predictionio_tpu.obs.monitor.collector import TraceCollector
from predictionio_tpu.obs.monitor.notify import AlertNotifier
from predictionio_tpu.obs.monitor.scrape import (
    FleetScraper,
    parse_prometheus_text,
    parse_targets,
)
from predictionio_tpu.obs.monitor.slo import (
    AlertStatus,
    SLOEngine,
    SLOSpec,
    load_slos,
    record_slo_ratios,
    tenant_slo_presets,
)
from predictionio_tpu.obs.monitor.tsdb import (
    TSDB,
    MetricsSampler,
    RecordingRule,
    SnapshotWriter,
    evaluate_rules,
    load_recording_rules,
    load_snapshot,
    sample_families,
    save_snapshot,
)
from predictionio_tpu.utils.env import env_float, env_int, env_path
from predictionio_tpu.utils.env import env_bool

__all__ = [
    "TSDB",
    "AlertNotifier",
    "MetricsSampler",
    "FleetScraper",
    "RecordingRule",
    "SLOEngine",
    "SLOSpec",
    "AlertStatus",
    "Monitor",
    "SnapshotWriter",
    "TraceCollector",
    "enabled",
    "evaluate_rules",
    "get_monitor",
    "load_recording_rules",
    "load_slos",
    "load_snapshot",
    "parse_prometheus_text",
    "parse_targets",
    "record_slo_ratios",
    "sample_families",
    "save_snapshot",
    "tenant_slo_presets",
]


def enabled() -> bool:
    return env_bool("PIO_TSDB")


class Monitor:
    """One TSDB + sampler + optional SLO engine per process.

    `attach(label, registry)` refcounts: the first attach starts the
    sampler (and the SLO engine when specs exist), the last `detach`
    stops and JOINS both. Families are sampled first-wins by name in
    attach order, then the process-default registry — the exact merge
    `GET /metrics` renders, so history and scrape can't disagree."""

    def __init__(self):
        self.sampler_interval_s = env_float("PIO_TSDB_INTERVAL_S", 5.0)
        self.slo_interval_s = env_float("PIO_SLO_INTERVAL_S", 15.0)
        # durable tier (ISSUE 18): with a directory configured, the
        # rings are backed by a WAL + sealed-block disk store and the
        # constructor REPLAYS the durable tail — a restarted process
        # alerts on pre-restart burn instead of starting amnesiac. The
        # durable tier supersedes the JSON snapshot (PIO_TSDB_SNAPSHOT).
        self.durable_dir = env_path("PIO_TSDB_DIR") or None
        if self.durable_dir and enabled():
            from predictionio_tpu.obs.monitor.durable import DurableTSDB

            self.tsdb: TSDB = DurableTSDB(
                self.durable_dir,
                capacity=int(env_float("PIO_TSDB_POINTS", 720)),
                max_series=int(env_float("PIO_TSDB_MAX_SERIES", 4096)),
                flush_interval_s=env_float("PIO_TSDB_FLUSH_S", 2.0),
                seal_points=env_int("PIO_TSDB_SEAL_POINTS", 50000),
                seal_age_s=env_float("PIO_TSDB_SEAL_AGE_S", 300.0),
            )
        else:
            self.tsdb = TSDB(
                capacity=int(env_float("PIO_TSDB_POINTS", 720)),
                max_series=int(env_float("PIO_TSDB_MAX_SERIES", 4096)),
            )
        # snapshot persistence (ISSUE 15 satellite): with a path
        # configured, history survives restarts — reload here, persist
        # periodically (and on last detach) below
        self.snapshot_path = (
            None if self.durable_dir
            else env_path("PIO_TSDB_SNAPSHOT") or None
        )
        self.snapshot_interval_s = env_float(
            "PIO_TSDB_SNAPSHOT_INTERVAL_S", 60.0
        )
        if self.snapshot_path and enabled():
            restored = load_snapshot(self.tsdb, self.snapshot_path)
            if restored:
                import logging

                logging.getLogger(__name__).info(
                    "restored %d TSDB series from %s",
                    restored, self.snapshot_path,
                )
        self._lock = threading.Lock()
        self._attached: list[tuple[int, str, Any]] = []  # (token, label, reg)
        self._next_token = 1
        self._sampler: Optional[MetricsSampler] = None
        self._engine: Optional[SLOEngine] = None
        self._snapshotter: Optional[SnapshotWriter] = None
        self._compactor: Optional[Any] = None
        self._slos: list[SLOSpec] = load_slos()
        # per-tenant presets (ISSUE 16): auto-derived at mux attach,
        # kept apart from the operator's _slos — an operator spec with
        # the same name always wins in the union fed to the engine
        self._presets: list[SLOSpec] = []
        # recording rules (ISSUE 16): evaluated on the sampler tick
        # via MetricsSampler.post_sample — no extra thread
        self.recording_rules: list[RecordingRule] = load_recording_rules()
        # the fleet trace collector, when this process runs one
        # (gateways, dashboards, `pio monitor`) — registered via
        # set_collector; its lifecycle stays with its owner
        self.collector: Optional[TraceCollector] = None
        # scraped exemplar index (ISSUE 16, per-route in ISSUE 17):
        # family → observing label set → trace id → (value, ts), fed by
        # the fleet scraper's `# EXEMPLAR` lines; merged with the local
        # registries' exemplars on read. The bound is per (family,
        # label set) — a slow /metrics route can no longer evict the
        # /queries.json evidence an alert actually needs.
        self._exemplars: dict[
            str, dict[tuple, dict[str, tuple[float, float]]]
        ] = {}
        self._exemplar_cap = max(16, 4 * env_int("PIO_TRACE_EXEMPLARS"))
        # push-telemetry bookkeeping (ISSUE 17): last receipt wall time
        # and latest devprof report per pushed instance. The sampler
        # tick re-derives telemetry_last_push_age_seconds from
        # _push_last so the series AGES between pushes — a worker gone
        # silent trips a threshold alert exactly like up{instance}==0.
        self._push_last: dict[str, float] = {}
        self.push_reports: "dict[str, dict]" = {}
        self._push_reports_cap = 64
        # push sinks (ISSUE 9 satellite): webhook/exec fired on
        # pending→firing (and resolve) transitions — SLO alerts AND the
        # externally-raised ones below
        self.notifier: AlertNotifier = AlertNotifier.from_env()
        # externally-managed alerts (e.g. the online drift-pause): name →
        # status dict, merged into alerts_payload and the firing gauge
        self._external: dict[str, dict] = {}

    # -- what the sampler samples ------------------------------------------
    def _families(self) -> list:
        """Every attached registry's families plus the process-default
        ones. Same-NAMED families from different servers are all kept —
        a query server's and a storage daemon's `http_requests_total`
        carry disjoint `server=` label children, and dropping the
        later-attached server's family would blind its SLOs entirely.
        Exact duplicate (name, labels) series — the per-registry
        jax/devprof gauges reading one global source — are deduped
        per-tick by the sampler, first writer wins."""
        from predictionio_tpu.obs.registry import get_default_registry

        seen_ids: set[int] = set()
        out = []
        with self._lock:
            registries = [reg for _t, _l, reg in self._attached]
        registries.append(get_default_registry())
        for reg in registries:
            for fam in reg.families():
                if id(fam) not in seen_ids:
                    seen_ids.add(id(fam))
                    out.append(fam)
        return out

    # -- lifecycle ---------------------------------------------------------
    def attach(self, label: str, registry: Any) -> Optional[int]:
        """Register a server's registry for sampling; returns a token
        for `detach` (None when the plane is disabled or the server has
        no registry)."""
        if not enabled() or registry is None:
            return None
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._attached.append((token, label, registry))
        self._ensure_threads()
        return token

    def detach(self, token: Optional[int]) -> None:
        if token is None:
            return
        stop_sampler = stop_engine = stop_snapshotter = None
        stop_compactor = None
        stop_flusher = False
        with self._lock:
            self._attached = [
                row for row in self._attached if row[0] != token
            ]
            if not self._attached:
                stop_sampler, self._sampler = self._sampler, None
                stop_engine, self._engine = self._engine, None
                stop_snapshotter, self._snapshotter = (
                    self._snapshotter, None
                )
                stop_compactor, self._compactor = self._compactor, None
                stop_flusher = self.durable_dir is not None
        # join OUTSIDE the lock: the threads' loops call back into us
        if stop_engine is not None:
            stop_engine.stop()
        if stop_sampler is not None:
            stop_sampler.stop()
        if stop_snapshotter is not None:
            stop_snapshotter.stop()  # also writes the final snapshot
        if stop_compactor is not None:
            stop_compactor.stop()
        if stop_flusher and hasattr(self.tsdb, "flush_once"):
            self.tsdb.stop()  # final WAL drain + fsync
        if stop_engine is not None or stop_sampler is not None:
            # last detach also joins in-flight alert deliveries — a
            # notification thread must not outlive the plane (ISSUE 12)
            self.notifier.close(timeout=2.0)

    def _ensure_threads(self) -> None:
        with self._lock:
            if not self._attached:
                return
            if self._sampler is None:
                self._sampler = MetricsSampler(
                    self.tsdb, self._families, self.sampler_interval_s,
                    post_sample=self._post_sample,
                )
                self._sampler.start()
            specs = self._slo_union_locked()
            if self._engine is None and specs:
                self._engine = SLOEngine(
                    self.tsdb, specs, self.slo_interval_s,
                    on_transition=self._on_transition,
                )
                # recorded fast path: trust ratios no staler than ~2
                # sampler ticks (plus slack); beyond that the engine
                # rescans raw rings itself
                self._engine.recorded_max_age_s = (
                    2.5 * self.sampler_interval_s
                )
                self._engine.start()
            if self._snapshotter is None and self.snapshot_path:
                self._snapshotter = SnapshotWriter(
                    self.tsdb, self.snapshot_path,
                    interval_s=self.snapshot_interval_s,
                )
                self._snapshotter.start()
            if self._compactor is None and self.durable_dir and hasattr(
                self.tsdb, "flush_once"
            ):
                from predictionio_tpu.obs.monitor.compact import Compactor

                self.tsdb.start()  # the tsdb-wal flusher
                self._compactor = Compactor(
                    self.tsdb,
                    interval_s=env_float("PIO_TSDB_COMPACT_S", 30.0),
                    retention={
                        "raw": env_float("PIO_TSDB_RETENTION_RAW"),
                        "5m": env_float("PIO_TSDB_RETENTION_5M"),
                        "1h": env_float("PIO_TSDB_RETENTION_1H"),
                    },
                )
                self._compactor.start()

    def _post_sample(self, tsdb: TSDB, now: float) -> None:
        """Recording pass, on the sampler thread right after each raw
        snapshot: user recording rules first, then the per-SLO ratio
        series the engine's fast path reads."""
        if self.recording_rules:
            evaluate_rules(tsdb, self.recording_rules, now)
        with self._lock:
            specs = self._slo_union_locked()
            push_last = dict(self._push_last)
        if specs:
            record_slo_ratios(tsdb, specs, now)
        for instance, last in push_last.items():
            tsdb.add(
                "telemetry_last_push_age_seconds",
                {"instance": instance},
                max(0.0, now - last), "gauge", now,
            )

    # -- SLOs --------------------------------------------------------------
    def _slo_union_locked(self) -> list[SLOSpec]:
        """Operator specs + tenant presets; an operator spec shadows a
        preset with the same name."""
        names = {s.name for s in self._slos}
        return self._slos + [
            p for p in self._presets if p.name not in names
        ]

    def set_slos(self, specs: list[SLOSpec]) -> None:
        """Install/replace the SLO set; starts the engine if servers are
        already attached (tests and `pio monitor` configure this way,
        deployments use PIO_SLOS)."""
        with self._lock:
            self._slos = list(specs)
            if self._engine is not None:
                self._engine.set_specs(self._slo_union_locked())
        self._ensure_threads()

    def apply_tenant_presets(self, tenant_ids) -> None:
        """Install auto-derived per-tenant SLO presets (the mux calls
        this on attach/refresh when PIO_TENANT_SLO_PRESETS is set).
        No-op when the tenant set is unchanged."""
        specs = tenant_slo_presets(tenant_ids)
        with self._lock:
            if [s.name for s in specs] == [
                s.name for s in self._presets
            ]:
                return
            self._presets = specs
            if self._engine is not None:
                self._engine.set_specs(self._slo_union_locked())
        self._ensure_threads()

    # -- trace collector + exemplars (ISSUE 16) ----------------------------
    def set_collector(self, collector: Optional[TraceCollector]) -> None:
        """Register (or clear) this process's fleet trace collector so
        `GET /debug/traces?fleet=1` and alert enrichment reach it. The
        owner (gateway / dashboard / `pio monitor`) keeps start/stop."""
        self.collector = collector

    def note_exemplar(self, family: str, trace_id: str, value: float,
                      ts: Optional[float] = None,
                      labels: Optional[dict] = None) -> None:
        """Index one scraped exemplar: bounded per (family, observing
        label set), one slot per trace id, evicting the fastest when
        full — each route/verb keeps its own slowest traces."""
        import time as _time

        ts = _time.time() if ts is None else float(ts)
        lkey = tuple(sorted(
            (str(k), str(v)) for k, v in (labels or {}).items()
        ))
        with self._lock:
            d = self._exemplars.setdefault(family, {}).setdefault(
                lkey, {}
            )
            prev = d.get(trace_id)
            if prev is not None:
                if value > prev[0]:
                    d[trace_id] = (value, ts)
                return
            if len(d) >= self._exemplar_cap:
                floor_tid = min(d, key=lambda t: d[t])
                if value <= d[floor_tid][0]:
                    return
                del d[floor_tid]
            d[trace_id] = (value, ts)

    def exemplars(self, family: Optional[str] = None,
                  limit: int = 8,
                  labels: Optional[dict] = None) -> list[dict]:
        """Slowest-first exemplars across the scraped fleet index AND
        the local registries' histogram families, deduped by trace id.
        `labels` filters to label sets containing those pairs (e.g.
        ``{"route": "/queries.json"}`` — the per-route view)."""
        from predictionio_tpu.obs.registry import HistogramFamily

        want = None if not labels else set(
            (str(k), str(v)) for k, v in labels.items()
        )
        rows: list[dict] = []
        with self._lock:
            for fam, by_lkey in self._exemplars.items():
                if family and fam != family:
                    continue
                for lkey, d in by_lkey.items():
                    if want is not None and not want <= set(lkey):
                        continue
                    rows.extend(
                        {"family": fam, "trace_id": tid,
                         "value": v, "ts": ts, "labels": dict(lkey)}
                        for tid, (v, ts) in d.items()
                    )
        for f in self._families():
            if isinstance(f, HistogramFamily) and (
                not family or f.name == family
            ):
                for ex in f.exemplars():
                    ex_labels = ex.get("labels") or {}
                    if want is not None and not want <= set(
                        (str(k), str(v)) for k, v in ex_labels.items()
                    ):
                        continue
                    rows.append({"family": f.name, **ex})
        rows.sort(key=lambda r: r["value"], reverse=True)
        seen: set[str] = set()
        out: list[dict] = []
        for r in rows:
            if r["trace_id"] in seen:
                continue
            seen.add(r["trace_id"])
            out.append(r)
            if len(out) >= max(1, limit):
                break
        return out

    # -- push telemetry (ISSUE 17) -----------------------------------------
    def note_push(self, instance: str, sampled_at: float,
                  devprof: Optional[dict] = None,
                  now: Optional[float] = None) -> None:
        """Bookkeeping for one ingested push: freshness (the sampler
        re-derives telemetry_last_push_age_seconds from this) and the
        instance's latest devprof report. Writes an immediate age≈0
        point so the series exists even before the next sampler tick —
        `pio tsdb` right after a push must already see it."""
        import time as _time

        now = _time.time() if now is None else now
        with self._lock:
            self._push_last[instance] = now
            if devprof is not None:
                self.push_reports[instance] = devprof
                while len(self.push_reports) > self._push_reports_cap:
                    self.push_reports.pop(
                        next(iter(self.push_reports))
                    )
        self.tsdb.add(
            "telemetry_last_push_age_seconds", {"instance": instance},
            max(0.0, now - float(sampled_at)), "gauge", now,
        )

    def push_status(self) -> list[dict]:
        """Per-instance push freshness for dashboards/CLI."""
        import time as _time

        now = _time.time()
        with self._lock:
            rows = [
                {
                    "instance": instance,
                    "age_s": round(max(0.0, now - last), 3),
                    "devprof": instance in self.push_reports,
                }
                for instance, last in self._push_last.items()
            ]
        rows.sort(key=lambda r: r["instance"])
        return rows

    def _enrich_alert(self, payload: dict) -> dict:
        """Attach evidence to a firing alert: the slowest exemplar
        trace ids from the relevant latency family, plus the slowest
        assembled fleet traces when a collector runs here — the alert
        links straight to `pio trace show --fleet <id>`."""
        spec = payload.get("spec") or {}
        fam = (
            "tenant_serve_seconds" if spec.get("tenant")
            else "http_request_seconds"
        )
        try:
            exs = self.exemplars(family=fam, limit=4) or self.exemplars(
                limit=4
            )
        except Exception:
            exs = []
        if exs:
            payload["exemplars"] = exs
        collector = self.collector
        if collector is not None:
            try:
                slow = collector.slowest(limit=3)
            except Exception:
                slow = []
            if slow:
                payload["fleet_traces"] = slow
        return payload

    def _on_transition(
        self, payload: dict, old_state: str, new_state: str
    ) -> None:
        if new_state == "firing":
            payload = self._enrich_alert(dict(payload))
        if new_state in ("firing", "resolved"):
            self.notifier.notify(dict(
                payload, transition=f"{old_state}->{new_state}"
            ))

    # -- external alerts (ISSUE 9: drift-pause visibility) -----------------
    def _firing_gauge(self):
        from predictionio_tpu.obs.registry import get_default_registry

        return get_default_registry().gauge(
            "alerts_firing", "SLO alerts currently firing (1) or not (0)",
            # label-bound: declared SLO specs + external alerts, which
            # remove() their series on resolve (ISSUE 9 round 5)
            ("slo",),
        )

    def raise_alert(self, name: str, info: Optional[dict] = None) -> None:
        """Raise (or refresh) an externally-managed alert: visible at
        `GET /alerts` / `pio alerts`, exported on `alerts_firing{slo}`,
        and pushed through the notification sinks on the inactive→firing
        edge."""
        import time as _time

        with self._lock:
            prev = self._external.get(name)
            was_firing = prev is not None and prev.get("state") == "firing"
            st = {
                "slo": name,
                "state": "firing",
                "external": True,
                "since": (
                    prev.get("since") if was_firing else _time.time()
                ),
                **(info or {}),
            }
            self._external[name] = st
        try:
            self._firing_gauge().set(1.0, slo=name)
        except Exception:
            pass
        if not was_firing:
            self.notifier.notify(dict(st, transition="inactive->firing"))

    def resolve_alert(self, name: str) -> None:
        import time as _time

        with self._lock:
            st = self._external.get(name)
            if st is None or st.get("state") != "firing":
                return
            # resolved entries are DROPPED (after notifying), not kept:
            # unlike SLO alerts (fixed spec set, states cycle in place),
            # external names are open-ended — keeping every resolved
            # one would grow /alerts and the firing-gauge label set
            # monotonically over pause/resume cycles
            self._external.pop(name, None)
            st = dict(st, state="resolved", since=_time.time())
        try:
            # remove, don't zero: open-ended external names (one per
            # consumer cursor) would otherwise leave a dead 0-series
            # per name on /metrics — and in the TSDB — forever
            self._firing_gauge().remove(slo=name)
        except Exception:
            pass
        self.notifier.notify(dict(st, transition="firing->resolved"))

    def _external_rows(self) -> list[dict]:
        with self._lock:
            return [dict(v) for v in self._external.values()]

    @property
    def engine(self) -> Optional[SLOEngine]:
        return self._engine

    @property
    def attached_count(self) -> int:
        with self._lock:
            return len(self._attached)

    def alerts_payload(self) -> dict:
        """The `GET /alerts` body — stable shape whether or not the
        engine is running. Externally-raised alerts (drift-pause) merge
        into `alerts`/`firing` alongside the SLO ones."""
        engine = self._engine
        ext = self._external_rows()
        if engine is None:
            out = {
                "enabled": enabled(),
                "slos": [s.to_dict() for s in self._slos],
                "alerts": [],
                "firing": [],
                "message": (
                    "monitoring disabled (PIO_TSDB=0)" if not enabled()
                    else "no SLO engine running (configure PIO_SLOS or "
                         "Monitor.set_slos)"
                ),
            }
        else:
            out = {"enabled": True, **engine.payload()}
            for row in out.get("alerts", []):
                if row.get("state") == "firing":
                    self._enrich_alert(row)
        if ext:
            out["alerts"] = list(out.get("alerts", [])) + [
                r for r in ext if r.get("state") != "inactive"
            ]
            out["firing"] = list(out.get("firing", [])) + [
                r["slo"] for r in ext if r.get("state") == "firing"
            ]
        return out

    def tsdb_payload(self, qs: dict[str, str]) -> dict:
        """The `GET /debug/tsdb` body: summary by default; `?name=`
        (+`labels=k:v,...` `window_s=` `agg=rate|increase|quantile`
        `q=`) for points/aggregates."""
        if not enabled():
            return {"enabled": False, "series": []}
        expr_s = qs.get("expr")
        if expr_s:
            from predictionio_tpu.obs.monitor.expr import (
                ExprError,
                evaluate_rows,
            )

            try:
                window_s = (
                    float(qs["window_s"]) if "window_s" in qs else 300.0
                )
            except ValueError:
                window_s = 300.0
            try:
                rows = evaluate_rows(
                    self.tsdb, expr_s, default_window_s=window_s
                )
            except ExprError as e:
                return {"enabled": True, "expr": expr_s, "error": str(e)}
            return {"enabled": True, "expr": expr_s, "result": rows}
        name = qs.get("name")
        if not name:
            try:
                limit = int(qs.get("limit", "0") or 0)
            except ValueError:
                limit = 0
            return {"enabled": True, **self.tsdb.summary(limit=limit)}
        match: Optional[dict] = None
        labels_s = qs.get("labels", "")
        if labels_s:
            match = {}
            for pair in labels_s.split(","):
                if not pair:
                    continue
                k, _, v = (
                    pair.partition(":") if ":" in pair
                    else pair.partition("=")
                )
                match[k.strip()] = v.strip()
        try:
            window_s = float(qs["window_s"]) if "window_s" in qs else None
        except ValueError:
            window_s = None
        agg = qs.get("agg")
        out: dict[str, Any] = {"enabled": True, "name": name}
        if agg in ("rate", "increase"):
            w = window_s or 300.0
            value = (
                self.tsdb.rate(name, match, w) if agg == "rate"
                else self.tsdb.increase(name, match, w)
            )
            out.update({"agg": agg, "window_s": w, "value": value})
        elif agg == "quantile":
            try:
                q = float(qs.get("q", "0.99"))
            except ValueError:
                q = 0.99
            out.update({
                "agg": agg, "q": q, "window_s": window_s,
                "value": self.tsdb.quantile_over_time(
                    name, q, match, window_s
                ),
            })
        else:
            out["series"] = self.tsdb.range(name, match, window_s)
        return out


_monitor: Optional[Monitor] = None
_monitor_lock = threading.Lock()


def get_monitor() -> Monitor:
    """The process-wide monitor (lazy, so env knobs set before first
    server start are honored)."""
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            _monitor = Monitor()
        return _monitor
