"""Downsampling compactor + retention for the durable TSDB tier.

The ``tsdb-compactor`` thread periodically rolls sealed raw blocks
into the 5m tier and 5m blocks into the 1h tier, then enforces
per-tier retention (PIO_TSDB_RETENTION_{RAW,5M,1H}) — the pruning
half of the segmentfs seal/footer/prune discipline, applied to
telemetry.

Each downsampled bucket stores count/sum/min/max/first/last plus
``inc``: the reset-aware counter increase WITHIN the bucket, computed
here from the raw points while they still exist. At query time a
window's increase is the sum of its buckets' ``inc`` plus the
reset-aware first/last joins between adjacent buckets — exact over
full buckets, so ``rate()`` and ``increase()`` survive tiering (the
documented slop is confined to the window's two partial edge
buckets). ``quantile_over_time`` answers from one representative
(``last``) per bucket with error bounded by the in-bucket [min, max]
range. Rolling 5m→1h aggregates the same columns without touching raw
data again: ``inc`` sums plus the joins interior to the hour.

A bucket is only compacted once it can no longer grow: its end must be
older than ``grace_s`` (seal age + flush slack) so every raw point for
it has been sealed. Source blocks are deleted by retention only after
the next tier's watermark has passed them — retention can never eat
data that was not yet downsampled.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Any, Optional

from predictionio_tpu.obs.monitor.durable import (
    DS_COLS,
    TIER_BUCKETS,
    BLOCK_SUFFIX,
    DurableTSDB,
    _join_delta,
    _merge_series,
    write_block,
)
from predictionio_tpu.obs.monitor.tsdb import increase_of

log = logging.getLogger(__name__)

#: source → target downsampling edges, in application order
TIER_CHAIN: tuple[tuple[str, str], ...] = (("raw", "5m"), ("5m", "1h"))

DEFAULT_RETENTION: dict[str, float] = {
    "raw": 6 * 3600.0,
    "5m": 3 * 86400.0,
    "1h": 14 * 86400.0,
}


def _bucket_rows_from_raw(ts: list[float], vals: list[float],
                          bucket_s: float, lo_bs: Optional[float],
                          hi_end: float) -> tuple[list[int],
                                                  dict[str, list[float]]]:
    """Bucket raw (t, v) points into complete buckets: starts aligned
    to `bucket_s`, >= lo_bs (watermark), ending by `hi_end`."""
    per: dict[float, list[float]] = {}
    for t, v in zip(ts, vals):
        bs = math.floor(t / bucket_s) * bucket_s
        if lo_bs is not None and bs < lo_bs:
            continue
        if bs + bucket_s > hi_end:
            continue
        per.setdefault(bs, []).append(v)
    out_ts: list[int] = []
    cols: dict[str, list[float]] = {c: [] for c in DS_COLS}
    for bs in sorted(per):
        vs = per[bs]
        out_ts.append(int(round(bs * 1000.0)))
        cols["count"].append(float(len(vs)))
        cols["sum"].append(math.fsum(vs))
        cols["min"].append(min(vs))
        cols["max"].append(max(vs))
        cols["first"].append(vs[0])
        cols["last"].append(vs[-1])
        cols["inc"].append(increase_of((0.0, v) for v in vs))
    return out_ts, cols


def _bucket_rows_from_ds(ts: list[float], cols: dict[str, list[float]],
                         bucket_s: float, lo_bs: Optional[float],
                         hi_end: float, src_bucket_s: float
                         ) -> tuple[list[int], dict[str, list[float]]]:
    """Re-bucket downsampled rows into coarser complete buckets,
    preserving exact counter ``inc`` via interior first/last joins."""
    per: dict[float, list[int]] = {}
    for i, t in enumerate(ts):
        bs = math.floor(t / bucket_s) * bucket_s
        if lo_bs is not None and bs < lo_bs:
            continue
        # the whole source bucket must fit inside the target bucket
        if t + src_bucket_s > bs + bucket_s or bs + bucket_s > hi_end:
            continue
        per.setdefault(bs, []).append(i)
    out_ts: list[int] = []
    out: dict[str, list[float]] = {c: [] for c in DS_COLS}
    for bs in sorted(per):
        idxs = sorted(per[bs], key=lambda i: ts[i])
        inc = 0.0
        prev_last: Optional[float] = None
        for j, i in enumerate(idxs):
            if j > 0:
                inc += _join_delta(prev_last, cols["first"][i])
            inc += cols["inc"][i]
            prev_last = cols["last"][i]
        out_ts.append(int(round(bs * 1000.0)))
        out["count"].append(math.fsum(cols["count"][i] for i in idxs))
        out["sum"].append(math.fsum(cols["sum"][i] for i in idxs))
        out["min"].append(min(cols["min"][i] for i in idxs))
        out["max"].append(max(cols["max"][i] for i in idxs))
        out["first"].append(cols["first"][idxs[0]])
        out["last"].append(cols["last"][idxs[-1]])
        out["inc"].append(inc)
    return out_ts, out


class Compactor:
    """Background downsample+retention pass over a DurableTSDB's tiers.
    `stop()` joins the thread — the no-leaked-threads contract every
    monitor thread follows."""

    thread_name = "tsdb-compactor"

    def __init__(self, durable: DurableTSDB, interval_s: float = 30.0,
                 retention: Optional[dict[str, float]] = None,
                 grace_s: Optional[float] = None):
        self.durable = durable
        self.interval_s = max(0.1, float(interval_s))
        self.retention = dict(DEFAULT_RETENTION)
        if retention:
            self.retention.update(retention)
        if grace_s is None:
            grace_s = durable.seal_age_s + 2.0 * durable.flush_interval_s
        self.grace_s = max(0.0, float(grace_s))
        self._lock = threading.Lock()
        self.compacted_blocks = 0  # guarded-by: _lock
        self.compacted_buckets = 0  # guarded-by: _lock
        self.removed_blocks = 0  # guarded-by: _lock
        self.passes = 0  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one pass ------------------------------------------------------------

    def _watermark(self, tier: str) -> Optional[float]:
        """Exclusive bucket-start floor for the next compaction into
        `tier`: everything before it is already downsampled."""
        blocks = self.durable.tiers[tier].blocks()
        if not blocks:
            return None
        bucket_s = TIER_BUCKETS[tier]
        newest = max(b.max_t for b in blocks)
        return math.floor(newest / bucket_s) * bucket_s + bucket_s

    def _downsample_edge(self, src_name: str, dst_name: str, now: float,
                         force: bool) -> int:
        d = self.durable
        src = d.tiers[src_name]
        dst = d.tiers[dst_name]
        bucket_s = TIER_BUCKETS[dst_name]
        lo_bs = self._watermark(dst_name)
        hi_end = now + bucket_s if force else now - self.grace_s
        src_blocks = src.blocks(lo_bs, hi_end)
        if not src_blocks:
            return 0
        keys = {}
        for b in src_blocks:
            for key, entry in b.series.items():
                keys.setdefault(key, entry.get("k", "gauge"))
        rows = []
        lo_ms = hi_ms = None
        buckets = 0
        for key in sorted(keys):
            ts, cols = _merge_series(
                src_blocks, key, lo_bs if lo_bs is not None else 0.0,
                hi_end,
            )
            if not ts:
                continue
            if src.bucket_s == 0:
                out_ts, out_cols = _bucket_rows_from_raw(
                    ts, cols["v"], bucket_s, lo_bs, hi_end
                )
            else:
                out_ts, out_cols = _bucket_rows_from_ds(
                    ts, cols, bucket_s, lo_bs, hi_end, src.bucket_s
                )
            if not out_ts:
                continue
            rows.append((key[0], key[1], keys[key], out_ts, out_cols))
            buckets += len(out_ts)
            lo_ms = out_ts[0] if lo_ms is None else min(lo_ms, out_ts[0])
            hi_ms = out_ts[-1] if hi_ms is None else max(hi_ms, out_ts[-1])
        if not rows:
            return 0
        import os as _os

        path = _os.path.join(
            dst.root, f"b-{lo_ms}-{hi_ms}-d{int(bucket_s)}{BLOCK_SUFFIX}"
        )
        write_block(path, dst_name, rows)
        dst.invalidate()
        with self._lock:
            self.compacted_blocks += 1
            self.compacted_buckets += buckets
        return buckets

    def _enforce_retention(self, now: float) -> int:
        d = self.durable
        removed = 0
        next_of = {"raw": "5m", "5m": "1h", "1h": None}
        for tier, nxt in next_of.items():
            keep_s = float(self.retention.get(tier, 0.0))
            if keep_s <= 0:
                continue
            cutoff = now - keep_s
            next_wm = None
            if nxt is not None:
                blocks = d.tiers[nxt].blocks()
                next_wm = max((b.max_t for b in blocks), default=None)
                if next_wm is not None:
                    # a ds block's max_t is its newest bucket START;
                    # data is rolled up through that bucket's END
                    next_wm += TIER_BUCKETS[nxt]
            doomed = []
            for b in d.tiers[tier].blocks():
                if b.max_t >= cutoff:
                    continue
                # never prune data the next tier has not rolled up yet
                if nxt is not None and (next_wm is None
                                        or b.max_t > next_wm):
                    continue
                doomed.append(b.path)
            removed += d.tiers[tier].remove_blocks(doomed)
        if removed:
            with self._lock:
                self.removed_blocks += removed
        return removed

    def run_once(self, now: Optional[float] = None,
                 force: bool = False) -> dict[str, int]:
        """One compaction pass. `force` ignores the grace window and
        compacts every sealed bucket (tests, shutdown)."""
        now = time.time() if now is None else now
        buckets = 0
        for src_name, dst_name in TIER_CHAIN:
            buckets += self._downsample_edge(src_name, dst_name, now, force)
        removed = self._enforce_retention(now)
        with self._lock:
            self.passes += 1
        return {"buckets": buckets, "removed_blocks": removed}

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "passes": self.passes,
                "compacted_blocks": self.compacted_blocks,
                "compacted_buckets": self.compacted_buckets,
                "removed_blocks": self.removed_blocks,
                "grace_s": self.grace_s,
                "retention": dict(self.retention),
            }

    # -- thread lifecycle ----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=self.thread_name, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except Exception:
                log.warning("TSDB compaction pass failed; retrying next "
                            "tick", exc_info=True)
