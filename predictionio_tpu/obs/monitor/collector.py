"""Fleet trace collector: many processes' span fragments → one tree.

PR 2's span recorder is process-local: a hedged query through the
gateway leaves a `gateway.request` fragment in the gateway, one
`server.request` fragment per attempted replica, and (under the
storage daemon) RPC fragments further down — three stores, no joined
view. The :class:`TraceCollector` closes that gap the same way the
:class:`FleetScraper` does for metrics: it polls every target's
``/debug/traces?spans=1`` (the raw pre-sampling span dump), merges the
local recorder's own recent spans, and stitches everything that shares
a trace id (the propagated ``X-Request-ID``) into one cross-process
tree.

Tail sampling happens HERE, over the assembled trace: keep when any
span errored, when the root ran past ``slow_ms``, or when the trace
crossed a hedge/failover attempt (`gateway.attempt` children beyond
the primary) — those are exactly the traces an operator opens.
Fragments that never grow a root span ("orphans": the rooting process
died, or its dump was missed) are held for ``hold_s`` so a late root
can still claim them, then expired.

Runs under the same lifecycle discipline as the scraper: the owning
process (gateway, dashboard, ``pio monitor``) starts/stops it, `stop()`
joins the ``trace-collector`` thread, and it registers itself on the
process :class:`Monitor` so every server's ``/debug/traces?fleet=1``
and ``pio trace --fleet`` reach the assembled store. No jax anywhere
on this import path — the gateway's import-leak guard covers it.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from collections import OrderedDict
from typing import Any, Optional

from predictionio_tpu.obs import spans as _spans
from predictionio_tpu.utils.env import env_float, env_int

log = logging.getLogger(__name__)

# attempt kinds that mark a trace "hedged" for the keep decision
_HEDGE_KINDS = ("hedge", "failover")


class TraceCollector:
    """Background poll loop assembling cross-process traces.

    `targets` is [(instance, base_url)] — the same shape the scraper
    uses, and the gateway keeps both lists in sync from its replica
    registry. The local recorder is always included (the gateway's own
    fragments never cross HTTP)."""

    thread_name = "trace-collector"

    def __init__(
        self,
        targets: Optional[list[tuple[str, str]]] = None,
        recorder: Optional[_spans.SpanRecorder] = None,
        interval_s: Optional[float] = None,
        hold_s: Optional[float] = None,
        max_traces: Optional[int] = None,
        slow_ms: Optional[float] = None,
        timeout_s: float = 5.0,
    ):
        self.targets: list[tuple[str, str]] = list(targets or [])
        self.recorder = (
            recorder if recorder is not None
            else _spans.get_default_recorder()
        )
        self.interval_s = max(0.05, float(
            interval_s if interval_s is not None
            else env_float("PIO_TRACE_COLLECT_INTERVAL_S")
        ))
        self.hold_s = float(
            hold_s if hold_s is not None
            else env_float("PIO_TRACE_COLLECT_HOLD_S")
        )
        self.max_traces = int(
            max_traces if max_traces is not None
            else env_int("PIO_TRACE_COLLECT_MAX")
        )
        self.slow_ms = float(
            slow_ms if slow_ms is not None else self.recorder.slow_ms
        )
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        # trace_id -> {"spans": {span_id: span-dict}, "last_seen": t}
        self._frags: "OrderedDict[str, dict]" = OrderedDict()  # guarded-by: _lock
        # trace_id -> {"spans": {span_id: dict}, "reason": str, ...}
        self._traces: "OrderedDict[str, dict]" = OrderedDict()  # guarded-by: _lock
        # per-target poll cursor (epoch seconds of the last good poll)
        self._cursors: dict[str, float] = {}
        self._polls = 0
        self._poll_errors = 0
        self._expired_orphans = 0
        self._pushed_spans = 0  # spans arrived via POST /telemetry/push  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one pass ----------------------------------------------------------
    def collect_once(self, now: Optional[float] = None) -> int:
        """One poll+stitch pass; returns how many spans were ingested."""
        now = time.time() if now is None else now
        ingested = 0
        # local fragments first: the rooting gateway span usually lives
        # here, so orphan remote fragments resolve in the same pass
        cursor = self._cursors.get("", 0.0)
        for sp in self.recorder.recent(since=cursor):
            ingested += self._ingest(sp.to_dict(), now)
        self._cursors[""] = now - self.interval_s
        for instance, base in list(self.targets):
            self._polls += 1
            cursor = self._cursors.get(instance, 0.0)
            url = f"{base}/debug/traces?spans=1&since={cursor:.3f}"
            try:
                with urllib.request.urlopen(
                    url, timeout=self.timeout_s
                ) as r:
                    payload = json.loads(r.read().decode(errors="replace"))
            except Exception as e:
                self._poll_errors += 1
                log.debug(
                    "trace poll of %s (%s) failed: %s", instance, base, e
                )
                continue
            for sp in payload.get("spans") or []:
                if isinstance(sp, dict):
                    ingested += self._ingest(sp, now)
            # next poll re-covers one interval of overlap; span_id
            # dedup makes the overlap free and clock skew harmless
            self._cursors[instance] = (
                float(payload.get("now", now)) - self.interval_s
            )
        self._settle(now)
        return ingested

    def ingest_spans(self, spans: list, now: Optional[float] = None) -> int:
        """Pushed span batches (POST /telemetry/push): ingest out-of-band
        spans from a process that died before any poll could reach it.
        Rooted pushed traces are promoted immediately with reason
        "pushed" even when boring — the process is gone, so "wait for
        the poll loop to decide" would just expire them; a train trace
        that cost a subprocess its whole life is worth one slot. Unrooted
        fragments keep the normal hold_s grace for a late root."""
        now = time.time() if now is None else now
        ingested = 0
        tids: set = set()
        for sp in spans or []:
            if not isinstance(sp, dict):
                continue
            n = self._ingest(sp, now)
            ingested += n
            if n and sp.get("trace_id"):
                tids.add(sp["trace_id"])
        if not ingested:
            return 0
        with self._lock:
            self._pushed_spans += ingested
            for tid in tids:
                frag = self._frags.get(tid)
                if frag is None:
                    continue
                spans_by_id = frag["spans"]
                rooted = any(
                    not s.get("parent_span_id")
                    for s in spans_by_id.values()
                )
                if not rooted:
                    continue
                del self._frags[tid]
                self._traces[tid] = {
                    "spans": spans_by_id,
                    "reason": (
                        self._keep_reason(spans_by_id.values()) or "pushed"
                    ),
                    "assembled_at": now,
                }
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)
        return ingested

    def _ingest(self, sp: dict, now: float) -> int:
        tid = sp.get("trace_id")
        sid = sp.get("span_id")
        if not tid or not sid:
            return 0
        with self._lock:
            kept = self._traces.get(tid)
            if kept is not None:
                # late fragment of an already-assembled trace: merge,
                # without refreshing its eviction age
                if sid not in kept["spans"] and (
                    len(kept["spans"]) < self.recorder.max_spans_per_trace
                ):
                    kept["spans"][sid] = sp
                    return 1
                return 0
            frag = self._frags.get(tid)
            if frag is None:
                frag = self._frags[tid] = {
                    "spans": {}, "first_seen": now, "last_seen": now,
                }
                # pending-fragment bound: under sustained traffic every
                # request opens a fragment for up to hold_s — the map
                # must stay bounded even if the settle pass lags
                while len(self._frags) > max(256, 4 * self.max_traces):
                    self._frags.popitem(last=False)
            if sid in frag["spans"]:
                return 0
            if len(frag["spans"]) >= self.recorder.max_spans_per_trace:
                return 0
            frag["spans"][sid] = sp
            frag["last_seen"] = now
            return 1

    def _settle(self, now: float) -> None:
        """Promote assembled fragments that earned retention; expire
        rooted-but-boring and orphan fragments past the hold window."""
        with self._lock:
            for tid in list(self._frags):
                frag = self._frags[tid]
                spans = frag["spans"]
                rooted = any(
                    not s.get("parent_span_id") for s in spans.values()
                )
                reason = self._keep_reason(spans.values())
                if rooted and reason is not None:
                    del self._frags[tid]
                    self._traces[tid] = {
                        "spans": spans,
                        "reason": reason,
                        "assembled_at": now,
                    }
                    continue
                if now - frag["first_seen"] >= self.hold_s:
                    # orphan (no root arrived) or boring: expired. The
                    # hold covers poll skew — a replica fragment lands
                    # a pass or two before the gateway's root.
                    if not rooted:
                        self._expired_orphans += 1
                    del self._frags[tid]
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)

    def _keep_reason(self, spans) -> Optional[str]:
        hedged = False
        slow = False
        for s in spans:
            if s.get("error"):
                return "error"
            attrs = s.get("attrs") or {}
            if (
                s.get("name") == "gateway.attempt"
                and attrs.get("kind") in _HEDGE_KINDS
            ):
                hedged = True
            if float(s.get("duration_ms") or 0.0) >= self.slow_ms:
                slow = True
        if hedged:
            return "hedged"
        if slow:
            return "slow"
        return None

    # -- reading -----------------------------------------------------------
    def get_trace(self, trace_id: str) -> list[dict]:
        """Start-ordered span dicts of one assembled trace ([] if
        unknown)."""
        with self._lock:
            rec = self._traces.get(trace_id)
            spans = list(rec["spans"].values()) if rec else []
        return sorted(spans, key=lambda s: s.get("start") or 0.0)

    def summaries(self, limit: int = 50) -> list[dict]:
        """Newest-first one-line views of the assembled fleet traces."""
        with self._lock:
            items = list(self._traces.items())
        out = []
        for tid, rec in reversed(items[-limit:] if limit else items):
            spans = list(rec["spans"].values())
            ids = {s.get("span_id") for s in spans}
            roots = [
                s for s in spans
                if not s.get("parent_span_id")
                or s.get("parent_span_id") not in ids
            ] or spans
            root = max(roots, key=lambda s: s.get("duration_ms") or 0.0)
            servers = sorted({
                str((s.get("attrs") or {}).get("server"))
                for s in spans if (s.get("attrs") or {}).get("server")
            })
            out.append({
                "trace_id": tid,
                "root": root.get("name"),
                "servers": servers,
                "path": (root.get("attrs") or {}).get("path"),
                "spans": len(spans),
                "duration_ms": root.get("duration_ms"),
                "error": any(s.get("error") for s in spans),
                "kept": rec["reason"],
                "start": min(
                    (s.get("start") or 0.0) for s in spans
                ),
            })
        return out

    def slowest(self, limit: int = 3) -> list[dict]:
        """The slowest assembled traces — what a firing alert links to."""
        rows = self.summaries(limit=0)
        rows.sort(key=lambda r: r.get("duration_ms") or 0.0, reverse=True)
        return rows[:limit]

    def perfetto_export(self, trace_id: Optional[str] = None) -> dict:
        """Chrome trace-event JSON over assembled traces (same shape as
        SpanRecorder.perfetto_export, but each fragment's originating
        server becomes its own process row — the fleet waterfall)."""
        with self._lock:
            if trace_id is not None:
                rec = self._traces.get(trace_id)
                spans = list(rec["spans"].values()) if rec else []
            else:
                spans = [
                    s for rec in self._traces.values()
                    for s in rec["spans"].values()
                ]
        procs: dict[str, int] = {}
        events: list[dict] = []
        by_id = {s.get("span_id"): s for s in spans}

        def depth(s: dict, hops: int = 0) -> int:
            parent = by_id.get(s.get("parent_span_id") or "")
            if parent is None or hops > 32:
                return 0
            return 1 + depth(parent, hops + 1)

        for s in sorted(spans, key=lambda x: x.get("start") or 0.0):
            attrs = s.get("attrs") or {}
            proc = str(
                attrs.get("server")
                or str(s.get("name") or "span").split(".")[0]
            )
            pid = procs.setdefault(proc, len(procs) + 1)
            events.append({
                "ph": "X",
                "name": s.get("name"),
                "cat": "pio-fleet",
                "ts": round((s.get("start") or 0.0) * 1e6, 3),
                "dur": round((s.get("duration_ms") or 0.0) * 1e3, 3),
                "pid": pid,
                "tid": depth(s),
                "args": {
                    "trace_id": s.get("trace_id"),
                    "span_id": s.get("span_id"),
                    "parent_span_id": s.get("parent_span_id"),
                    "error": s.get("error"),
                    **{k: str(v) for k, v in attrs.items()},
                },
            })
        meta = [
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "args": {"name": proc},
            }
            for proc, pid in procs.items()
        ]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def status(self) -> dict[str, Any]:
        with self._lock:
            assembled = len(self._traces)
            pending = len(self._frags)
        return {
            "targets": len(self.targets),
            "interval_s": self.interval_s,
            "hold_s": self.hold_s,
            "assembled": assembled,
            "pending_fragments": pending,
            "polls": self._polls,
            "poll_errors": self._poll_errors,
            "expired_orphans": self._expired_orphans,
            "pushed_spans": self._pushed_spans,
        }

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=self.thread_name, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s + 5)
            self._thread = None

    def _loop(self) -> None:
        while True:
            try:
                self.collect_once()
            except Exception:
                log.exception("trace collect pass failed; will retry")
            if self._stop.wait(self.interval_s):
                return
