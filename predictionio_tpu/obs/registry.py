"""Unified metrics registry: counters, gauges, labeled histograms.

The one observability surface every process shares (ISSUE 1). The
reference had three disjoint telemetry shapes — per-app hourly Stats on
the event server, lossy running averages on the deploy server
(CreateServer.scala:603-610), and a JSON timing blob on the
EngineInstance row — none scrapeable. This registry replaces all three
as the source of truth: servers mount their registry at `GET /metrics`
(Prometheus text exposition v0.0.4), the train workflow records stage
durations into the process-default registry, and the legacy surfaces
(status HTML, EngineInstance blob, `pio status`) render snapshots of it.

Thread-safety: one lock per metric family guards both child creation and
child mutation — servers update from many handler threads concurrently.
Histograms use fixed cumulative buckets (Prometheus semantics) and
derive p50/p95/p99 by linear interpolation inside the target bucket,
the same estimate `histogram_quantile()` computes server-side."""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Iterable, Optional, Sequence

from predictionio_tpu.obs.tracing import current_trace_id
from predictionio_tpu.utils.env import env_int

# latency seconds: spans sub-ms device dispatches to multi-second trains
DEFAULT_LATENCY_BUCKETS: tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)
# micro-batch depth: powers of two up to 2x the default max_batch of 64
BATCH_SIZE_BUCKETS: tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _label_str(labelnames: Sequence[str], labelvalues: Sequence[str],
               extra: Optional[tuple[str, str]] = None) -> str:
    pairs = list(zip(labelnames, labelvalues))
    if extra is not None:
        pairs.append(extra)
    if not pairs:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in pairs
    )
    return "{" + inner + "}"


class _Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class _Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class _Histogram:
    __slots__ = ("bucket_counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.bucket_counts = [0] * (n_buckets + 1)  # +1 for the +Inf bucket
        self.sum = 0.0
        self.count = 0


class MetricFamily:
    """One named metric + its per-label-set children."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str, labelnames: Sequence[str]):
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], Any] = {}

    def _new_child(self) -> Any:
        raise NotImplementedError

    def _child(self, labelvalues: tuple[str, ...]) -> Any:
        child = self._children.get(labelvalues)
        if child is None:
            child = self._children[labelvalues] = self._new_child()
        return child

    def _values(self, **labels: Any) -> tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}"
            )
        return tuple(str(labels[n]) for n in self.labelnames)

    def remove(self, **labels: Any) -> bool:
        """Drop one label-set child (ISSUE 9: open-ended label values —
        per-consumer alert names — must not leave dead series on
        /metrics forever). Returns whether it existed."""
        with self._lock:
            return self._children.pop(self._values(**labels), None) is not None


class CounterFamily(MetricFamily):
    kind = "counter"

    def _new_child(self) -> _Counter:
        return _Counter()

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._child(self._values(**labels)).value += amount

    def value(self, **labels: Any) -> float:
        with self._lock:
            child = self._children.get(self._values(**labels))
            return child.value if child is not None else 0.0

    @property
    def total(self) -> float:
        with self._lock:
            return sum(c.value for c in self._children.values())


class GaugeFamily(MetricFamily):
    kind = "gauge"

    def __init__(self, name, help_text, labelnames,
                 callback: Optional[Callable[[], float]] = None):
        super().__init__(name, help_text, labelnames)
        if callback is not None and labelnames:
            raise ValueError("callback gauges cannot be labeled")
        self.callback = callback

    def _new_child(self) -> _Gauge:
        return _Gauge()

    def set(self, value: float, **labels: Any) -> None:
        with self._lock:
            self._child(self._values(**labels)).value = float(value)

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        with self._lock:
            self._child(self._values(**labels)).value += amount

    def dec(self, amount: float = 1.0, **labels: Any) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: Any) -> float:
        if self.callback is not None:
            try:
                return float(self.callback())
            except Exception:
                return 0.0
        with self._lock:
            child = self._children.get(self._values(**labels))
            return child.value if child is not None else 0.0


class HistogramFamily(MetricFamily):
    kind = "histogram"

    def __init__(self, name, help_text, labelnames,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                 lower_bound: float = 0.0):
        super().__init__(name, help_text, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket")
        # smallest value observe() can legally receive: quantile()
        # interpolates the first bucket from here. 0 is right for
        # latencies; count-valued histograms (batch_size) pass 1 so a
        # bucket of all-ones yields p50=1, not an impossible 0.5
        self.lower_bound = float(lower_bound)
        # ISSUE 16 exemplars, ISSUE 17 per-route indexing: the slowest N
        # (trace-id, value) pairs observed PER LABEL SET while a request
        # trace was in scope — one slot per trace id, so a single
        # pathological request cannot monopolize a reservoir. Keying by
        # label set (route/verb/tenant...) means "which trace do I open
        # for the /queries.json alert" no longer competes with a slow
        # /metrics scrape for the same bounded list.
        self._exemplar_cap = env_int("PIO_TRACE_EXEMPLARS")
        self._exemplars: dict[
            tuple[str, ...], dict[str, tuple[float, float]]
        ] = {}

    def _new_child(self) -> _Histogram:
        return _Histogram(len(self.buckets))

    def observe(self, value: float, **labels: Any) -> None:
        value = float(value)
        tid = current_trace_id() if self._exemplar_cap > 0 else None
        with self._lock:
            lv = self._values(**labels)
            child = self._child(lv)
            i = 0
            for i, edge in enumerate(self.buckets):
                if value <= edge:
                    break
            else:
                i = len(self.buckets)  # +Inf bucket
            child.bucket_counts[i] += 1
            child.sum += value
            child.count += 1
            if tid is not None:
                self._note_exemplar_locked(lv, tid, value)

    def _note_exemplar_locked(self, lv: tuple[str, ...], tid: str,
                              value: float) -> None:
        d = self._exemplars.setdefault(lv, {})
        prev = d.get(tid)
        if prev is not None:
            if value > prev[0]:
                d[tid] = (value, time.time())
            return
        if len(d) >= self._exemplar_cap:
            floor_tid = min(d, key=lambda t: d[t])
            if value <= d[floor_tid][0]:
                return
            del d[floor_tid]
        d[tid] = (value, time.time())

    def exemplars(self) -> list[dict]:
        """Retained exemplars, slowest first:
        [{trace_id, value, ts, labels}] — `labels` is the observing
        label set (route/verb/...), per-set bounded."""
        with self._lock:
            items = [
                (lv, tid, val, ts)
                for lv, d in self._exemplars.items()
                for tid, (val, ts) in d.items()
            ]
        items.sort(key=lambda row: row[2], reverse=True)
        return [
            {
                "trace_id": tid, "value": val, "ts": ts,
                "labels": dict(zip(self.labelnames, lv)),
            }
            for lv, tid, val, ts in items
        ]

    def _get(self, labels: dict) -> Optional[_Histogram]:
        return self._children.get(self._values(**labels))

    def count_of(self, **labels: Any) -> int:
        with self._lock:
            c = self._get(labels)
            return c.count if c else 0

    def sum_of(self, **labels: Any) -> float:
        with self._lock:
            c = self._get(labels)
            return c.sum if c else 0.0

    def quantile(self, q: float, **labels: Any) -> float:
        """Estimate quantile `q` by linear interpolation within the target
        cumulative bucket (what PromQL's histogram_quantile computes)."""
        with self._lock:
            c = self._get(labels)
            if c is None or c.count == 0:
                return 0.0
            target = q * c.count
            cum = 0
            prev_edge = self.lower_bound
            for edge, n in zip(self.buckets, c.bucket_counts):
                if n and cum + n >= target:
                    frac = (target - cum) / n
                    return prev_edge + (edge - prev_edge) * frac
                cum += n
                prev_edge = edge
            # fell in the +Inf bucket: the highest finite edge is the
            # best bounded estimate available
            return self.buckets[-1]

    # unlabeled-family conveniences (the server hot-path histograms)
    @property
    def count(self) -> int:
        return self.count_of()

    @property
    def sum(self) -> float:
        return self.sum_of()

    @property
    def mean(self) -> float:
        with self._lock:
            c = self._children.get(())
            if c is None or c.count == 0:
                return 0.0
            return c.sum / c.count


class MetricsRegistry:
    """Create-or-get metric families by name; render/snapshot them all."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, MetricFamily] = {}

    def _get_or_create(self, cls: type, name: str, help_text: str,
                       labelnames: Sequence[str], **kw) -> Any:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or (
                    tuple(labelnames) != fam.labelnames
                ):
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        f"type or label set"
                    )
                if "buckets" in kw and (
                    tuple(sorted(float(b) for b in kw["buckets"]))
                    != fam.buckets
                    or float(kw.get("lower_bound", 0.0)) != fam.lower_bound
                ):
                    # same loudness as type/label drift: a caller reading
                    # batch sizes through latency buckets would otherwise
                    # get silently-wrong quantiles
                    raise ValueError(
                        f"histogram {name!r} re-registered with different "
                        f"buckets"
                    )
                if kw.get("callback") is not None and isinstance(
                    fam, GaugeFamily
                ):
                    # newest callback wins: a re-attached component
                    # (e.g. a fresh TenantMux after a server restart in
                    # the same process) must not leave /metrics reading
                    # — and keeping alive — the dead instance's closure
                    fam.callback = kw["callback"]
                return fam
            fam = cls(name, help_text, tuple(labelnames), **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_text: str = "",
                labelnames: Sequence[str] = ()) -> CounterFamily:
        return self._get_or_create(CounterFamily, name, help_text, labelnames)

    def gauge(self, name: str, help_text: str = "",
              labelnames: Sequence[str] = ()) -> GaugeFamily:
        return self._get_or_create(GaugeFamily, name, help_text, labelnames)

    def gauge_callback(self, name: str, help_text: str,
                       callback: Callable[[], float]) -> GaugeFamily:
        """Gauge sampled at render/snapshot time (e.g. live device buffers)."""
        return self._get_or_create(
            GaugeFamily, name, help_text, (), callback=callback
        )

    def histogram(self, name: str, help_text: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                  lower_bound: float = 0.0) -> HistogramFamily:
        return self._get_or_create(
            HistogramFamily, name, help_text, labelnames, buckets=buckets,
            lower_bound=lower_bound,
        )

    def families(self) -> list[MetricFamily]:
        with self._lock:
            return list(self._families.values())

    # -- exposition --------------------------------------------------------
    def render(self) -> str:
        return render_families(self.families())

    def snapshot(self) -> dict:
        """JSON-able view: counters/gauges → value, histograms → count,
        sum, mean, p50/p95/p99 per label set. This is what bench.py embeds
        in BENCH_*.json and what `pio status`/status_html render."""
        out: dict[str, Any] = {}
        for fam in sorted(self.families(), key=lambda f: f.name):
            rows = []
            if isinstance(fam, GaugeFamily) and fam.callback is not None:
                rows.append({"labels": {}, "value": fam.value()})
            elif isinstance(fam, HistogramFamily):
                with fam._lock:
                    items = list(fam._children.items())
                for lv, c in items:
                    row = {
                        "labels": dict(zip(fam.labelnames, lv)),
                        "count": c.count,
                        "sum": round(c.sum, 6),
                        "mean": round(c.sum / c.count, 6) if c.count else 0.0,
                    }
                    for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                        row[key] = round(
                            fam.quantile(q, **row["labels"]), 6
                        )
                    rows.append(row)
            else:
                with fam._lock:
                    items = list(fam._children.items())
                for lv, c in items:
                    rows.append({
                        "labels": dict(zip(fam.labelnames, lv)),
                        "value": c.value,
                    })
            if rows:
                out[fam.name] = {"type": fam.kind, "values": rows}
        return out


def render_families(families: Iterable[MetricFamily]) -> str:
    """Prometheus text exposition format v0.0.4."""
    lines: list[str] = []
    for fam in sorted(families, key=lambda f: f.name):
        lines.append(f"# HELP {fam.name} {fam.help or fam.name}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        if isinstance(fam, GaugeFamily) and fam.callback is not None:
            lines.append(f"{fam.name} {_format_value(fam.value())}")
            continue
        with fam._lock:
            items = sorted(fam._children.items())
            if isinstance(fam, HistogramFamily):
                for lv, c in items:
                    cum = 0
                    for edge, n in zip(fam.buckets, c.bucket_counts):
                        cum += n
                        ls = _label_str(
                            fam.labelnames, lv, ("le", _format_value(edge))
                        )
                        lines.append(f"{fam.name}_bucket{ls} {cum}")
                    ls = _label_str(fam.labelnames, lv, ("le", "+Inf"))
                    lines.append(f"{fam.name}_bucket{ls} {c.count}")
                    ls = _label_str(fam.labelnames, lv)
                    lines.append(
                        f"{fam.name}_sum{ls} {_format_value(c.sum)}"
                    )
                    lines.append(f"{fam.name}_count{ls} {c.count}")
            else:
                if not items and not fam.labelnames:
                    lines.append(f"{fam.name} 0")
                for lv, c in items:
                    ls = _label_str(fam.labelnames, lv)
                    lines.append(
                        f"{fam.name}{ls} {_format_value(c.value)}"
                    )
        if isinstance(fam, HistogramFamily):
            # exemplars ride as comment lines (a scraper that doesn't
            # understand them skips '#'; ours parses them back into the
            # fleet exemplar index). Emitted outside the family lock —
            # exemplars() takes it. The trailing token is the observing
            # label set as compact JSON (ISSUE 17 per-route indexing);
            # it is omitted for label-less families, which keeps the
            # 6-token legacy format parseable both ways.
            import json as _json

            for ex in fam.exemplars():
                line = (
                    f"# EXEMPLAR {fam.name} {ex['trace_id']} "
                    f"{repr(float(ex['value']))} {ex['ts']:.3f}"
                )
                if ex.get("labels"):
                    line += " " + _json.dumps(
                        ex["labels"], separators=(",", ":"),
                        sort_keys=True,
                    )
                lines.append(line)
    return "\n".join(lines) + "\n"


def render_merged(*registries: Optional[MetricsRegistry]) -> str:
    """Render several registries as one exposition document, first
    registry winning on family-name collisions (a server scrape shows its
    own registry plus the process-default one carrying train metrics)."""
    seen: set[str] = set()
    families: list[MetricFamily] = []
    for reg in registries:
        if reg is None:
            continue
        for fam in reg.families():
            if fam.name not in seen:
                seen.add(fam.name)
                families.append(fam)
    return render_families(families)


_default_registry = MetricsRegistry()


def get_default_registry() -> MetricsRegistry:
    """The process-wide registry: train workflows and anything not owned
    by a specific server record here."""
    return _default_registry
