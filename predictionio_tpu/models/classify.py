"""Classification kernels as XLA programs: multinomial NB + softmax LR.

Replaces the reference classification template's delegation to Spark MLlib
(`NaiveBayes.train(lambda)` and LogisticRegressionWithLBFGS, used by
examples/scala-parallel-classification/add-algorithm/src/main/scala/
NaiveBayesAlgorithm.scala:40 / RandomForestAlgorithm.scala).

TPU-first shape: both kernels are a handful of dense matmuls/segment-sums
over an (N, D) feature matrix staged to HBM once — NB training is one
segment-sum pass (label-indexed), LR is a jitted full-batch gradient loop
on the MXU. No per-row Python, no dynamic shapes."""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.ops.segment import segment_sum


# ---------------------------------------------------------------------------
# Multinomial naive Bayes (MLlib NaiveBayes parity: additive smoothing)
# ---------------------------------------------------------------------------


@dataclass
class NaiveBayesModel:
    log_prior: np.ndarray  # (C,)
    log_likelihood: np.ndarray  # (C, D)

    def predict_scores(self, x: np.ndarray) -> np.ndarray:
        """(B, C) log joint scores."""
        return np.asarray(_nb_scores(jnp.asarray(x), jnp.asarray(self.log_prior),
                                     jnp.asarray(self.log_likelihood)))

    def predict(self, x: np.ndarray) -> np.ndarray:
        return self.predict_scores(np.atleast_2d(x)).argmax(axis=-1)


@jax.jit
def _nb_scores(x, log_prior, log_like):
    return x @ log_like.T + log_prior  # MXU


@partial(jax.jit, static_argnames=("n_classes",))
def _nb_train(x, y, *, n_classes: int, lam: float):
    n, d = x.shape
    class_count = segment_sum(jnp.ones(n, jnp.float32), y, n_classes)
    feat_sum = segment_sum(x, y, n_classes)  # (C, D)
    log_prior = jnp.log(class_count) - jnp.log(jnp.float32(n))
    smoothed = feat_sum + lam
    log_like = jnp.log(smoothed) - jnp.log(
        jnp.sum(feat_sum, axis=1, keepdims=True) + lam * d
    )
    return log_prior, log_like


def train_naive_bayes(
    x: np.ndarray, y: np.ndarray, n_classes: int, lam: float = 1.0
) -> NaiveBayesModel:
    """x must be non-negative (multinomial counts / binary indicators)."""
    x = np.asarray(x, dtype=np.float32)
    y = np.asarray(y, dtype=np.int32)
    if (x < 0).any():
        raise ValueError("multinomial NB requires non-negative features")
    log_prior, log_like = _nb_train(
        jnp.asarray(x), jnp.asarray(y), n_classes=n_classes, lam=lam
    )
    return NaiveBayesModel(np.asarray(log_prior), np.asarray(log_like))


# ---------------------------------------------------------------------------
# Softmax (multinomial) logistic regression — full-batch GD under jit
# ---------------------------------------------------------------------------


@dataclass
class LogisticRegressionModel:
    weights: np.ndarray  # (D+1, C) — last row is the bias

    def predict_scores(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(
            _lr_scores(jnp.asarray(np.atleast_2d(x)), jnp.asarray(self.weights))
        )

    def predict(self, x: np.ndarray) -> np.ndarray:
        return self.predict_scores(x).argmax(axis=-1)


@jax.jit
def _lr_scores(x, w):
    return x @ w[:-1] + w[-1]


@partial(jax.jit, static_argnames=("n_classes", "iterations"))
def _lr_train(
    x, y, *, n_classes: int, iterations: int, lr: float, l2: float
):
    n, d = x.shape
    y1h = jax.nn.one_hot(y, n_classes, dtype=jnp.float32)

    def loss(w):
        logits = x @ w[:-1] + w[-1]
        ll = jnp.mean(
            jnp.sum(y1h * jax.nn.log_softmax(logits, axis=-1), axis=-1)
        )
        return -ll + 0.5 * l2 * jnp.sum(w[:-1] ** 2)

    grad = jax.grad(loss)

    def body(_, w):
        return w - lr * grad(w)

    w0 = jnp.zeros((d + 1, n_classes), jnp.float32)
    return jax.lax.fori_loop(0, iterations, body, w0)


def train_logistic_regression(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    iterations: int = 200,
    lr: float = 0.5,
    l2: float = 1e-4,
    normalize: bool = True,
) -> LogisticRegressionModel:
    x = np.asarray(x, dtype=np.float32)
    y = np.asarray(y, dtype=np.int32)
    if normalize:
        # scale features to unit stdev so a fixed lr behaves across datasets;
        # fold the scaling into the returned weights
        std = x.std(axis=0)
        std = np.where(std > 0, std, 1.0).astype(np.float32)
        x = x / std
    w = np.asarray(
        _lr_train(
            jnp.asarray(x), jnp.asarray(y),
            n_classes=n_classes, iterations=iterations, lr=lr, l2=l2,
        )
    )
    if normalize:
        w = np.concatenate([w[:-1] / std[:, None], w[-1:]], axis=0)
    return LogisticRegressionModel(weights=w)
