"""Classification kernels as XLA programs: multinomial NB + softmax LR.

Replaces the reference classification template's delegation to Spark MLlib
(`NaiveBayes.train(lambda)` and LogisticRegressionWithLBFGS, used by
examples/scala-parallel-classification/add-algorithm/src/main/scala/
NaiveBayesAlgorithm.scala:40 / RandomForestAlgorithm.scala).

TPU-first shape: both kernels are a handful of dense matmuls/segment-sums
over an (N, D) feature matrix staged to HBM once — NB training is one
segment-sum pass (label-indexed), LR is a jitted full-batch gradient loop
on the MXU. No per-row Python, no dynamic shapes."""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from predictionio_tpu.obs import devprof as _devprof

from predictionio_tpu.ops.segment import segment_sum


def _shard_batch(mesh, x, y, w):
    """Shard the (N, D) batch over the data axis with inert weight-0
    padding rows — the analogue of the reference's RDD partitioning of
    labeled points (e2 CategoricalNaiveBayes.scala aggregate / MLlib GD
    treeAggregate)."""
    from predictionio_tpu.parallel.mesh import pad_and_shard_rows

    return pad_and_shard_rows(mesh, x, y, w)


# ---------------------------------------------------------------------------
# Multinomial naive Bayes (MLlib NaiveBayes parity: additive smoothing)
# ---------------------------------------------------------------------------


@dataclass
class NaiveBayesModel:
    log_prior: np.ndarray  # (C,)
    log_likelihood: np.ndarray  # (C, D)

    def predict_scores(self, x: np.ndarray) -> np.ndarray:
        """(B, C) log joint scores."""
        return np.asarray(_nb_scores(jnp.asarray(x), jnp.asarray(self.log_prior),
                                     jnp.asarray(self.log_likelihood)))

    def predict(self, x: np.ndarray) -> np.ndarray:
        return self.predict_scores(np.atleast_2d(x)).argmax(axis=-1)


@jax.jit
def _nb_scores(x, log_prior, log_like):
    return x @ log_like.T + log_prior  # MXU


@partial(jax.jit, static_argnames=("n_classes",))
def _nb_train(x, y, w, *, n_classes: int, lam: float):
    d = x.shape[1]
    class_count = segment_sum(w, y, n_classes)
    feat_sum = segment_sum(x * w[:, None], y, n_classes)  # (C, D)
    log_prior = jnp.log(class_count) - jnp.log(jnp.sum(w))
    smoothed = feat_sum + lam
    log_like = jnp.log(smoothed) - jnp.log(
        jnp.sum(feat_sum, axis=1, keepdims=True) + lam * d
    )
    return log_prior, log_like


_nb_scores = _devprof.instrument("classify.nb_scores", _nb_scores)
_nb_train = _devprof.instrument("classify.nb_train", _nb_train)


def train_naive_bayes(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    lam: float = 1.0,
    mesh: Optional[jax.sharding.Mesh] = None,
) -> NaiveBayesModel:
    """x must be non-negative (multinomial counts / binary indicators).

    With `mesh`, the (N, D) batch is sharded over the data axis; the
    label-indexed segment-sums reduce locally per shard and GSPMD inserts
    the ICI all-reduce — the TPU-native analogue of the reference's
    aggregateByKey pass (e2 CategoricalNaiveBayes.scala:55-70)."""
    x = np.asarray(x, dtype=np.float32)
    y = np.asarray(y, dtype=np.int32)
    if (x < 0).any():
        raise ValueError("multinomial NB requires non-negative features")
    w = np.ones(x.shape[0], np.float32)
    if mesh is not None:
        xj, yj, wj = _shard_batch(mesh, x, y, w)
    else:
        xj, yj, wj = jnp.asarray(x), jnp.asarray(y), jnp.asarray(w)
    log_prior, log_like = _nb_train(xj, yj, wj, n_classes=n_classes, lam=lam)
    return NaiveBayesModel(np.asarray(log_prior), np.asarray(log_like))


@partial(jax.jit, static_argnames=("n_classes",))
def _nb_train_grid(x, y, w, lams, *, n_classes: int):
    # vmap over the smoothing grid: the data-dependent segment sums are
    # computed ONCE and closed over; only the O(C·D) smoothing/log math
    # vectorizes per grid point
    class_count = segment_sum(w, y, n_classes)
    feat_sum = segment_sum(x * w[:, None], y, n_classes)
    d = x.shape[1]

    def smooth(lam):
        log_prior = jnp.log(class_count) - jnp.log(jnp.sum(w))
        smoothed = feat_sum + lam
        log_like = jnp.log(smoothed) - jnp.log(
            jnp.sum(feat_sum, axis=1, keepdims=True) + lam * d
        )
        return log_prior, log_like

    return jax.vmap(smooth)(lams)


_nb_train_grid = _devprof.instrument(
    "classify.nb_train_grid", _nb_train_grid
)


def train_naive_bayes_grid(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    lams: Sequence[float],
) -> list[NaiveBayesModel]:
    """Whole smoothing grid in ONE device program (VERDICT r2 #9: tuning
    throughput — the expensive label-indexed segment sums run once, the
    per-lambda smoothing is vmapped)."""
    x = np.asarray(x, dtype=np.float32)
    y = np.asarray(y, dtype=np.int32)
    if (x < 0).any():
        raise ValueError("multinomial NB requires non-negative features")
    w = np.ones(x.shape[0], np.float32)
    priors, likes = _nb_train_grid(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(w),
        jnp.asarray(np.asarray(lams, np.float32)), n_classes=n_classes,
    )
    priors, likes = np.asarray(priors), np.asarray(likes)
    return [NaiveBayesModel(priors[g], likes[g]) for g in range(len(lams))]


# ---------------------------------------------------------------------------
# Softmax (multinomial) logistic regression — full-batch GD under jit
# ---------------------------------------------------------------------------


@dataclass
class LogisticRegressionModel:
    weights: np.ndarray  # (D+1, C) — last row is the bias

    def predict_scores(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(
            _lr_scores(jnp.asarray(np.atleast_2d(x)), jnp.asarray(self.weights))
        )

    def predict(self, x: np.ndarray) -> np.ndarray:
        return self.predict_scores(x).argmax(axis=-1)


@jax.jit
def _lr_scores(x, w):
    return x @ w[:-1] + w[-1]


@partial(jax.jit, static_argnames=("n_classes", "iterations"))
def _lr_train(
    x, y, wt, *, n_classes: int, iterations: int, lr: float, l2: float
):
    return _lr_train_body(
        x, y, wt, lr, l2, n_classes=n_classes, iterations=iterations
    )


_lr_scores = _devprof.instrument("classify.lr_scores", _lr_scores)
_lr_train = _devprof.instrument("classify.lr_train", _lr_train)


def train_logistic_regression(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    iterations: int = 200,
    lr: float = 0.5,
    l2: float = 1e-4,
    normalize: bool = True,
    mesh: Optional[jax.sharding.Mesh] = None,
) -> LogisticRegressionModel:
    """With `mesh`, the batch is sharded over the data axis and the
    full-batch gradient reduces via GSPMD psum — the analogue of MLlib
    LBFGS's treeAggregate gradient (used by LogisticRegressionWithLBFGS)."""
    x = np.asarray(x, dtype=np.float32)
    y = np.asarray(y, dtype=np.int32)
    if normalize:
        x, mu, std = _standardize(x)
    wt = np.ones(x.shape[0], np.float32)
    if mesh is not None:
        xj, yj, wtj = _shard_batch(mesh, x, y, wt)
    else:
        xj, yj, wtj = jnp.asarray(x), jnp.asarray(y), jnp.asarray(wt)
    w = np.asarray(
        _lr_train(
            xj, yj, wtj,
            n_classes=n_classes, iterations=iterations, lr=lr, l2=l2,
        )
    )
    if normalize:
        w = _fold_back_standardization(w, mu, std)
    return LogisticRegressionModel(weights=w)


def _standardize(x: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Center + scale so a fixed lr is stable across datasets — an
    uncentered mean component inflates the top Hessian eigenvalue past
    2/lr and GD amplifies float noise geometrically; the affine map folds
    back into the returned weights via _fold_back_standardization."""
    mu = x.mean(axis=0).astype(np.float32)
    std = x.std(axis=0)
    std = np.where(std > 0, std, 1.0).astype(np.float32)
    return (x - mu) / std, mu, std


def _fold_back_standardization(w, mu, std) -> np.ndarray:
    scaled = w[:-1] / std[:, None]
    bias = w[-1:] - (mu / std) @ w[:-1]
    return np.concatenate([scaled, bias], axis=0)


@partial(jax.jit, static_argnames=("n_classes", "iterations"))
def _lr_train_grid(x, y, wt, lrs, l2s, *, n_classes: int, iterations: int):
    def one(lr, l2):
        return _lr_train_body(
            x, y, wt, lr, l2, n_classes=n_classes, iterations=iterations
        )

    return jax.vmap(one)(lrs, l2s)


def _lr_train_body(x, y, wt, lr, l2, *, n_classes: int, iterations: int):
    d = x.shape[1]
    y1h = jax.nn.one_hot(y, n_classes, dtype=jnp.float32)

    def loss(w):
        logits = x @ w[:-1] + w[-1]
        row_ll = jnp.sum(y1h * jax.nn.log_softmax(logits, axis=-1), axis=-1)
        ll = jnp.sum(wt * row_ll) / jnp.sum(wt)
        return -ll + 0.5 * l2 * jnp.sum(w[:-1] ** 2)

    grad = jax.grad(loss)

    def body(_, w):
        return w - lr * grad(w)

    w0 = jnp.zeros((d + 1, n_classes), jnp.float32)
    return jax.lax.fori_loop(0, iterations, body, w0)


_lr_train_grid = _devprof.instrument(
    "classify.lr_train_grid", _lr_train_grid
)


def train_logistic_regression_grid(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    grid: Sequence[tuple[float, float]],  # (lr, l2) per point
    iterations: int = 200,
    normalize: bool = True,
) -> list[LogisticRegressionModel]:
    """Whole (lr, l2) grid as ONE vmapped GD program: G gradient loops run
    as a single batched device computation instead of G sequential jit
    dispatches (VERDICT r2 #9)."""
    x = np.asarray(x, dtype=np.float32)
    y = np.asarray(y, dtype=np.int32)
    if normalize:
        x, mu, std = _standardize(x)
    wt = np.ones(x.shape[0], np.float32)
    lrs = jnp.asarray([g[0] for g in grid], jnp.float32)
    l2s = jnp.asarray([g[1] for g in grid], jnp.float32)
    ws = np.asarray(
        _lr_train_grid(
            jnp.asarray(x), jnp.asarray(y), jnp.asarray(wt), lrs, l2s,
            n_classes=n_classes, iterations=iterations,
        )
    )
    out = []
    for g in range(len(grid)):
        w = ws[g]
        if normalize:
            w = _fold_back_standardization(w, mu, std)
        out.append(LogisticRegressionModel(weights=w))
    return out
