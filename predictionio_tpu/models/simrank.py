"""SimRank node-pair similarity as dense MXU matmul iteration.

Reference: examples/experimental/scala-parallel-friend-recommendation/
DeltaSimRankRDD.scala:14-50 — delta-SimRank over GraphX with per-pair
cartesian joins and reduceByKey shuffles (a sparsity optimization Spark
needs because each iteration is an all-pairs shuffle).

TPU-first re-design (NOT a port): SimRank's fixed point
    S(a,b) = C / (|I(a)||I(b)|) · Σ_{i∈I(a), j∈I(b)} S(i,j),  S(a,a)=1
is exactly the matrix iteration
    S ← C · Wᵀ S W,  then  diag(S) ← 1
with W the column-normalized in-adjacency (W[i, v] = 1/|I(v)| for
i ∈ I(v)). Two (N, N) matmuls per iteration run on the MXU — for the
graph sizes the reference demo handles (its SimRank example subsamples
to thousands of nodes; Sampling.scala) the dense form is both simpler
and faster than simulating the shuffle, and it is exact rather than
delta-approximate.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from predictionio_tpu.obs import devprof as _devprof

from predictionio_tpu.data.store.bimap import BiMap


@dataclass
class SimRankModel:
    scores: np.ndarray  # (N, N) float32 similarity matrix
    node_vocab: BiMap

    def top_k(self, node_idx: int, k: int) -> tuple[np.ndarray, np.ndarray]:
        """(scores, indices) of the k most similar OTHER nodes."""
        row = self.scores[node_idx].copy()
        row[node_idx] = -np.inf  # exclude self
        top = np.argsort(-row)[:k]
        return row[top], top


@partial(jax.jit, static_argnames=("iterations",))
def _simrank_jit(w: jax.Array, *, iterations: int, decay: float) -> jax.Array:
    n = w.shape[0]
    eye = jnp.eye(n, dtype=jnp.float32)

    def body(_, s):
        s = decay * (w.T @ s @ w)
        # pin the diagonal to 1 (the SimRank base case)
        return s * (1.0 - eye) + eye

    return jax.lax.fori_loop(0, iterations, body, eye)


_simrank_jit = _devprof.instrument(
    "simrank.iterate", _simrank_jit, scale_by="iterations"
)


def compute(
    src: np.ndarray,  # (E,) edge sources (node indices)
    dst: np.ndarray,  # (E,) edge destinations
    n_nodes: int,
    iterations: int = 5,
    decay: float = 0.8,
    node_vocab: BiMap | None = None,
) -> SimRankModel:
    """SimRank over a directed edge list. O(N²) memory — intended for the
    reference demo's scale (subsampled graphs of ~10³-10⁴ nodes)."""
    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    adj = np.zeros((n_nodes, n_nodes), dtype=np.float32)
    adj[src, dst] = 1.0  # duplicate edges collapse (simple graph)
    in_deg = adj.sum(axis=0)
    w = adj / np.maximum(in_deg, 1.0)[None, :]
    scores = np.asarray(
        _simrank_jit(jnp.asarray(w), iterations=iterations, decay=decay)
    )
    return SimRankModel(scores=scores, node_vocab=node_vocab or BiMap({}))


def simrank_reference(
    src: np.ndarray, dst: np.ndarray, n_nodes: int,
    iterations: int = 5, decay: float = 0.8,
) -> np.ndarray:
    """O(N²·E) literal-definition SimRank — test oracle only."""
    in_nb = [[] for _ in range(n_nodes)]
    for s, d in zip(src, dst):
        if s not in in_nb[d]:
            in_nb[d].append(int(s))
    s_mat = np.eye(n_nodes)
    for _ in range(iterations):
        nxt = np.zeros_like(s_mat)
        for a in range(n_nodes):
            for b in range(n_nodes):
                if a == b:
                    nxt[a, b] = 1.0
                    continue
                ia, ib = in_nb[a], in_nb[b]
                if not ia or not ib:
                    continue
                acc = sum(s_mat[i, j] for i in ia for j in ib)
                nxt[a, b] = decay * acc / (len(ia) * len(ib))
        s_mat = nxt
    return s_mat
