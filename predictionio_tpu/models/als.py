"""Alternating least squares (implicit + explicit) as an XLA program.

Replaces the reference templates' delegation to Spark MLlib ALS
(`ALS.trainImplicit` / `ALS.train`, used by
examples/scala-parallel-recommendation/*/ALSAlgorithm.scala:50-57 and the
similarproduct / ecommerce templates).

TPU-first design (NOT a port of MLlib's block-partitioned shuffle ALS):
- Interactions are a COO edge list staged to device once; each ALS
  half-step solves every row's k×k normal-equation system *simultaneously*
  with batched conjugate gradient, where the Gram-correction matvec is a
  matrix-free edge gather + segment-sum (ops/segment.py:edge_matvec).
  Memory stays O(E·k + (U+I)·k); no per-user k×k materialization, no
  factor-block shuffle.
- The whole alternating loop runs inside one jit with static shapes and
  `lax.fori_loop`; edges are pre-sorted per side on the host so segment
  reductions take the sorted fast path.
- Multi-chip: edges are sharded over the mesh's data axis; factor matrices
  are row-sharded over the model axis (replicated when mp == 1). GSPMD
  turns the segment-sum scatters into local partial sums + ICI
  all-reduce/all-gather — the TPU-native analogue of MLlib's shuffle
  (see parallel/mesh.py for mesh construction).

Implicit objective (Hu-Koren-Volinsky): confidence c = 1 + alpha·r,
preference p = 1; per-user system (YᵀY + Yᵀ(Cᵤ−I)Y + λI) xᵤ = YᵀCᵤpᵤ.
Explicit (ALS-WR): Σ_obs (r − x·y)² + λ(nᵤ‖xᵤ‖² + nᵢ‖yᵢ‖²).
"""

from __future__ import annotations

import io
import json
import logging
import os
from dataclasses import dataclass, field
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.data.store.bimap import BiMap
from predictionio_tpu.obs import devprof as _devprof
from predictionio_tpu.utils.env import env_int, env_str
from predictionio_tpu.ops.segment import (
    batched_cg,
    chunked_edge_matvec,
    chunked_gram_edge_sum,
    chunked_weighted_edge_sum,
    f32_gram,
)
from predictionio_tpu.ops.windowed import (
    flat_gram_matvec,
    plan_windows,
    windowed_gram_b,
)

# ranks up to this solve via explicitly-built per-row K×K operators (one
# edge pass per half-step); beyond it the matrix-free CG path keeps memory
# O(E·K) — the (N, K, K) operator tensor would start to dominate HBM
GRAM_SOLVER_MAX_RANK = 32
from predictionio_tpu.ops.topk import NEG_INF, masked_top_k


@dataclass(frozen=True)
class ALSParams:
    rank: int = 10
    iterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0  # implicit confidence scale
    implicit_prefs: bool = True
    cg_iterations: int = 3
    seed: int = 3
    # max edges per device program step; larger edge lists are scanned in
    # chunks so the lane-padded (E, K) gather intermediates stay bounded
    # (at ML-20M scale a single-shot build OOMs a 16G chip)
    edge_chunk_size: int = 1 << 21


@dataclass
class ALSFactors:
    """Trained factor matrices + id vocabularies."""

    user_factors: np.ndarray  # (U, K) float32
    item_factors: np.ndarray  # (I, K) float32
    user_vocab: BiMap  # user id → row
    item_vocab: BiMap  # item id → row
    params: ALSParams = field(default_factory=ALSParams)

    # -- persistence (replaces template IPersistentModel save/load) --------
    def to_bytes(self) -> bytes:
        buf = io.BytesIO()
        np.savez_compressed(
            buf,
            user_factors=self.user_factors,
            item_factors=self.item_factors,
            user_ids=np.array(list(self.user_vocab.to_dict().keys()), dtype=object),
            user_idx=np.array(list(self.user_vocab.to_dict().values()), dtype=np.int64),
            item_ids=np.array(list(self.item_vocab.to_dict().keys()), dtype=object),
            item_idx=np.array(list(self.item_vocab.to_dict().values()), dtype=np.int64),
            params=np.frombuffer(
                json.dumps(self.params.__dict__).encode(), dtype=np.uint8
            ),
        )
        return buf.getvalue()

    @staticmethod
    def from_bytes(data: bytes) -> "ALSFactors":
        with np.load(io.BytesIO(data), allow_pickle=True) as z:
            params = ALSParams(
                **json.loads(bytes(z["params"].tobytes()).decode())
            )
            user_vocab = BiMap(
                dict(zip(z["user_ids"].tolist(), z["user_idx"].tolist()))
            )
            item_vocab = BiMap(
                dict(zip(z["item_ids"].tolist(), z["item_idx"].tolist()))
            )
            return ALSFactors(
                user_factors=z["user_factors"],
                item_factors=z["item_factors"],
                user_vocab=user_vocab,
                item_vocab=item_vocab,
                params=params,
            )


# ---------------------------------------------------------------------------
# Core solver — windowed (scatter-free) path
# ---------------------------------------------------------------------------


def _half_step_windowed(
    fixed: jax.Array,  # (N_fixed_padded, K) — pad rows are exactly zero
    src: jax.Array,  # (n_chunks, CB, B_E) — rows into `fixed`
    val: jax.Array,  # (n_chunks, CB, B_E) — ratings (0 on pads)
    ok: jax.Array,  # (n_chunks, CB, B_E) — 1.0 real edge / 0.0 padding
    loc: jax.Array,  # (n_chunks, CB, B_E) — dst % WINDOW_ROWS
    bwin: jax.Array,  # (n_blocks_p,) — output window per block
    degree: jax.Array,  # (N_dst_padded,) — for ALS-WR reg (explicit only)
    x0: jax.Array,  # (N_dst_padded, K) warm start
    *,
    n_windows: int,
    implicit: bool,
    lam: float,
    alpha: float,
    cg_iterations: int,
    pallas_mode: Optional[str] = None,
    mesh=None,
) -> jax.Array:
    """One ALS half-step with the windowed one-hot reduction: a single
    fused edge pass builds b and all per-row gram corrections, then CG
    runs dense on the FLAT (N, K²) operators (flat_gram_matvec)."""
    n_dst, k = x0.shape
    if implicit:
        # implicit operator: YᵀY + Σ(c−1)yyᵀ + λI  (global gram term)
        gram = f32_gram(fixed)
        conf = 1.0 + alpha * jnp.abs(val)
        pref = (val > 0).astype(jnp.float32)
        w_b = conf * pref * ok
        w_g = (conf - 1.0) * ok
        b, corr_flat = windowed_gram_b(
            fixed, src, w_b, w_g, loc, bwin, n_windows,
            pallas=pallas_mode, mesh=mesh,
        )
        base = gram + lam * jnp.eye(k, dtype=jnp.float32)
        a_flat = corr_flat + base.reshape(1, k * k)
    else:
        # explicit (ALS-WR) operator: Σ_obs yyᵀ + λ·max(deg,1)·I
        w_b = val * ok
        w_g = ok
        b, corr_flat = windowed_gram_b(
            fixed, src, w_b, w_g, loc, bwin, n_windows,
            pallas=pallas_mode, mesh=mesh,
        )
        reg = lam * jnp.maximum(degree, 1.0)
        eye_flat = jnp.eye(k, dtype=jnp.float32).reshape(1, k * k)
        a_flat = corr_flat + reg[:, None] * eye_flat

    def matvec(v):
        return flat_gram_matvec(a_flat, v)

    return batched_cg(matvec, b, x0, cg_iterations)


# ---------------------------------------------------------------------------
# Core solver — dense-W fast path (sub-1%-density rating matrices)
# ---------------------------------------------------------------------------

# auto-dispatch bound for the bf16 dense rating matrix (ML-20M needs
# 7.45 GB of a 16 GB chip); PIO_DENSE_ALS=0 disables, =1 forces where it
# fits, PIO_DENSE_ALS_BYTES overrides the budget
DENSE_DEFAULT_BYTES = 9_000_000_000
# below this edge count the windowed path's staging is already cheap and
# CPU test suites compare against f32-exact references — auto keeps them
# on the windowed path unless PIO_DENSE_ALS=1 opts in
DENSE_AUTO_MIN_EDGES = 1_000_000


def _dense_half_step(
    r: jax.Array,
    fixed: jax.Array,  # factors of the side NOT being solved
    degree: jax.Array,  # (n_solved_p,) — -1 marks padding rows
    x0: jax.Array,
    *,
    solve_rows: bool,  # True: solve R's row side; False: its column side
    implicit: bool,
    lam: float,
    alpha: float,
    cg_iterations: int,
    dense_dtype: str,
    scale: float = 1.0,
    pallas_mode=None,
) -> jax.Array:
    """One ALS half-step with b/gram built by dense matmuls over R.

    Identical operator assembly + CG to the windowed path — only the
    edge pass differs: the fused Pallas kernel (ops/dense_pallas.py —
    ONE R read per pass, both weight tiles derived in VMEM) when
    `pallas_mode` is set and the storage is int8 with clean tile
    divisors, else the XLA two-dot scan (ops/dense.py). Padding rows
    have all-zero R and b=0, x0=0, so CG freezes them at zero exactly
    like window padding."""
    from predictionio_tpu.ops import dense

    k = x0.shape[1]
    use_kernel = pallas_mode is not None and r.dtype == jnp.int8
    if use_kernel:
        from predictionio_tpu.ops import dense_pallas

        rt, ct = dense_pallas.pick_tiles(*r.shape)
        use_kernel = rt > 0 and ct > 0
    if use_kernel:
        y32 = fixed.astype(jnp.float32)
        z32 = (
            fixed[:, :, None] * fixed[:, None, :]
        ).reshape(fixed.shape[0], k * k).astype(jnp.float32)
        ascale = jnp.asarray(
            [alpha / scale if implicit else 1.0 / scale], jnp.float32
        )
        fused = (
            dense_pallas.fused_row_pass
            if solve_rows
            else dense_pallas.fused_col_pass
        )
        b, corr_flat = fused(
            r, y32, z32, ascale, implicit=implicit,
            interpret=(pallas_mode == "interpret"),
            row_tile=rt, col_tile=ct,
        )
    else:
        edge_pass = (
            dense.dense_row_pass if solve_rows else dense.dense_col_pass
        )
        b, corr_flat = edge_pass(
            r, fixed, implicit=implicit, alpha=alpha,
            dense_dtype=dense_dtype, scale=scale,
        )
    if implicit:
        gram = f32_gram(fixed)
        base = gram + lam * jnp.eye(k, dtype=jnp.float32)
        a_flat = corr_flat + base.reshape(1, k * k)
    else:
        reg = lam * jnp.maximum(degree, 1.0)
        eye_flat = jnp.eye(k, dtype=jnp.float32).reshape(1, k * k)
        a_flat = corr_flat + reg[:, None] * eye_flat

    def matvec(v):
        return flat_gram_matvec(a_flat, v)

    return batched_cg(matvec, b, x0, cg_iterations)


@partial(
    jax.jit,
    static_argnames=(
        "rank", "iterations", "implicit", "cg_iterations", "dense_dtype",
        "scale", "pallas_mode",
    ),
)
def _train_jit_dense(
    r: jax.Array,  # (n_users_p, n_items_p) dense storage-dtype ratings
    user_deg: jax.Array,  # (n_users_p,), -1 on padding rows
    item_deg: jax.Array,  # (n_items_p,)
    uf0=None,
    itf0=None,
    *,
    rank: int,
    iterations: int,
    implicit: bool,
    lam: float,
    alpha: float,
    cg_iterations: int,
    seed: int,
    dense_dtype: str = "bf16",
    scale: float = 1.0,
    pallas_mode=None,
):
    """Whole alternating loop on the dense-W path: every half-step is two
    dense matmuls + the shared flat-operator CG. R enters as a jit
    ARGUMENT (a loop invariant produced by fused ops would risk the TPU
    fori-loop miscompile batched_cg's docstring records)."""
    n_users_p, n_items_p = r.shape
    if uf0 is not None and itf0 is not None:
        uf, itf = uf0, itf0
    else:
        ku, ki = jax.random.split(jax.random.PRNGKey(seed))
        # partitionable threefry: element i's bits depend only on (key,
        # i), not the array size — so the sharded trains (whose padded
        # shapes differ with dp/mp) slice IDENTICAL inits from their
        # larger draws and match this path exactly (newer jax defaults
        # to this; the pin makes the parity hold on every version)
        with jax.threefry_partitionable(True):
            uf = (
                jax.random.normal(ku, (n_users_p, rank), jnp.float32)
                / jnp.sqrt(rank)
            ) * (user_deg >= 0)[:, None]
            itf = (
                jax.random.normal(ki, (n_items_p, rank), jnp.float32)
                / jnp.sqrt(rank)
            ) * (item_deg >= 0)[:, None]

    def body(_, fs):
        uf, itf = fs
        uf = _dense_half_step(
            r, itf, user_deg, uf, solve_rows=True, implicit=implicit,
            lam=lam, alpha=alpha, cg_iterations=cg_iterations,
            dense_dtype=dense_dtype, scale=scale,
            pallas_mode=pallas_mode,
        )
        itf = _dense_half_step(
            r, uf, item_deg, itf, solve_rows=False, implicit=implicit,
            lam=lam, alpha=alpha, cg_iterations=cg_iterations,
            dense_dtype=dense_dtype, scale=scale,
            pallas_mode=pallas_mode,
        )
        return uf, itf

    return jax.lax.fori_loop(0, iterations, body, (uf, itf))


# device profiling (ISSUE 3): each top-level train program is a named
# executable in the registry. scale_by="iterations" corrects XLA's HLO
# cost analysis counting the fori_loop body once regardless of trip
# count (see obs/devprof.py); memory_analysis stays off — these are the
# multi-second compiles a duplicate AOT compile must not double.
_train_jit_dense = _devprof.instrument(
    "als.train_dense", _train_jit_dense, scale_by="iterations"
)


@partial(
    jax.jit,
    static_argnames=(
        "rank", "iterations", "implicit", "cg_iterations", "dense_dtype",
        "scale",
    ),
)
def _train_jit_dense_grid(
    r: jax.Array,
    user_deg: jax.Array,
    item_deg: jax.Array,
    lams: jax.Array,  # (G,)
    alphas: jax.Array,  # (G,)
    *,
    rank: int,
    iterations: int,
    implicit: bool,
    cg_iterations: int,
    seed: int,
    dense_dtype: str = "bf16",
    scale: float = 1.0,
):
    """(λ, α) grid on the dense path: R is closed over (vmap broadcasts
    it — ONE device matrix serves every grid point); the weight
    derivations and solves batch over the grid axis."""

    def one(lam, alpha):
        return _train_jit_dense(
            r, user_deg, item_deg,
            rank=rank, iterations=iterations, implicit=implicit,
            lam=lam, alpha=alpha, cg_iterations=cg_iterations, seed=seed,
            dense_dtype=dense_dtype, scale=scale,
        )

    return jax.vmap(one)(lams, alphas)


@partial(
    jax.jit,
    static_argnames=(
        "rank", "iterations", "implicit", "cg_iterations", "dense_dtype",
        "scale", "mesh",
    ),
)
def _train_jit_dense_sharded(
    r: jax.Array,  # (n_users_p, n_items_p) — row-sharded over dp
    user_deg: jax.Array,  # (n_users_p,) — row-sharded over dp
    item_deg: jax.Array,  # (n_items_p,) — replicated
    uf0=None,  # (n_users_p, rank) row-sharded / None
    itf0=None,  # (n_items_p, rank) replicated / None
    *,
    rank: int,
    iterations: int,
    implicit: bool,
    lam: float,
    alpha: float,
    cg_iterations: int,
    seed: int,
    dense_dtype: str = "bf16",
    scale: float = 1.0,
    mesh=None,
):
    """Dense-W alternating loop shard_map'd over the mesh.

    With mp == 1 (the PR-7 shape): the rating matrix is ROW-sharded
    over dp (each device owns a slab of users); factors stay
    replicated. Per iteration:

      user half: each device solves ITS user rows from its local slab —
                 fully local, zero collectives;
      item half: each device contracts its slab against its local user
                 factors into partial (n_items, ·) sums; ONE psum over
                 dp combines them and every device solves the (small)
                 item systems redundantly.

    With mp > 1 (ISSUE 10 model-axis sharding, activated by the
    engine.json `mesh` key): R is 2-D block-sharded (users over dp,
    items over mp), USER factors are row-sharded over dp and ITEM
    factors row-sharded over mp — no device ever holds a full factor
    matrix, so the factor state scales past one chip's HBM. Each
    half-step's cross-side normal-equation assembly becomes partial
    per-block sums + ONE all-reduce over the OPPOSITE axis (user half:
    psum over mp assembles b/Gram from the item shards; item half: psum
    over dp), then each shard solves only the systems of the rows it
    owns — the gather/all-reduce shape of MLlib ALS's block shuffle.

    This is the TPU-native shape of MLlib ALS's block distribution: the
    ratings never move, only the (tiny) factor matrices ride ICI.

    VALIDATION CAVEAT: the alternating fori_loop here reads the large
    sharded slab inside shard_map — the shape of program the recorded
    TPU fori-loop miscompile (batched_cg's docstring) bit at FULL scale
    while small shapes passed. This rig has one chip, so the sharded
    path is validated on CPU meshes + the dryrun only; the first real
    multi-chip deployment must re-run the bench's full-scale
    finiteness + windowed-agreement checks before trusting factors."""
    from predictionio_tpu.ops import dense as dense_ops
    from predictionio_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    n_users_p, n_items_p = r.shape
    if int(mesh.shape.get(MODEL_AXIS, 1)) > 1:
        return _dense_sharded_2d(
            r, user_deg, item_deg, uf0, itf0,
            rank=rank, iterations=iterations, implicit=implicit,
            lam=lam, alpha=alpha, cg_iterations=cg_iterations,
            seed=seed, dense_dtype=dense_dtype, scale=scale, mesh=mesh,
        )
    spec_r = jax.sharding.PartitionSpec(DATA_AXIS, None)
    spec_v = jax.sharding.PartitionSpec(DATA_AXIS)
    rep2 = jax.sharding.PartitionSpec(None, None)
    rep1 = jax.sharding.PartitionSpec(None)

    def local_train(r_l, udeg_l, ideg, uf0_l, itf0_r):
        n_u_local = r_l.shape[0]
        d = jax.lax.axis_index(DATA_AXIS)
        if uf0_l is not None and itf0_r is not None:
            uf_l, itf = uf0_l, itf0_r
        else:
            ku, ki = jax.random.split(jax.random.PRNGKey(seed))
            # generate the FULL init on every device (replicated
            # compute, deterministic) and slice the local slab so the
            # sharded run matches the single-device run exactly;
            # partitionable threefry makes the draw shape-stable, so
            # the differently-padded single-device init is a prefix
            with jax.threefry_partitionable(True):
                uf_full = (
                    jax.random.normal(ku, (n_users_p, rank), jnp.float32)
                    / jnp.sqrt(rank)
                )
                itf = (
                    jax.random.normal(ki, (n_items_p, rank), jnp.float32)
                    / jnp.sqrt(rank)
                ) * (ideg >= 0)[:, None]
            uf_l = jax.lax.dynamic_slice_in_dim(
                uf_full, d * n_u_local, n_u_local
            ) * (udeg_l >= 0)[:, None]

        k = rank
        eye_flat = jnp.eye(k, dtype=jnp.float32).reshape(1, k * k)

        def body(_, fs):
            uf_l, itf = fs
            # user half: local rows, local slab — no collectives
            uf_l = _dense_half_step(
                r_l, itf, udeg_l, uf_l, solve_rows=True,
                implicit=implicit, lam=lam, alpha=alpha,
                cg_iterations=cg_iterations, dense_dtype=dense_dtype,
                scale=scale,
            )
            # item half: partial sums from the local slab + ONE psum
            b, corr_flat = dense_ops.dense_col_pass(
                r_l, uf_l, implicit=implicit, alpha=alpha,
                dense_dtype=dense_dtype, scale=scale,
            )
            b = jax.lax.psum(b, DATA_AXIS)
            corr_flat = jax.lax.psum(corr_flat, DATA_AXIS)
            if implicit:
                gram = jax.lax.psum(f32_gram(uf_l), DATA_AXIS)
                base = gram + lam * jnp.eye(k, dtype=jnp.float32)
                a_flat = corr_flat + base.reshape(1, k * k)
            else:
                reg = lam * jnp.maximum(ideg, 1.0)
                a_flat = corr_flat + reg[:, None] * eye_flat

            def matvec(v):
                return flat_gram_matvec(a_flat, v)

            itf = batched_cg(matvec, b, itf, cg_iterations)
            return uf_l, itf

        return jax.lax.fori_loop(0, iterations, body, (uf_l, itf))

    # shard_map cannot spec None leaves — close over absent inits
    if uf0 is None or itf0 is None:
        fn = lambda r_l, udeg_l, ideg: local_train(
            r_l, udeg_l, ideg, None, None
        )
        args = (r, user_deg, item_deg)
        in_specs = (spec_r, spec_v, rep1)
    else:
        fn = local_train
        args = (r, user_deg, item_deg, uf0, itf0)
        in_specs = (spec_r, spec_v, rep1, spec_r, rep2)
    from predictionio_tpu.parallel.mesh import shard_map as _shard_map

    return _shard_map(
        fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(spec_r, rep2),
        check=False,
    )(*args)


def _dense_sharded_2d(
    r: jax.Array,  # (n_users_p, n_items_p) — block-sharded (dp, mp)
    user_deg: jax.Array,  # (n_users_p,) — sharded over dp
    item_deg: jax.Array,  # (n_items_p,) — sharded over mp
    uf0,  # (n_users_p, rank) sharded over dp / None
    itf0,  # (n_items_p, rank) sharded over mp / None
    *,
    rank: int,
    iterations: int,
    implicit: bool,
    lam: float,
    alpha: float,
    cg_iterations: int,
    seed: int,
    dense_dtype: str,
    scale: float,
    mesh,
):
    """The mp > 1 body of `_train_jit_dense_sharded` (ISSUE 10): R is
    2-D block-sharded, user factors live row-sharded over dp and item
    factors row-sharded over mp. Each half-step runs the SAME
    dense_row/col_pass kernels on the local block; the cross-side
    normal-equation assembly is one psum over the opposite axis (plus
    one for the implicit-mode global Gram), then each shard solves only
    its own rows' K×K systems. Inits are generated replicated from the
    same PRNG stream as the single-device path and sliced, so mp-
    sharded factors match the unsharded solve to f32 reduction-order
    tolerance."""
    from predictionio_tpu.ops import dense as dense_ops
    from predictionio_tpu.parallel.mesh import (
        DATA_AXIS,
        MODEL_AXIS,
        shard_map as _shard_map,
    )

    n_users_p, n_items_p = r.shape
    spec_r = jax.sharding.PartitionSpec(DATA_AXIS, MODEL_AXIS)
    spec_u1 = jax.sharding.PartitionSpec(DATA_AXIS)
    spec_i1 = jax.sharding.PartitionSpec(MODEL_AXIS)
    spec_u2 = jax.sharding.PartitionSpec(DATA_AXIS, None)
    spec_i2 = jax.sharding.PartitionSpec(MODEL_AXIS, None)

    def local_train(r_l, udeg_l, ideg_l, uf0_l, itf0_l):
        n_u_local, n_i_local = r_l.shape
        d = jax.lax.axis_index(DATA_AXIS)
        m = jax.lax.axis_index(MODEL_AXIS)
        if uf0_l is not None and itf0_l is not None:
            uf_l, itf_l = uf0_l, itf0_l
        else:
            ku, ki = jax.random.split(jax.random.PRNGKey(seed))
            # full init generated on every device (replicated compute,
            # deterministic), sliced to the local slab — identical
            # numbers to the single-device init (partitionable threefry
            # makes the draw a shape-stable prefix, see _train_jit_dense)
            with jax.threefry_partitionable(True):
                uf_full = (
                    jax.random.normal(ku, (n_users_p, rank), jnp.float32)
                    / jnp.sqrt(rank)
                )
                itf_full = (
                    jax.random.normal(ki, (n_items_p, rank), jnp.float32)
                    / jnp.sqrt(rank)
                )
            uf_l = jax.lax.dynamic_slice_in_dim(
                uf_full, d * n_u_local, n_u_local
            ) * (udeg_l >= 0)[:, None]
            itf_l = jax.lax.dynamic_slice_in_dim(
                itf_full, m * n_i_local, n_i_local
            ) * (ideg_l >= 0)[:, None]

        k = rank
        eye = jnp.eye(k, dtype=jnp.float32)
        eye_flat = eye.reshape(1, k * k)

        def body(_, fs):
            uf_l, itf_l = fs
            # user half: partial sums over MY item columns; psum over
            # mp assembles each user row's full b and Gram correction
            b, corr_flat = dense_ops.dense_row_pass(
                r_l, itf_l, implicit=implicit, alpha=alpha,
                dense_dtype=dense_dtype, scale=scale,
            )
            b = jax.lax.psum(b, MODEL_AXIS)
            corr_flat = jax.lax.psum(corr_flat, MODEL_AXIS)
            if implicit:
                gram = jax.lax.psum(f32_gram(itf_l), MODEL_AXIS)
                a_flat = corr_flat + (gram + lam * eye).reshape(1, k * k)
            else:
                reg = lam * jnp.maximum(udeg_l, 1.0)
                a_flat = corr_flat + reg[:, None] * eye_flat
            uf_l = batched_cg(
                lambda v: flat_gram_matvec(a_flat, v), b, uf_l,
                cg_iterations,
            )
            # item half: partial sums over MY user rows; psum over dp
            b, corr_flat = dense_ops.dense_col_pass(
                r_l, uf_l, implicit=implicit, alpha=alpha,
                dense_dtype=dense_dtype, scale=scale,
            )
            b = jax.lax.psum(b, DATA_AXIS)
            corr_flat = jax.lax.psum(corr_flat, DATA_AXIS)
            if implicit:
                gram = jax.lax.psum(f32_gram(uf_l), DATA_AXIS)
                a_flat = corr_flat + (gram + lam * eye).reshape(1, k * k)
            else:
                reg = lam * jnp.maximum(ideg_l, 1.0)
                a_flat = corr_flat + reg[:, None] * eye_flat
            itf_l = batched_cg(
                lambda v: flat_gram_matvec(a_flat, v), b, itf_l,
                cg_iterations,
            )
            return uf_l, itf_l

        return jax.lax.fori_loop(0, iterations, body, (uf_l, itf_l))

    # shard_map cannot spec None leaves — close over absent inits
    if uf0 is None or itf0 is None:
        fn = lambda r_l, udeg_l, ideg_l: local_train(
            r_l, udeg_l, ideg_l, None, None
        )
        args = (r, user_deg, item_deg)
        in_specs = (spec_r, spec_u1, spec_i1)
    else:
        fn = local_train
        args = (r, user_deg, item_deg, uf0, itf0)
        in_specs = (spec_r, spec_u1, spec_i1, spec_u2, spec_i2)
    return _shard_map(
        fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(spec_u2, spec_i2),
        check=False,
    )(*args)


_train_jit_dense_grid = _devprof.instrument(
    "als.train_dense_grid", _train_jit_dense_grid, scale_by="iterations"
)
_train_jit_dense_sharded = _devprof.instrument(
    "als.train_dense_sharded", _train_jit_dense_sharded,
    scale_by="iterations",
)


@dataclass
class StagedDenseTrain:
    """A dense-path train with the rating matrix resident on device.

    Mirrors StagedWindowedTrain: built once per training set by
    `stage_dense`; `run()` re-executes the compiled alternating loop
    with zero further host→device traffic."""

    device_args: tuple
    static_kwargs: dict
    n_users: int
    n_items: int
    host_prep_sec: float
    transfer_sec: float

    def run(self) -> tuple[jax.Array, jax.Array]:
        if self.static_kwargs.get("mesh") is not None:
            kwargs = {
                k: v
                for k, v in self.static_kwargs.items()
                if k != "pallas_mode"
            }
            return _train_jit_dense_sharded(*self.device_args, **kwargs)
        kwargs = {
            k: v for k, v in self.static_kwargs.items() if k != "mesh"
        }
        return _train_jit_dense(*self.device_args, **kwargs)

    def factors(self, uf, itf) -> tuple[np.ndarray, np.ndarray]:
        return np.asarray(uf)[: self.n_users], np.asarray(itf)[: self.n_items]


def dense_matrix_bytes(
    n_users: int, n_items: int, dense_dtype: str = "bf16", dp: int = 1,
    mp: int = 1,
) -> int:
    """Padded dense-R footprint — the auto-dispatch gate's input.
    `dp` > 1 pads rows (and `mp` > 1 columns) to whole per-device slabs
    (stage_dense does)."""
    from predictionio_tpu.ops.dense import (
        BYTES_PER_CELL,
        COL_PAD,
        ROW_BLOCK,
    )

    n_u_p = -(-n_users // (ROW_BLOCK * dp)) * (ROW_BLOCK * dp)
    n_i_p = -(-n_items // (COL_PAD * mp)) * (COL_PAD * mp)
    return n_u_p * n_i_p * BYTES_PER_CELL.get(dense_dtype, 2)


def dense_eligible(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_users: int,
    n_items: int,
    params: "ALSParams",
    mesh=None,
    dense_dtype: str = "bf16",
) -> bool:
    """Gate for the dense-W fast path.

    Requires: env not opting out, rank within the gram-solver bound,
    single-process execution when a mesh is given (the shard_map'd dense
    train row-shards R over dp; multi-host R staging is not wired, so
    multi-host falls back to the windowed path), the padded matrix
    within the HBM budget, unique (user, item) pairs (a dense cell can
    hold one rating; duplicate edges are summed by the windowed path, so
    dup data falls back to preserve semantics), and — explicit mode only
    — no zero-valued ratings (a dense zero must mean "unobserved").
    Auto mode also requires DENSE_AUTO_MIN_EDGES so small (test-scale)
    trains keep their f32-exact windowed numerics unless PIO_DENSE_ALS=1
    opts in."""
    env = env_str("PIO_DENSE_ALS").strip()
    if env == "0":
        return False
    if params.rank > GRAM_SOLVER_MAX_RANK:
        return False
    if mesh is not None and jax.process_count() > 1:
        return False
    if env != "1" and len(rows) < DENSE_AUTO_MIN_EDGES:
        return False
    budget = env_int("PIO_DENSE_ALS_BYTES", DENSE_DEFAULT_BYTES)
    if dense_dtype == "bf16":  # the default: predict what auto picks
        from predictionio_tpu.ops.dense import int8_scale

        if int8_scale(vals) is not None:
            dense_dtype = "int8"
    dp = mp = 1
    if mesh is not None and getattr(mesh, "devices", None) is not None:
        from predictionio_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        dp = int(mesh.shape.get(DATA_AXIS, 1))
        mp = int(mesh.shape.get(MODEL_AXIS, 1))
    if dense_matrix_bytes(
        n_users, n_items, dense_dtype, dp=dp, mp=mp
    ) > budget:
        return False
    if not params.implicit_prefs and np.any(vals == 0.0):
        return False
    key = rows.astype(np.int64) * np.int64(n_items) + cols.astype(np.int64)
    if np.unique(key).size != len(key):
        logging.getLogger(__name__).info(
            "dense ALS path skipped: duplicate (user, item) pairs"
        )
        return False
    return True


def _dense_pallas_mode():
    from predictionio_tpu.ops import dense_pallas

    return dense_pallas.resolve_mode("auto")


def stage_dense(
    rows, cols, vals, n_users, n_items, params,
    user_deg=None, item_deg=None, init_factors=None,
    dense_dtype: str = "auto",
    mesh=None,
) -> StagedDenseTrain:
    """Stage the dense-path train: pad dims to the block quanta, push the
    COO arrays once, densify ON DEVICE (the matrix never crosses the
    host link), and keep it resident.

    dense_dtype "auto" prefers int8 storage when every rating is exactly
    representable as round(r·s) for a small scale s (ML-style ratings
    are) — half the footprint and HBM stream of bf16, with block-local
    dequantization; otherwise bf16. "f32" is the exactness mode tests
    compare against the windowed path with."""
    import time as _time

    from predictionio_tpu.ops.dense import (
        COL_PAD,
        ROW_BLOCK,
        densify,
        int8_scale,
    )

    t0 = _time.perf_counter()
    rows = np.asarray(rows, dtype=np.int32)
    cols = np.asarray(cols, dtype=np.int32)
    vals = np.asarray(vals, dtype=np.float32)
    scale = 1.0
    if dense_dtype in ("auto", "int8"):
        s_q = int8_scale(vals)
        if s_q is not None:
            dense_dtype, scale = "int8", s_q
        elif dense_dtype == "int8":
            raise ValueError(
                "dense_dtype='int8' but ratings are not exactly int8-"
                "quantizable; use 'bf16' or 'auto'"
            )
        else:
            dense_dtype = "bf16"
    dp = mp = 1
    if mesh is not None and mesh.devices.size > 1:
        from predictionio_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        dp = int(mesh.shape.get(DATA_AXIS, 1))
        mp = int(mesh.shape.get(MODEL_AXIS, 1))
    # user rows pad to a slab multiple so every dp device scans whole
    # row blocks of its own slab; with mp > 1 (ISSUE 10) item columns
    # pad likewise so every mp device owns whole COL_PAD column blocks
    n_u_p = -(-n_users // (ROW_BLOCK * dp)) * (ROW_BLOCK * dp)
    n_i_p = -(-n_items // (COL_PAD * mp)) * (COL_PAD * mp)
    if user_deg is None:
        user_deg = np.zeros(n_users, np.float32)
        np.add.at(user_deg, rows, 1.0)
    if item_deg is None:
        item_deg = np.zeros(n_items, np.float32)
        np.add.at(item_deg, cols, 1.0)

    def pad_deg(deg, n_padded):
        out = np.full(n_padded, -1.0, np.float32)  # -1 marks padding
        out[: len(deg)] = deg
        return out

    uf0 = itf0 = None
    if init_factors is not None:
        uf_in = np.asarray(init_factors[0], np.float32)
        itf_in = np.asarray(init_factors[1], np.float32)
        if uf_in.shape != (n_users, params.rank) or itf_in.shape != (
            n_items, params.rank,
        ):
            raise ValueError(
                "init_factors shapes do not match (n_users/n_items, rank)"
            )
        uf0 = np.zeros((n_u_p, params.rank), np.float32)
        uf0[:n_users] = uf_in
        itf0 = np.zeros((n_i_p, params.rank), np.float32)
        itf0[:n_items] = itf_in
    host_prep = _time.perf_counter() - t0

    t0 = _time.perf_counter()
    r = densify(
        jnp.asarray(rows), jnp.asarray(cols), jnp.asarray(vals),
        n_rows_p=n_u_p, n_cols_p=n_i_p, dense_dtype=dense_dtype,
        scale=scale,
    )
    if mesh is not None and mesh.devices.size > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from predictionio_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        row_sh = NamedSharding(mesh, P(DATA_AXIS, None))
        vec_sh = NamedSharding(mesh, P(DATA_AXIS))
        rep = NamedSharding(mesh, P())
        if mp > 1:
            # model-axis sharding (ISSUE 10): R 2-D block-sharded, item
            # degree/factors row-sharded over mp — no device holds a
            # full factor matrix
            r_sh = NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS))
            ideg_sh = NamedSharding(mesh, P(MODEL_AXIS))
            itf_sh = NamedSharding(mesh, P(MODEL_AXIS, None))
        else:
            r_sh, ideg_sh, itf_sh = row_sh, rep, rep
        device_args = (
            jax.device_put(r, r_sh),
            jax.device_put(pad_deg(user_deg, n_u_p), vec_sh),
            jax.device_put(pad_deg(item_deg, n_i_p), ideg_sh),
            jax.device_put(uf0, row_sh) if uf0 is not None else None,
            jax.device_put(itf0, itf_sh) if itf0 is not None else None,
        )
    else:
        device_args = (
            r,
            jax.device_put(pad_deg(user_deg, n_u_p)),
            jax.device_put(pad_deg(item_deg, n_i_p)),
            jax.device_put(uf0) if uf0 is not None else None,
            jax.device_put(itf0) if itf0 is not None else None,
        )
    # a tiny HOST FETCH, not just block_until_ready: draining the device
    # queue through a fetch lets the densify transients actually
    # deallocate before the train program's workspace is allocated —
    # without it the first big train reproducibly hits RESOURCE_EXHAUSTED
    # at ML-20M on a 16 GB chip (observed on the axon transport, whose
    # frees are deferred until a sync point)
    np.asarray(r[:1, :8])
    transfer = _time.perf_counter() - t0
    return StagedDenseTrain(
        device_args=device_args,
        static_kwargs=dict(
            rank=params.rank,
            iterations=params.iterations,
            implicit=params.implicit_prefs,
            lam=params.lambda_,
            alpha=params.alpha,
            cg_iterations=params.cg_iterations,
            seed=params.seed,
            dense_dtype=dense_dtype,
            scale=scale,
            # resolved OUTSIDE the jit; the grid (vmap) and sharded
            # (shard_map) variants exclude the kernel for now — pop it
            pallas_mode=(
                None
                if (mesh is not None and mesh.devices.size > 1)
                else _dense_pallas_mode()
            ),
            mesh=mesh if (mesh is not None and mesh.devices.size > 1) else None,
        ),
        n_users=n_users,
        n_items=n_items,
        host_prep_sec=host_prep,
        transfer_sec=transfer,
    )


def _train_dense(
    rows, cols, vals, n_users, n_items, params,
    user_deg, item_deg, user_vocab, item_vocab, init_factors,
    dense_dtype: str = "auto",
    mesh=None,
) -> "ALSFactors":
    staged = stage_dense(
        rows, cols, vals, n_users, n_items, params,
        user_deg=user_deg, item_deg=item_deg, init_factors=init_factors,
        dense_dtype=dense_dtype, mesh=mesh,
    )
    uf, itf = staged.factors(*staged.run())
    return ALSFactors(
        user_factors=uf,
        item_factors=itf,
        user_vocab=user_vocab or BiMap({}),
        item_vocab=item_vocab or BiMap({}),
        params=params,
    )


# ---------------------------------------------------------------------------
# Core solver — scatter path (rank > 32 matrix-free CG, and meshes)
# ---------------------------------------------------------------------------


def _half_step_implicit(
    fixed: jax.Array,  # (N_fixed, K) — e.g. item factors when solving users
    src_idx: jax.Array,  # (E,) — edge rows into `fixed`
    dst_idx: jax.Array,  # (E,) — edge rows being solved (sorted)
    conf: jax.Array,  # (E,) confidence c = 1 + alpha*|r|
    pref: jax.Array,  # (E,) preference p = 1[r > 0] (MLlib trainImplicit)
    valid: jax.Array,  # (E,) 1.0 real edge / 0.0 padding
    x0: jax.Array,  # (N_dst, K) warm start
    lam: float,
    cg_iterations: int,
    n_chunks: int = 1,
) -> jax.Array:
    n_dst, k = x0.shape
    gram = f32_gram(fixed)  # (K, K)
    b = chunked_weighted_edge_sum(
        fixed, src_idx, dst_idx, conf * pref * valid, n_dst, n_chunks
    )

    if k <= GRAM_SOLVER_MAX_RANK:
        # explicit per-row operator: ONE edge pass builds all Σ(c-1)yyᵀ
        # corrections; CG then runs on the dense (N, K, K) batch with no
        # further edge traffic (2·cg_iterations fewer HBM sweeps)
        corr = chunked_gram_edge_sum(
            fixed, src_idx, dst_idx, (conf - 1.0) * valid, n_dst, n_chunks
        ).reshape(n_dst, k, k)
        a = corr + gram[None, :, :] + lam * jnp.eye(k, dtype=jnp.float32)

        def matvec(v):
            return jnp.einsum("nij,nj->ni", a, v)

        return batched_cg(matvec, b, x0, cg_iterations)

    def matvec(v):
        base = v @ gram + lam * v
        # (c-1) is already 0 for pads (r=0), but multiply by `valid` so
        # padding is inert regardless of alpha/rating conventions
        corr = chunked_edge_matvec(
            fixed, v, src_idx, dst_idx, (conf - 1.0) * valid, n_dst, n_chunks
        )
        return base + corr

    return batched_cg(matvec, b, x0, cg_iterations)


def _half_step_explicit(
    fixed: jax.Array,
    src_idx: jax.Array,
    dst_idx: jax.Array,
    ratings: jax.Array,
    valid: jax.Array,  # (E,) 1.0 real edge / 0.0 padding
    degree: jax.Array,  # (N_dst,) observation counts for ALS-WR scaling
    x0: jax.Array,
    lam: float,
    cg_iterations: int,
    n_chunks: int = 1,
) -> jax.Array:
    n_dst, k = x0.shape
    b = chunked_weighted_edge_sum(
        fixed, src_idx, dst_idx, ratings * valid, n_dst, n_chunks
    )
    reg = lam * jnp.maximum(degree, 1.0)  # ALS-WR per-row scaling

    if k <= GRAM_SOLVER_MAX_RANK:
        obs = chunked_gram_edge_sum(
            fixed, src_idx, dst_idx, valid, n_dst, n_chunks
        ).reshape(n_dst, k, k)
        a = obs + reg[:, None, None] * jnp.eye(k, dtype=jnp.float32)

        def matvec(v):
            return jnp.einsum("nij,nj->ni", a, v)

        return batched_cg(matvec, b, x0, cg_iterations)

    def matvec(v):
        base = reg[:, None] * v
        obs = chunked_edge_matvec(
            fixed, v, src_idx, dst_idx, valid, n_dst, n_chunks
        )
        return base + obs

    return batched_cg(matvec, b, x0, cg_iterations)


@partial(
    jax.jit,
    static_argnames=(
        "n_user_windows", "n_item_windows", "rank", "iterations", "implicit",
        "cg_iterations", "pallas_mode", "mesh",
    ),
)
def _train_jit_windowed(
    u_src, u_val, u_ok, u_loc, u_bwin,  # user-side plan (solving users)
    i_src, i_val, i_ok, i_loc, i_bwin,  # item-side plan (solving items)
    user_deg, item_deg,
    uf0=None, itf0=None,
    *,
    n_user_windows: int,
    n_item_windows: int,
    rank: int,
    iterations: int,
    implicit: bool,
    lam: float,
    alpha: float,
    cg_iterations: int,
    seed: int,
    pallas_mode: Optional[str] = None,
    mesh: Optional[jax.sharding.Mesh] = None,
):
    """Whole alternating loop on the windowed (scatter-free) path.

    Factor matrices are window-padded; pad rows start exactly zero and CG
    freezes them at zero (b=0, x0=0 ⇒ r0=0), so they never contaminate
    the fixed-side gram.

    With a mesh, chunk arrays arrive sharded part-major over dp (see
    stage_windowed); factors are row-sharded over mp (replicated when
    mp == 1) and each edge pass ends in one GSPMD-inserted all-reduce of
    the window sums."""
    from predictionio_tpu.ops.windowed import WINDOW_ROWS

    if mesh is not None and mesh.devices.size > 1:
        from predictionio_tpu.parallel.mesh import (
            MODEL_AXIS,
            factor_sharding,
            replicated,
        )

        # the pallas kernel no longer downgrades under a mesh: P > 1
        # runs it shard_map'd over dp (ops/windowed.py) — only pass the
        # mesh handle through so the edge pass can build the shard_map
        sh = (
            factor_sharding(mesh)
            if mesh.shape.get(MODEL_AXIS, 1) > 1
            else replicated(mesh)
        )

        def shard_factors(f):
            return jax.lax.with_sharding_constraint(f, sh)

        half_step_mesh = mesh
    else:
        half_step_mesh = None

        def shard_factors(f):
            return f

    n_users_p = n_user_windows * WINDOW_ROWS
    n_items_p = n_item_windows * WINDOW_ROWS
    if uf0 is not None and itf0 is not None:
        uf, itf = shard_factors(uf0), shard_factors(itf0)
    else:
        ku, ki = jax.random.split(jax.random.PRNGKey(seed))
        # partitionable threefry across ALL train paths: draws are
        # shape-stable per element, so differently-padded paths (dense
        # vs windowed vs sharded slabs) agree on the real rows
        with jax.threefry_partitionable(True):
            uf = (
                jax.random.normal(ku, (n_users_p, rank), jnp.float32)
                / jnp.sqrt(rank)
            )
            itf = (
                jax.random.normal(ki, (n_items_p, rank), jnp.float32)
                / jnp.sqrt(rank)
            )
        # zero the window-padding rows so they stay exactly zero under CG
        uf = shard_factors(uf * (user_deg >= 0)[:, None])
        itf = shard_factors(itf * (item_deg >= 0)[:, None])

    def body(_, fs):
        uf, itf = fs
        uf = shard_factors(_half_step_windowed(
            itf, u_src, u_val, u_ok, u_loc, u_bwin, user_deg, uf,
            n_windows=n_user_windows, implicit=implicit, lam=lam,
            alpha=alpha, cg_iterations=cg_iterations,
            pallas_mode=pallas_mode, mesh=half_step_mesh,
        ))
        itf = shard_factors(_half_step_windowed(
            uf, i_src, i_val, i_ok, i_loc, i_bwin, item_deg, itf,
            n_windows=n_item_windows, implicit=implicit, lam=lam,
            alpha=alpha, cg_iterations=cg_iterations,
            pallas_mode=pallas_mode, mesh=half_step_mesh,
        ))
        return uf, itf

    return jax.lax.fori_loop(0, iterations, body, (uf, itf))


_train_jit_windowed = _devprof.instrument(
    "als.train_windowed", _train_jit_windowed, scale_by="iterations"
)


@partial(
    jax.jit,
    static_argnames=(
        "n_user_windows", "n_item_windows", "rank", "iterations", "implicit",
        "cg_iterations", "pallas_mode",
    ),
)
def _train_jit_windowed_grid(
    u_src, u_val, u_ok, u_loc, u_bwin,
    i_src, i_val, i_ok, i_loc, i_bwin,
    user_deg, item_deg,
    lams, alphas,  # (G,) f32 — the hyperparameter grid axis
    *,
    n_user_windows: int,
    n_item_windows: int,
    rank: int,
    iterations: int,
    implicit: bool,
    cg_iterations: int,
    seed: int,
    pallas_mode: Optional[str] = None,
):
    """N-point (λ, α) grid trained as ONE device program (VERDICT r3 #6).

    The staged edge plan is hyperparameter-independent at fixed rank, so
    every grid point shares it (vmap broadcasts — no G× edge copies in
    HBM); the alternating loops and their CG solves run batched over the
    grid axis. The Pallas edge kernel vmaps too (VERDICT r4 #2): the
    per-chunk kernel has no cross-grid-step state, so pallas_call's
    grid-prepending batching rule is sound for it — verified against
    per-point runs in tests/test_windowed_pallas.py."""

    def one(lam, alpha):
        return _train_jit_windowed(
            u_src, u_val, u_ok, u_loc, u_bwin,
            i_src, i_val, i_ok, i_loc, i_bwin,
            user_deg, item_deg,
            n_user_windows=n_user_windows,
            n_item_windows=n_item_windows,
            rank=rank, iterations=iterations, implicit=implicit,
            lam=lam, alpha=alpha, cg_iterations=cg_iterations, seed=seed,
            pallas_mode=pallas_mode, mesh=None,
        )

    return jax.vmap(one)(lams, alphas)


_train_jit_windowed_grid = _devprof.instrument(
    "als.train_windowed_grid", _train_jit_windowed_grid,
    scale_by="iterations",
)


def train_grid(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_users: int,
    n_items: int,
    params_list: Sequence[ALSParams],
    user_vocab: Optional[BiMap] = None,
    item_vocab: Optional[BiMap] = None,
) -> list["ALSFactors"]:
    """Train an ALS hyperparameter grid sharing staged training data.

    λ/α vary FREELY within one device program (vmapped solves); rank /
    iterations / cg_iterations / implicit / seed set program SHAPE, so
    grid points are grouped by that signature and each group runs as one
    batched launch — but every group shares ONE staging, because both
    staged forms are rank-independent (the WindowPlan blocks by
    destination row only; the dense rating matrix doesn't know about
    factors at all). A rank×λ grid therefore costs G_rank launches over
    one staged edge set instead of G_rank·G_λ serial train+stagings
    (VERDICT r4 #7; reference: the strictly serial MetricEvaluator grid,
    core/.../controller/Engine.scala:758-764)."""
    for p in params_list:
        if p.rank > GRAM_SOLVER_MAX_RANK:
            raise ValueError(
                f"train_grid supports rank <= {GRAM_SOLVER_MAX_RANK}"
            )
    rows = np.asarray(rows, dtype=np.int32)
    cols = np.asarray(cols, dtype=np.int32)
    vals = np.asarray(vals, dtype=np.float32)

    # group by program-shape signature, preserving input positions
    groups: dict[tuple, list[int]] = {}
    for i, p in enumerate(params_list):
        key = (
            p.rank, p.iterations, p.cg_iterations, p.implicit_prefs, p.seed
        )
        groups.setdefault(key, []).append(i)

    base = params_list[0]
    # data-dependent eligibility (pair uniqueness, quantization, budget)
    # is identical for every group — check once against base, then only
    # the cheap per-group condition (explicit mode forbids zero ratings)
    use_dense = dense_eligible(rows, cols, vals, n_users, n_items, base)
    if use_dense and not all(
        params_list[ix[0]].implicit_prefs for ix in groups.values()
    ):
        has_zero = bool(np.any(vals == 0.0))
        use_dense = not has_zero or all(
            params_list[ix[0]].implicit_prefs for ix in groups.values()
        )
    staged_d = staged_w = None
    if use_dense:
        # ONE device rating matrix serves every grid point and every
        # rank group (vmap broadcasts; R has no rank axis)
        staged_d = stage_dense(rows, cols, vals, n_users, n_items, base)
    else:
        staged_w = stage_windowed(rows, cols, vals, n_users, n_items, base)

    out: list[Optional[ALSFactors]] = [None] * len(params_list)
    for key, idxs in groups.items():
        rank, iterations, cg_iterations, implicit, seed = key
        lams = jnp.asarray(
            [params_list[i].lambda_ for i in idxs], jnp.float32
        )
        alphas = jnp.asarray(
            [params_list[i].alpha for i in idxs], jnp.float32
        )
        if staged_d is not None:
            kwargs = dict(staged_d.static_kwargs)
            kwargs.pop("lam"), kwargs.pop("alpha")
            kwargs.pop("mesh", None)  # grids run single-device
            kwargs.pop("pallas_mode", None)  # vmap excluded for now
            kwargs.update(
                rank=rank, iterations=iterations,
                cg_iterations=cg_iterations, implicit=implicit, seed=seed,
            )
            ufs, itfs = _train_jit_dense_grid(
                *staged_d.device_args[:3], lams, alphas, **kwargs
            )
        else:
            kwargs = dict(staged_w.static_kwargs)
            for grid_axis_or_unsupported in ("lam", "alpha", "mesh"):
                kwargs.pop(grid_axis_or_unsupported)
            kwargs.update(
                rank=rank, iterations=iterations,
                cg_iterations=cg_iterations, implicit=implicit, seed=seed,
            )
            ufs, itfs = _train_jit_windowed_grid(
                *staged_w.device_args[:12], lams, alphas, **kwargs
            )
        ufs, itfs = np.asarray(ufs), np.asarray(itfs)
        for g, i in enumerate(idxs):
            out[i] = ALSFactors(
                user_factors=ufs[g][:n_users],
                item_factors=itfs[g][:n_items],
                user_vocab=user_vocab or BiMap({}),
                item_vocab=item_vocab or BiMap({}),
                params=params_list[i],
            )
    return out  # type: ignore[return-value]


@partial(
    jax.jit,
    static_argnames=(
        "n_users", "n_items", "rank", "iterations", "implicit", "cg_iterations",
        "mesh", "n_chunks",
    ),
)
def _train_jit(
    u_src: jax.Array,  # (E,) item idx, sorted by user
    u_dst: jax.Array,  # (E,) user idx, sorted
    u_val: jax.Array,  # (E,)
    u_ok: jax.Array,  # (E,) 1.0 real / 0.0 pad
    i_src: jax.Array,  # (E,) user idx, sorted by item
    i_dst: jax.Array,  # (E,) item idx, sorted
    i_val: jax.Array,  # (E,)
    i_ok: jax.Array,  # (E,)
    user_deg: jax.Array,
    item_deg: jax.Array,
    uf0: Optional[jax.Array] = None,  # warm start (resume/checkpoint)
    itf0: Optional[jax.Array] = None,
    *,
    n_users: int,
    n_items: int,
    rank: int,
    iterations: int,
    implicit: bool,
    lam: float,
    alpha: float,
    cg_iterations: int,
    seed: int,
    mesh: Optional[jax.sharding.Mesh] = None,
    n_chunks: int = 1,
):
    if mesh is not None:
        from predictionio_tpu.parallel.mesh import MODEL_AXIS, factor_sharding, replicated

        sh = (
            factor_sharding(mesh)
            if mesh.shape.get(MODEL_AXIS, 1) > 1
            else replicated(mesh)
        )

        def shard_factors(f):
            return jax.lax.with_sharding_constraint(f, sh)

    else:

        def shard_factors(f):
            return f

    if uf0 is not None and itf0 is not None:
        uf = shard_factors(uf0)
        itf = shard_factors(itf0)
    else:
        ku, ki = jax.random.split(jax.random.PRNGKey(seed))
        # signed gaussian init scaled by 1/sqrt(rank); an all-positive init
        # (as some ALS impls use) starts near rank-1 and converges far slower
        with jax.threefry_partitionable(True):
            uf = shard_factors(
                jax.random.normal(ku, (n_users, rank), jnp.float32)
                / jnp.sqrt(rank)
            )
            itf = shard_factors(
                jax.random.normal(ki, (n_items, rank), jnp.float32)
                / jnp.sqrt(rank)
            )

    if implicit:
        # MLlib trainImplicit semantics (Hu-Koren-Volinsky with signed
        # feedback): confidence from |r| so a dislike (r<0) still raises
        # confidence, preference 1 only for r>0 — a disliked item is pulled
        # toward 0 HARDER than a never-seen one, and c stays positive so
        # the normal-equation operator is always SPD for CG.
        u_w = 1.0 + alpha * jnp.abs(u_val)
        i_w = 1.0 + alpha * jnp.abs(i_val)
        u_p = (u_val > 0).astype(jnp.float32)
        i_p = (i_val > 0).astype(jnp.float32)

        def body(_, fs):
            uf, itf = fs
            uf = shard_factors(_half_step_implicit(
                itf, u_src, u_dst, u_w, u_p, u_ok, uf, lam, cg_iterations,
                n_chunks,
            ))
            itf = shard_factors(_half_step_implicit(
                uf, i_src, i_dst, i_w, i_p, i_ok, itf, lam, cg_iterations,
                n_chunks,
            ))
            return uf, itf

    else:

        def body(_, fs):
            uf, itf = fs
            uf = shard_factors(_half_step_explicit(
                itf, u_src, u_dst, u_val, u_ok, user_deg, uf, lam,
                cg_iterations, n_chunks,
            ))
            itf = shard_factors(_half_step_explicit(
                uf, i_src, i_dst, i_val, i_ok, item_deg, itf, lam,
                cg_iterations, n_chunks,
            ))
            return uf, itf

    uf, itf = jax.lax.fori_loop(0, iterations, body, (uf, itf))
    return uf, itf


_train_jit = _devprof.instrument(
    "als.train_edge", _train_jit, scale_by="iterations"
)


def train(
    rows: np.ndarray,  # (E,) user indices
    cols: np.ndarray,  # (E,) item indices
    vals: np.ndarray,  # (E,) ratings / interaction weights
    n_users: int,
    n_items: int,
    params: ALSParams = ALSParams(),
    user_vocab: Optional[BiMap] = None,
    item_vocab: Optional[BiMap] = None,
    mesh: Optional[jax.sharding.Mesh] = None,
    init_factors: Optional[tuple[np.ndarray, np.ndarray]] = None,
) -> ALSFactors:
    """Train factors from a COO interaction list.

    `init_factors=(uf, itf)` warm-starts the alternating loop (checkpoint
    resume / incremental retrain); ALS iterations are memoryless in the
    factor state, so k resumed segments of m iterations reproduce one
    k·m-iteration run.

    When `mesh` is given, edge arrays are sharded over its first (data)
    axis and GSPMD inserts the ICI all-reduces for the segment sums;
    factor matrices are row-sharded over the model axis when it has more
    than one device, else replicated.
    """
    rows = np.asarray(rows, dtype=np.int32)
    cols = np.asarray(cols, dtype=np.int32)
    vals = np.asarray(vals, dtype=np.float32)
    user_deg = np.zeros(n_users, np.float32)
    np.add.at(user_deg, rows, 1.0)
    item_deg = np.zeros(n_items, np.float32)
    np.add.at(item_deg, cols, 1.0)

    if dense_eligible(rows, cols, vals, n_users, n_items, params, mesh):
        return _train_dense(
            rows, cols, vals, n_users, n_items, params,
            user_deg, item_deg, user_vocab, item_vocab, init_factors,
            mesh=mesh,
        )

    if params.rank <= GRAM_SOLVER_MAX_RANK:
        return _train_windowed(
            rows, cols, vals, n_users, n_items, params,
            user_deg, item_deg, user_vocab, item_vocab, init_factors,
            mesh=mesh,
        )

    valid = np.ones(len(rows), np.float32)
    n_chunks = max(
        1, -(-len(rows) // max(1, params.edge_chunk_size))
    )
    # pad so the edge axis divides by n_chunks (and the mesh size when
    # sharded) — padded edges carry valid=0.0 and are inert in every term
    unit = n_chunks * (mesh.devices.size if mesh is not None else 1)
    pad = (-len(rows)) % unit
    if pad:
        rows = np.concatenate([rows, np.zeros(pad, np.int32)])
        cols = np.concatenate([cols, np.zeros(pad, np.int32)])
        vals = np.concatenate([vals, np.zeros(pad, np.float32)])
        valid = np.concatenate([valid, np.zeros(pad, np.float32)])

    by_user = np.argsort(rows, kind="stable")
    by_item = np.argsort(cols, kind="stable")

    uf0 = itf0 = None
    if init_factors is not None:
        uf0 = np.asarray(init_factors[0], np.float32)
        itf0 = np.asarray(init_factors[1], np.float32)
        if uf0.shape != (n_users, params.rank) or itf0.shape != (
            n_items, params.rank,
        ):
            raise ValueError(
                "init_factors shapes do not match (n_users/n_items, rank)"
            )
    args = (
        cols[by_user], rows[by_user], vals[by_user], valid[by_user],
        rows[by_item], cols[by_item], vals[by_item], valid[by_item],
        user_deg, item_deg, uf0, itf0,
    )
    kwargs = dict(
        n_users=n_users,
        n_items=n_items,
        rank=params.rank,
        iterations=params.iterations,
        implicit=params.implicit_prefs,
        lam=params.lambda_,
        alpha=params.alpha,
        cg_iterations=params.cg_iterations,
        seed=params.seed,
        n_chunks=n_chunks,
    )
    if mesh is not None:
        if jax.process_count() > 1:
            # multi-host: device_put cannot place onto other processes'
            # devices — stage through the loader seam instead. Every
            # process passes the identical full edge arrays; stage_rows
            # extracts this process's contiguous row block and assembles
            # the global sharded array (reference analogue: HBase
            # executor-partitioned reads, HBPEvents.scala:84-90).
            from predictionio_tpu.parallel.loader import (
                stage_replicated,
                stage_rows,
            )

            device_args = list(stage_rows(mesh, *args[:8])) + [
                stage_replicated(mesh, a) if a is not None else None
                for a in args[8:]
            ]
        else:
            from predictionio_tpu.parallel.mesh import edge_sharding, replicated

            edge_sh = edge_sharding(mesh)
            rep_sh = replicated(mesh)
            device_args = [
                jax.device_put(a, edge_sh) for a in args[:8]
            ] + [
                jax.device_put(a, rep_sh) if a is not None else None
                for a in args[8:]
            ]
        uf, itf = _train_jit(*device_args, mesh=mesh, **kwargs)
    else:
        uf, itf = _train_jit(*args, **kwargs)
    uf, itf = np.asarray(uf), np.asarray(itf)
    return ALSFactors(
        user_factors=uf,
        item_factors=itf,
        user_vocab=user_vocab or BiMap({}),
        item_vocab=item_vocab or BiMap({}),
        params=params,
    )


@dataclass
class StagedWindowedTrain:
    """A windowed-path train with all edge data staged on device.

    Built once per training set by `stage_windowed`; `run()` re-executes
    the compiled alternating loop with no further host→device traffic —
    the unit bench.py times to report device throughput without host-prep
    or transfer noise."""

    device_args: tuple
    static_kwargs: dict
    n_users: int
    n_items: int
    host_prep_sec: float
    transfer_sec: float

    def run(self) -> tuple[jax.Array, jax.Array]:
        """One full train; returns window-padded device factor arrays."""
        return _train_jit_windowed(*self.device_args, **self.static_kwargs)

    def factors(self, uf: jax.Array, itf: jax.Array) -> tuple[np.ndarray, np.ndarray]:
        return np.asarray(uf)[: self.n_users], np.asarray(itf)[: self.n_items]


def stage_windowed(
    rows, cols, vals, n_users, n_items, params,
    user_deg=None, item_deg=None, init_factors=None,
    mesh: Optional[jax.sharding.Mesh] = None,
) -> StagedWindowedTrain:
    """Host plan + device staging for the windowed (scatter-free) path.

    Host builds the two block plans (users-sorted and items-sorted) once —
    see ops/windowed.py — and pushes every edge array to device HBM.

    With a mesh, each plan is built with n_parts = |dp| contiguous block
    groups; chunk arrays land sharded part-major over dp (multi-host:
    each process stages only its contiguous slice of parts — the
    HBPEvents.scala:84-90 partitioned-read role). Degrees/init factors
    are replicated; mp row-sharding is applied inside the jit."""
    import time as _time

    t0 = _time.perf_counter()
    # single gate for both staging sharding and mesh pass-through: a
    # model-parallel-only mesh (dp=1, mp>1) still stages replicated
    # arrays but must reach the jit so mp row-sharding applies (ADVICE r4)
    use_mesh = mesh is not None and mesh.devices.size > 1
    n_parts = 1
    if use_mesh:
        from predictionio_tpu.parallel.mesh import DATA_AXIS

        n_parts = int(mesh.shape.get(DATA_AXIS, 1))
    if user_deg is None:
        user_deg = np.zeros(n_users, np.float32)
        np.add.at(user_deg, rows, 1.0)
    if item_deg is None:
        item_deg = np.zeros(n_items, np.float32)
        np.add.at(item_deg, cols, 1.0)
    by_user = np.argsort(rows, kind="stable")
    by_item = np.argsort(cols, kind="stable")
    plan_u = plan_windows(rows[by_user], n_users, n_parts)
    plan_i = plan_windows(cols[by_item], n_items, n_parts)

    def pad_deg(deg, n_padded):
        out = np.full(n_padded, -1.0, np.float32)  # -1 marks window padding
        out[: len(deg)] = deg
        return out

    uf0 = itf0 = None
    if init_factors is not None:
        uf_in = np.asarray(init_factors[0], np.float32)
        itf_in = np.asarray(init_factors[1], np.float32)
        if uf_in.shape != (n_users, params.rank) or itf_in.shape != (
            n_items, params.rank,
        ):
            raise ValueError(
                "init_factors shapes do not match (n_users/n_items, rank)"
            )
        uf0 = np.zeros((plan_u.n_rows_padded, params.rank), np.float32)
        uf0[:n_users] = uf_in
        itf0 = np.zeros((plan_i.n_rows_padded, params.rank), np.float32)
        itf0[:n_items] = itf_in

    host_args = (
        plan_u.take(cols[by_user]),
        plan_u.take(vals[by_user]),
        plan_u.chunked_valid(),
        plan_u.chunked_local(),
        plan_u.block_window,
        plan_i.take(rows[by_item]),
        plan_i.take(vals[by_item]),
        plan_i.chunked_valid(),
        plan_i.chunked_local(),
        plan_i.block_window,
        pad_deg(user_deg, plan_u.n_rows_padded),
        pad_deg(item_deg, plan_i.n_rows_padded),
        uf0, itf0,
    )
    host_prep = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    if use_mesh:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from predictionio_tpu.parallel.mesh import DATA_AXIS

        n_procs = jax.process_count()
        p_idx = jax.process_index()

        def put(a):
            if a is None:
                return None
            # chunk arrays (P, L, CB, B_E) and block_window (P*L*CB,)
            # shard their leading axis over dp; everything else
            # (degrees, init factors) is replicated. With dp == 1
            # (mp-only mesh) NOTHING is dp-sharded — the multi-process
            # slice below would otherwise compute shape[0] // n_procs
            # = 0 and hand GSPMD an empty local buffer
            sharded = n_parts > 1 and (
                a.ndim == 4 or a.dtype == np.int32 and a.ndim == 1
            )
            spec = (
                P(DATA_AXIS, *([None] * (a.ndim - 1))) if sharded else P()
            )
            sh = NamedSharding(mesh, spec)
            if n_procs > 1:
                local = a
                if sharded:
                    per = a.shape[0] // n_procs
                    local = a[p_idx * per : (p_idx + 1) * per]
                return jax.make_array_from_process_local_data(
                    sh, local, a.shape
                )
            return jax.device_put(a, sh)

        device_args = tuple(put(a) for a in host_args)
    else:
        device_args = tuple(
            jax.device_put(a) if a is not None else None for a in host_args
        )
    transfer = _time.perf_counter() - t0
    from predictionio_tpu.ops.windowed import resolve_pallas_mode

    return StagedWindowedTrain(
        device_args=device_args,
        static_kwargs=dict(
            n_user_windows=plan_u.n_windows,
            n_item_windows=plan_i.n_windows,
            rank=params.rank,
            iterations=params.iterations,
            implicit=params.implicit_prefs,
            lam=params.lambda_,
            alpha=params.alpha,
            cg_iterations=params.cg_iterations,
            seed=params.seed,
            # resolved OUTSIDE the jit so the trace cache keys on it
            pallas_mode=resolve_pallas_mode("auto"),
            mesh=mesh if use_mesh else None,
        ),
        n_users=n_users,
        n_items=n_items,
        host_prep_sec=host_prep,
        transfer_sec=transfer,
    )


def _train_windowed(
    rows, cols, vals, n_users, n_items, params,
    user_deg, item_deg, user_vocab, item_vocab, init_factors,
    mesh: Optional[jax.sharding.Mesh] = None,
) -> "ALSFactors":
    """Train on the windowed scatter-free path (single device or mesh)."""
    staged = stage_windowed(
        rows, cols, vals, n_users, n_items, params,
        user_deg=user_deg, item_deg=item_deg, init_factors=init_factors,
        mesh=mesh,
    )
    uf, itf = staged.factors(*staged.run())
    return ALSFactors(
        user_factors=uf,
        item_factors=itf,
        user_vocab=user_vocab or BiMap({}),
        item_vocab=item_vocab or BiMap({}),
        params=params,
    )


# ---------------------------------------------------------------------------
# Serving-side scoring
# ---------------------------------------------------------------------------
#
# Two generations coexist:
#
# - the PR-2 path (`recommend` + `_recommend_jit[_nomask]`): exact-width
#   f32 factor matrices, XLA two-step (scores matmul -> lax.top_k). Kept
#   for callers that serve straight off an ALSFactors.
# - the ISSUE-11 path (`stage_serving` + `recommend_serving`): a staged
#   `ServingFactors` whose item matrix is pad-aligned for the fused
#   Pallas recommend+top-k kernel (ops/recommend_pallas.py — one HBM
#   pass, no (B, I) score matrix), optionally int8-quantized per row
#   (half the factor stream; int8xint8->int32 scoring), with device-side
#   copy-on-write row publish for the online fold-in so a tick re-ships
#   only its dirty rows instead of a factor matrix.
#
# Donation note (measured, not assumed): the per-query programs' outputs
# ((B, k) values + indices) are strictly smaller than every input, so
# `donate_argnums` on the query-row/mask buffers has nothing to alias —
# XLA reports the donation unusable. The donation lever that IS real on
# this shape is the state-update path: `_set_rows_donated` aliases a
# grown factor table into its row-published successor during fold-in
# publish, and it only ever runs on a buffer this publish privately
# created (the COW copy readers never see), so swaps stay zero-drop.


@partial(jax.jit, static_argnames=("k",))
def _recommend_jit(
    user_rows: jax.Array,  # (B,) int — rows into user_factors
    user_factors: jax.Array,  # (U, K) device-resident
    item_factors: jax.Array,  # (I, K) device-resident
    exclude_mask: jax.Array,  # (B, I) bool
    k: int,
):
    scores = user_factors[user_rows] @ item_factors.T  # (B, I) — MXU
    return masked_top_k(scores, k, exclude_mask)


@partial(jax.jit, static_argnames=("k",))
def _recommend_jit_nomask(
    user_rows: jax.Array,
    user_factors: jax.Array,
    item_factors: jax.Array,
    k: int,
):
    scores = user_factors[user_rows] @ item_factors.T
    return jax.lax.top_k(scores, k)


# serving kernels opt into full memory_analysis (memory=True): the
# duplicate AOT compile per signature is ~100 ms and lands in warmup —
# the bucket ladder pre-compiles every live shape before traffic
_recommend_jit = _devprof.instrument(
    "als.recommend_masked", _recommend_jit, memory=True
)
_recommend_jit_nomask = _devprof.instrument(
    "als.recommend", _recommend_jit_nomask, memory=True
)


def recommend(
    model: ALSFactors,
    user_indices: np.ndarray,  # (B,) rows into user_factors
    k: int,
    exclude_mask: Optional[np.ndarray] = None,  # (B, I) bool
    item_factors_device: Optional[jax.Array] = None,
    user_factors_device: Optional[jax.Array] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Top-k items for a batch of users; returns (scores, item_indices).

    The serving hot path is ONE device dispatch: only the (B,) user rows
    (and the mask, when any filter applies) cross host→device per query;
    both factor matrices stay HBM-resident across queries
    (CreateServer-style TPU-resident model state). The unfiltered path
    skips mask allocation entirely."""
    itf = (
        item_factors_device
        if item_factors_device is not None
        else jnp.asarray(model.item_factors)
    )
    uf = (
        user_factors_device
        if user_factors_device is not None
        else jnp.asarray(model.user_factors)
    )
    rows = jnp.asarray(np.asarray(user_indices, dtype=np.int32))
    if exclude_mask is None:
        vals, idx = _recommend_jit_nomask(rows, uf, itf, k)
    else:
        vals, idx = _recommend_jit(rows, uf, itf, jnp.asarray(exclude_mask), k)
    return np.asarray(vals), np.asarray(idx)


# -- staged serving state (ISSUE 11) ----------------------------------------


SERVE_DTYPES = ("f32", "bf16", "int8")


@dataclass(frozen=True)
class ServingFactors:
    """Device-resident serving-side factor state, staged ONCE and reused
    across every call (the donated-resident-state contract: per-query
    traffic is the (B,) row ids and, when filters apply, the packed
    mask words or exclusion row list).

    `items` is row-padded to `ops.recommend_pallas.ITEM_PAD` so the
    fused kernel always finds a dividing tile; `n_items` is the live
    extent (pad rows are masked dead inside the kernel and sliced off
    on the XLA fallback). dtype "int8" holds BOTH matrices per-row
    symmetric-quantized with their scale vectors (users (U, 1),
    items (1, I_p)) — scoring is int8xint8->int32 with the scale outer
    product dequantizing in registers; "bf16" (ISSUE 14, the middle
    ground) halves the factor stream with bf16xbf16->f32 scoring and
    no scale vectors. `item_inv_norm` carries the items' f32-row
    inverse L2 norms so the cosine verbs (`similar_serving`, itemsim's
    on-the-fly cosine) serve off the SAME resident slab — cosine is
    the scaled dot, never a normalized copy in HBM."""

    users: jax.Array  # (U, K) f32 | bf16 | int8
    items: jax.Array  # (I_p, K) f32 | bf16 | int8 — pad rows zero
    user_scale: Optional[jax.Array]  # (U, 1) f32 when int8
    item_scale: Optional[jax.Array]  # (1, I_p) f32 when int8
    n_items: int
    dtype: str  # "f32" | "bf16" | "int8"
    mode: Optional[str]  # resolved pallas mode (None = XLA two-step)
    item_inv_norm: Optional[jax.Array] = None  # (1, I_p) f32 — cosine

    @property
    def n_users(self) -> int:
        return int(self.users.shape[0])

    def device_nbytes(self) -> float:
        total = float(self.users.nbytes + self.items.nbytes)
        if self.user_scale is not None:
            total += float(self.user_scale.nbytes + self.item_scale.nbytes)
        if self.item_inv_norm is not None:
            total += float(self.item_inv_norm.nbytes)
        return total


def stage_serving(
    factors: "ALSFactors",
    serve_dtype: str = "f32",
    mode: str = "auto",
) -> ServingFactors:
    """Stage (and for "int8", quantize) the factor matrices for serving.

    Quantization happens HERE — at model publish / fold-in restage —
    never per query; `serving_publish_rows` keeps a folded tick from
    re-running this on anything but the dirty rows."""
    return _stage_arrays(
        np.asarray(factors.user_factors, np.float32),
        np.asarray(factors.item_factors, np.float32),
        serve_dtype, mode,
    )


def stage_item_serving(
    item_matrix: np.ndarray,
    serve_dtype: str = "f32",
    mode: str = "auto",
) -> ServingFactors:
    """Item-only staging for cosine-only models (itemsim's (I, U)
    column vectors): same ServingFactors contract with an empty user
    side — `similar_serving` is the only verb that makes sense here."""
    itf = np.asarray(item_matrix, np.float32)
    return _stage_arrays(
        np.zeros((0, itf.shape[1] if itf.ndim == 2 else 0), np.float32),
        itf, serve_dtype, mode,
    )


def _stage_arrays(
    uf: np.ndarray, itf: np.ndarray, serve_dtype: str, mode: str
) -> ServingFactors:
    from predictionio_tpu.ops import recommend_pallas as _rp

    if serve_dtype not in SERVE_DTYPES:
        raise ValueError(
            f"serve_dtype must be one of {SERVE_DTYPES}, got "
            f"{serve_dtype!r}"
        )
    n_items, k = itf.shape if itf.ndim == 2 else (0, uf.shape[1])
    i_p = _rp.pad_items(n_items)
    # inverse norms from the PRE-quantization f32 rows: the cosine
    # verbs normalize by the true magnitudes, identically across dtypes
    inv = jax.device_put(_rp.inv_norms_np(itf, i_p))
    resolved = _rp.resolve_mode(mode)
    if serve_dtype == "int8":
        uq, us = _rp.quantize_rows_np(uf)
        iq, isc = _rp.quantize_rows_np(itf)
        items = np.zeros((i_p, k), np.int8)
        items[:n_items] = iq
        iscale = np.ones((1, i_p), np.float32)
        iscale[0, :n_items] = isc
        return ServingFactors(
            users=jax.device_put(uq),
            items=jax.device_put(items),
            user_scale=jax.device_put(us[:, None]),
            item_scale=jax.device_put(iscale),
            n_items=n_items,
            dtype="int8",
            mode=resolved,
            item_inv_norm=inv,
        )
    np_dt = np.float32
    items = np.zeros((i_p, k), np_dt)
    items[:n_items] = itf
    users_dev = jax.device_put(uf)
    items_dev = jax.device_put(items)
    if serve_dtype == "bf16":
        users_dev = users_dev.astype(jnp.bfloat16)
        items_dev = items_dev.astype(jnp.bfloat16)
    return ServingFactors(
        users=users_dev,
        items=items_dev,
        user_scale=None,
        item_scale=None,
        n_items=n_items,
        dtype=serve_dtype,
        mode=resolved,
        item_inv_norm=inv,
    )


def _serve_dtype_of(items: jax.Array) -> str:
    dt = str(items.dtype)
    return "int8" if dt == "int8" else ("bf16" if dt == "bfloat16" else "f32")


def _fused_or_xla_topk(
    q, items, qs, isc, mask_bits, excl_rows, n_items, *, k, mode
):
    """One dispatch seam for every serving verb, shared with the
    sharded tier: ops/recommend_pallas.py:fused_or_xla_topk (the fused
    one-pass kernel where a mode resolved, else the XLA two-step with
    IDENTICAL scoring + exclusion semantics — incl. the batch-size-
    stable `q @ items.T` dot spelling its docstring records)."""
    from predictionio_tpu.ops.recommend_pallas import fused_or_xla_topk

    return fused_or_xla_topk(
        q, items, qs, isc, mask_bits, excl_rows, n_items, k=k, mode=mode
    )


@partial(jax.jit, static_argnames=("k", "mode"))
def _serve_recommend_jit(
    rows: jax.Array,  # (B,) int32 — the per-call traffic
    users: jax.Array,
    items: jax.Array,
    user_scale: Optional[jax.Array],
    item_scale: Optional[jax.Array],
    mask_bits: Optional[jax.Array],  # (B, I_p/32) int32 packed words
    excl_rows: Optional[jax.Array],  # (B, E) int32 row list, -1 padded
    n_items: jax.Array,  # () int32 live item count, TRACED — online
    # vocab growth within the pad must not retrace the serving program
    *,
    k: int,
    mode: Optional[str],
):
    """The staged-state serving program: gather the query block from the
    resident user matrix, then either the fused one-pass Pallas kernel
    (mode "tpu"/"interpret") or the XLA two-step fallback — both share
    the int8/bf16 scoring semantics (quantized gather, int32/f32
    accumulate, scale-product dequant) so a mode change never changes
    scores."""
    int8 = items.dtype == jnp.int8
    q = users[rows]
    qs = user_scale[rows] if int8 else None
    isc = item_scale if int8 else None
    return _fused_or_xla_topk(
        q, items, qs, isc, mask_bits, excl_rows, n_items, k=k, mode=mode
    )


@partial(jax.jit, static_argnames=("k", "mode"))
def _serve_similar_jit(
    rows: jax.Array,  # (B,) int32 item rows — the per-call traffic
    items: jax.Array,
    item_scale: Optional[jax.Array],
    item_inv_norm: jax.Array,  # (1, I_p) f32
    mask_bits: Optional[jax.Array],
    excl_rows: Optional[jax.Array],
    n_items: jax.Array,
    *,
    k: int,
    mode: Optional[str],
):
    """Fused cosine `similar` off the SAME resident item slab as
    recommend (ISSUE 14 tentpole part 1): cosine(q, x) =
    (q·x)·(1/|q|)·(1/|x|) — the inverse norms ride the kernel's scale
    inputs, so no normalized factor copy ever exists in HBM. int8
    composes: the effective scales are (dequant scale · inverse norm)
    per side."""
    q = items[rows]
    inv_q = item_inv_norm[0, rows][:, None]  # (B, 1)
    if items.dtype == jnp.int8:
        qs = item_scale[0, rows][:, None] * inv_q
        isc = item_scale * item_inv_norm
    else:
        qs = inv_q
        isc = item_inv_norm
    return _fused_or_xla_topk(
        q, items, qs, isc, mask_bits, excl_rows, n_items, k=k, mode=mode
    )


@partial(jax.jit, static_argnames=("k", "mode"))
def _serve_similar_vecs_jit(
    vecs: jax.Array,  # (B, K) f32 query vectors (basket means)
    items: jax.Array,
    item_scale: Optional[jax.Array],
    item_inv_norm: jax.Array,
    mask_bits: Optional[jax.Array],
    excl_rows: Optional[jax.Array],
    n_items: jax.Array,
    *,
    k: int,
    mode: Optional[str],
):
    """Cosine top-k against ARBITRARY f32 query vectors (the
    similarproduct basket mean) from the staged state: the query side
    quantizes in-jit for int8 slabs (quantize_rows_jnp), norms fold
    into the scale product like every other cosine verb."""
    from predictionio_tpu.ops.recommend_pallas import quantize_rows_jnp

    inv_q = 1.0 / (
        jnp.linalg.norm(vecs, axis=-1, keepdims=True) + 1e-9
    )
    if items.dtype == jnp.int8:
        q, qscale = quantize_rows_jnp(vecs)
        qs = qscale * inv_q
        isc = item_scale * item_inv_norm
    else:
        q = vecs.astype(items.dtype)
        qs = inv_q
        isc = item_inv_norm
    return _fused_or_xla_topk(
        q, items, qs, isc, mask_bits, excl_rows, n_items, k=k, mode=mode
    )


# serving kernels opt into memory analysis (bucket-ladder warmup pays the
# duplicate AOT compile); int8/bf16 signatures roofline against their
# dtype's peak via devprof's dtype-aware table (ISSUE 11 satellite) —
# only the call site knows the resident item matrix IS the MXU dtype
_serve_recommend_jit = _devprof.instrument(
    "als.recommend_serving", _serve_recommend_jit, memory=True,
    dtype_of=lambda args, kwargs: _serve_dtype_of(args[2]),
)
_serve_similar_jit = _devprof.instrument(
    "als.similar_serving", _serve_similar_jit, memory=True,
    dtype_of=lambda args, kwargs: _serve_dtype_of(args[1]),
)
_serve_similar_vecs_jit = _devprof.instrument(
    "als.similar_vecs_serving", _serve_similar_vecs_jit, memory=True,
    dtype_of=lambda args, kwargs: _serve_dtype_of(args[1]),
)


def _exclusion_device_args(
    serving: ServingFactors,
    batch: int,
    exclude_mask: Optional[np.ndarray],
    exclude_rows: Optional[np.ndarray],
    extra_rows: Optional[np.ndarray] = None,
):
    """Host-side exclusion packing shared by the serving verbs: a row
    list (the common small-blacklist case) ships (B, E) int32 at a
    pow2-bucketed width; anything wider — or a dense mask — packs to
    bit words at 1/32 the f32 bytes. `extra_rows` appends one
    always-excluded row per query (similar's exclude_self)."""
    from predictionio_tpu.ops import recommend_pallas as _rp

    i_p = int(serving.items.shape[0])
    if exclude_mask is not None:
        mask = np.asarray(exclude_mask, bool)
        if extra_rows is not None:
            mask = mask.copy()
            mask[np.arange(batch), np.asarray(extra_rows)] = True
        return jnp.asarray(_rp.pack_mask_np(mask, i_p)), None
    if exclude_rows is not None and extra_rows is None:
        # fast path: an already -1-padded (B, E) int32 array (the
        # engines' _exclusion_args builds exactly this) ships as-is —
        # re-listing every cell through Python ints per micro-batch
        # would cost more than the exclusion itself
        ex = np.asarray(exclude_rows, np.int32)
        if ex.shape[1] <= _rp.ROWLIST_MAX:
            return None, (jnp.asarray(ex) if ex.shape[1] else None)
    lists: list[list[int]] = [[] for _ in range(batch)]
    if exclude_rows is not None:
        for b, row in enumerate(exclude_rows):
            lists[b] = [int(x) for x in row if int(x) >= 0]
    if extra_rows is not None:
        for b, r in enumerate(np.asarray(extra_rows)):
            lists[b].append(int(r))
    widest = max((len(r) for r in lists), default=0)
    if widest == 0:
        return None, None
    if widest > _rp.ROWLIST_MAX:
        # too wide for the unrolled compare chain: scatter host-side
        # into packed words instead (still 1/32 the f32 mask bytes)
        mask = np.zeros((batch, i_p), bool)
        for b, row in enumerate(lists):
            hits = np.asarray(row, np.int64)
            hits = hits[(hits >= 0) & (hits < i_p)]
            mask[b, hits] = True
        return jnp.asarray(_rp.pack_mask_np(mask, i_p)), None
    return None, jnp.asarray(_rp.rowlist_np(lists))


def recommend_serving(
    serving: ServingFactors,
    user_indices: np.ndarray,
    k: int,
    exclude_mask: Optional[np.ndarray] = None,  # (B, n_items) bool
    exclude_rows: Optional[np.ndarray] = None,  # (B, E) int, -1 padded
) -> tuple[np.ndarray, np.ndarray]:
    """Top-k items from staged serving state; same (scores, indices)
    contract as `recommend`. ONE device dispatch; only the row ids (and
    the packed exclusion words / row list, when filters apply) cross
    host->device."""
    k = min(int(k), serving.n_items)
    if k <= 0 or serving.n_users == 0:
        b = len(np.asarray(user_indices))
        return (
            np.zeros((b, 0), np.float32), np.zeros((b, 0), np.int64),
        )
    rows = jnp.asarray(np.asarray(user_indices, np.int32))
    bits, ex = _exclusion_device_args(
        serving, int(rows.shape[0]), exclude_mask, exclude_rows
    )
    vals, idx = _serve_recommend_jit(
        rows, serving.users, serving.items, serving.user_scale,
        serving.item_scale, bits, ex,
        jnp.asarray(serving.n_items, jnp.int32),
        k=k, mode=serving.mode,
    )
    return np.asarray(vals), np.asarray(idx)


def similar_serving(
    serving: ServingFactors,
    item_indices: np.ndarray,
    k: int,
    exclude_self: bool = True,
    exclude_mask: Optional[np.ndarray] = None,
    exclude_rows: Optional[np.ndarray] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Fused cosine top-k for a batch of item rows off the staged
    state — `als.similar` and itemsim's on-the-fly column cosine both
    route here (ISSUE 14). exclude_self rides the row-list fast path
    (one entry per query) unless a dense mask is already in play."""
    k = min(int(k), serving.n_items)
    rows_np = np.asarray(item_indices, np.int32)
    if k <= 0 or serving.n_items == 0:
        return (
            np.zeros((len(rows_np), 0), np.float32),
            np.zeros((len(rows_np), 0), np.int64),
        )
    bits, ex = _exclusion_device_args(
        serving, len(rows_np), exclude_mask, exclude_rows,
        extra_rows=rows_np if exclude_self else None,
    )
    vals, idx = _serve_similar_jit(
        jnp.asarray(rows_np), serving.items, serving.item_scale,
        serving.item_inv_norm, bits, ex,
        jnp.asarray(serving.n_items, jnp.int32),
        k=k, mode=serving.mode,
    )
    return np.asarray(vals), np.asarray(idx)


def similar_vectors_serving(
    serving: ServingFactors,
    vectors: np.ndarray,  # (B, K) f32 query vectors
    k: int,
    exclude_mask: Optional[np.ndarray] = None,
    exclude_rows: Optional[np.ndarray] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Cosine top-k against arbitrary query vectors (similarproduct's
    basket mean) from the staged state."""
    k = min(int(k), serving.n_items)
    vecs = np.asarray(vectors, np.float32)
    if k <= 0 or serving.n_items == 0:
        return (
            np.zeros((len(vecs), 0), np.float32),
            np.zeros((len(vecs), 0), np.int64),
        )
    bits, ex = _exclusion_device_args(
        serving, len(vecs), exclude_mask, exclude_rows
    )
    vals, idx = _serve_similar_vecs_jit(
        jnp.asarray(vecs), serving.items, serving.item_scale,
        serving.item_inv_norm, bits, ex,
        jnp.asarray(serving.n_items, jnp.int32),
        k=k, mode=serving.mode,
    )
    return np.asarray(vals), np.asarray(idx)


# -- device-side fold-in publish (COW + donation where private) -------------


@jax.jit
def _set_rows_cow(table, rows, values):
    """Row publish OFF a SHARED buffer: .at[].set copies, so readers
    holding the old reference (in-flight pipelined batches) keep a live,
    unchanged buffer — the zero-drop swap contract."""
    return table.at[rows].set(values)


@partial(jax.jit, donate_argnums=(0,))
def _set_rows_donated(table, rows, values):
    """Row publish INTO a donated buffer. ONLY for tables this publish
    privately created (the grown/padded successor no reader has seen):
    XLA aliases the buffer and the publish costs the dirty rows, not a
    matrix copy. Donating a shared buffer here would corrupt concurrent
    readers — callers must uphold the privacy invariant."""
    return table.at[rows].set(values)


@jax.jit
def _set_cols_cow(table, cols, values):
    """COW column write for the (1, I_p) item-scale vector."""
    return table.at[0, cols].set(values)


@partial(jax.jit, donate_argnums=(0,))
def _set_cols_donated(table, cols, values):
    return table.at[0, cols].set(values)


# the publish-path jits are tiny row writes, but they ARE top-level
# dispatch boundaries (every fold-in tick pays them): instrumenting
# keeps the serving-state publish visible in the devprof report
_set_rows_cow = _devprof.instrument("als.publish_rows_cow", _set_rows_cow)
_set_rows_donated = _devprof.instrument(
    "als.publish_rows_donated", _set_rows_donated
)
_set_cols_cow = _devprof.instrument("als.publish_cols_cow", _set_cols_cow)
_set_cols_donated = _devprof.instrument(
    "als.publish_cols_donated", _set_cols_donated
)


def _grow_table(table: jax.Array, n_rows: int, axis: int = 0) -> jax.Array:
    """Zero-pad a factor/scale table to `n_rows` along `axis` (device
    concat — the result is PRIVATE to the caller: safe to donate into)."""
    extra = n_rows - int(table.shape[axis])
    if extra <= 0:
        return table
    shape = list(table.shape)
    shape[axis] = extra
    return jnp.concatenate(
        [table, jnp.zeros(shape, table.dtype)], axis=axis
    )


def serving_publish_rows(
    serving: ServingFactors,
    user_rows: Optional[np.ndarray] = None,
    user_vals: Optional[np.ndarray] = None,  # (Ru, K) f32 solved rows
    item_rows: Optional[np.ndarray] = None,
    item_vals: Optional[np.ndarray] = None,
    n_users: Optional[int] = None,
    n_items: Optional[int] = None,
) -> ServingFactors:
    """Publish a fold-in tick's dirty rows into the staged serving state
    WITHOUT re-staging a factor matrix: quantize only the dirty rows
    (int8 mode) and write them device-side. The first write off a
    SHARED table is copy-on-write (in-flight readers keep a live,
    unchanged buffer — zero-drop swaps); vocab growth zero-pads the
    table first (a private device concat) and the row write into that
    private successor is DONATED, so growth costs the dirty rows plus
    one aliased pad, never a host restage."""
    from predictionio_tpu.ops import recommend_pallas as _rp

    n_users = max(
        serving.n_users, 0 if n_users is None else int(n_users)
    )
    n_items_new = max(
        serving.n_items, 0 if n_items is None else int(n_items)
    )
    users, uscale = serving.users, serving.user_scale
    items, iscale = serving.items, serving.item_scale
    inv = serving.item_inv_norm
    int8 = serving.dtype == "int8"

    if user_rows is not None and len(user_rows) > 0:
        ur = jnp.asarray(np.asarray(user_rows, np.int32))
        uv = np.asarray(user_vals, np.float32)
        grown = n_users > serving.n_users
        if grown:
            users = _grow_table(users, n_users)  # private successor
        set_rows = _set_rows_donated if grown else _set_rows_cow
        if int8:
            q, s = _rp.quantize_rows_np(uv)
            users = set_rows(users, ur, jnp.asarray(q))
            if grown:
                uscale = _grow_table(uscale, n_users)
                uscale = _set_rows_donated(
                    uscale, ur, jnp.asarray(s[:, None])
                )
            else:
                uscale = _set_rows_cow(uscale, ur, jnp.asarray(s[:, None]))
        else:
            users = set_rows(users, ur, jnp.asarray(uv, users.dtype))
    elif n_users > serving.n_users:
        users = _grow_table(users, n_users)
        if int8:
            uscale = _grow_table(uscale, n_users)

    if item_rows is not None and len(item_rows) > 0:
        ir = jnp.asarray(np.asarray(item_rows, np.int32))
        iv = np.asarray(item_vals, np.float32)
        i_p = int(items.shape[0])
        grown = n_items_new > i_p  # growth past the staged pad headroom
        i_p_new = _rp.pad_items(n_items_new)
        if grown:
            items = _grow_table(items, i_p_new)
        set_rows = _set_rows_donated if grown else _set_rows_cow
        set_cols = _set_cols_donated if grown else _set_cols_cow
        if int8:
            q, s = _rp.quantize_rows_np(iv)
            items = set_rows(items, ir, jnp.asarray(q))
            if grown:
                iscale = _grow_table(iscale, i_p_new, axis=1)
            iscale = set_cols(iscale, ir, jnp.asarray(s))
        else:
            items = set_rows(items, ir, jnp.asarray(iv, items.dtype))
        if inv is not None:
            # the cosine verbs' inverse norms track the dirty rows'
            # NEW f32 magnitudes — a fold tick must not serve stale
            # norms under similar while recommend sees fresh factors
            if grown:
                inv = _grow_table(inv, i_p_new, axis=1)
            inv = set_cols(
                inv, ir, jnp.asarray(_rp.inv_norms_np(iv)[0])
            )
    elif n_items_new > int(items.shape[0]):
        items = _grow_table(items, _rp.pad_items(n_items_new))
        if int8:
            iscale = _grow_table(
                iscale, _rp.pad_items(n_items_new), axis=1
            )
        if inv is not None:
            inv = _grow_table(inv, _rp.pad_items(n_items_new), axis=1)

    return ServingFactors(
        users=users, items=items, user_scale=uscale, item_scale=iscale,
        n_items=n_items_new, dtype=serving.dtype, mode=serving.mode,
        item_inv_norm=inv,
    )


@partial(jax.jit, static_argnames=("k",))
def _similar_jit(query_vecs: jax.Array, item_factors: jax.Array, exclude_mask, k: int):
    # cosine similarity on L2-normalized factors
    qn = query_vecs / (jnp.linalg.norm(query_vecs, axis=-1, keepdims=True) + 1e-9)
    fn = item_factors / (jnp.linalg.norm(item_factors, axis=-1, keepdims=True) + 1e-9)
    return masked_top_k(qn @ fn.T, k, exclude_mask)


_similar_jit = _devprof.instrument("als.similar", _similar_jit, memory=True)


def similar_items(
    model: ALSFactors,
    item_indices: np.ndarray,
    k: int,
    exclude_self: bool = True,
) -> tuple[np.ndarray, np.ndarray]:
    """Item-item cosine over factors (similarproduct template's core,
    examples/scala-parallel-similarproduct)."""
    itf = jnp.asarray(model.item_factors)
    q = itf[np.asarray(item_indices)]
    n_items = itf.shape[0]
    mask = np.zeros((len(item_indices), n_items), dtype=bool)
    if exclude_self:
        mask[np.arange(len(item_indices)), np.asarray(item_indices)] = True
    vals, idx = _similar_jit(q, itf, jnp.asarray(mask), k)
    return np.asarray(vals), np.asarray(idx)


def score_pairs(model: ALSFactors, user_idx: np.ndarray, item_idx: np.ndarray) -> np.ndarray:
    """Predicted rating/score for explicit (user, item) pairs — used by eval
    metrics (RMSE) and batch predict."""
    u = model.user_factors[np.asarray(user_idx)]
    i = model.item_factors[np.asarray(item_idx)]
    return np.sum(u * i, axis=-1)


# ---------------------------------------------------------------------------
# Online fold-in (ISSUE 9): single-side incremental solve
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("implicit", "cg_iterations"))
def _fold_in_jit(
    fixed: jax.Array,  # (N, K) — the OPPOSITE side's factors, held fixed
    edge_idx: jax.Array,  # (R, E) int32 — rows into `fixed` (0 on pads)
    edge_val: jax.Array,  # (R, E) — ratings/weights (0 on pads)
    edge_ok: jax.Array,  # (R, E) — 1.0 real edge / 0.0 padding
    lam: jax.Array,  # () f32
    alpha: jax.Array,  # () f32
    *,
    implicit: bool,
    cg_iterations: int,
) -> jax.Array:
    """Solve R dirty rows' k×k regularized normal-equation systems against
    the fixed opposite factor matrix — ONE ALS half-step restricted to the
    dirty rows (the classic fold-in). Identical operator assembly to
    `_half_step_implicit` / `_half_step_explicit`, but over a dense
    (R, E) per-row edge block instead of the global COO list, so a tick's
    worth of new/changed users solves as one tiny batched device program.
    lam/alpha ride as traced scalars: parameter changes don't recompile."""
    n, k = fixed.shape
    y = fixed[edge_idx]  # (R, E, K)
    eye = jnp.eye(k, dtype=jnp.float32)
    if implicit:
        conf = 1.0 + alpha * jnp.abs(edge_val)
        pref = (edge_val > 0).astype(jnp.float32)
        w_b = conf * pref * edge_ok
        w_g = (conf - 1.0) * edge_ok
        gram = f32_gram(fixed)
        b = jnp.einsum("re,rek->rk", w_b, y)
        a = (
            jnp.einsum("re,rek,rel->rkl", w_g, y, y)
            + gram[None, :, :]
            + lam * eye
        )
    else:
        w_b = edge_val * edge_ok
        b = jnp.einsum("re,rek->rk", w_b, y)
        deg = jnp.sum(edge_ok, axis=1)
        reg = lam * jnp.maximum(deg, 1.0)
        a = (
            jnp.einsum("re,rek,rel->rkl", edge_ok, y, y)
            + reg[:, None, None] * eye
        )

    def matvec(v):
        return jnp.einsum("rkl,rl->rk", a, v)

    return batched_cg(matvec, b, jnp.zeros_like(b), cg_iterations)


_fold_in_jit = _devprof.instrument("als.fold_in", _fold_in_jit, memory=True)


def _fold_edge_bucket(n: int) -> int:
    """Pow2 ladder with a floor of 8 for the per-row edge axis — bounds
    distinct compiled fold-in shapes the way serving buckets do."""
    return max(8, 1 << (max(n, 1) - 1).bit_length())


def fold_in_rows(
    fixed: np.ndarray,  # (N, K) opposite-side factors (host or device)
    edges: Sequence[Sequence[tuple[int, float]]],  # per dirty row: (fixed_row, value)
    params: ALSParams,
    fixed_device: Optional[jax.Array] = None,
) -> np.ndarray:
    """Public single-side fold-in solve (ISSUE 9): for each dirty row,
    solve its regularized least-squares system against the FIXED opposite
    factor matrix and return the (R, K) solved factors.

    Row/edge axes are bucketed to a small pow2 ladder so a streaming
    consumer's ticks reuse a handful of compiled programs; pads carry
    edge_ok=0 and are inert in every term (same discipline as the train
    paths). Rows with zero edges solve to exactly zero."""
    from predictionio_tpu.utils.bucket import batch_bucket

    if not edges:
        return np.zeros((0, params.rank), np.float32)
    r_real = len(edges)
    r_pad = batch_bucket(r_real)
    e_pad = _fold_edge_bucket(max(len(e) for e in edges))
    idx = np.zeros((r_pad, e_pad), np.int32)
    val = np.zeros((r_pad, e_pad), np.float32)
    ok = np.zeros((r_pad, e_pad), np.float32)
    for r, row in enumerate(edges):
        for e, (j, v) in enumerate(row):
            idx[r, e] = j
            val[r, e] = v
            ok[r, e] = 1.0
    fx = fixed_device if fixed_device is not None else jnp.asarray(
        np.asarray(fixed, np.float32)
    )
    solved = _fold_in_jit(
        fx, jnp.asarray(idx), jnp.asarray(val), jnp.asarray(ok),
        jnp.float32(params.lambda_), jnp.float32(params.alpha),
        implicit=params.implicit_prefs,
        cg_iterations=params.cg_iterations,
    )
    return np.asarray(solved)[:r_real]


def warm_start_factors(
    parent: ALSFactors,
    user_vocab: BiMap,
    item_vocab: BiMap,
    params: ALSParams,
) -> tuple[np.ndarray, np.ndarray]:
    """Map a parent version's factors onto a NEW training vocabulary —
    the warm start that makes periodic retrains reconverge with the
    stream instead of re-deriving it from noise (ISSUE 9). Rows whose id
    survives copy the parent's factors; brand-new rows get the standard
    scaled gaussian init (ALS is memoryless in factor state, so a warm
    start changes the trajectory, not the fixed point)."""
    rng = np.random.RandomState(params.seed)

    def align(old_vocab: BiMap, new_vocab: BiMap, old: np.ndarray, n: int):
        out = (
            rng.standard_normal((n, params.rank)).astype(np.float32)
            / np.sqrt(params.rank)
        )
        k = min(params.rank, old.shape[1]) if old.size else 0
        for ident, new_row in new_vocab.items():
            old_row = old_vocab.get(ident)
            if old_row is not None and old_row < old.shape[0] and k:
                out[new_row, :k] = old[old_row, :k]
        return out

    uf0 = align(
        parent.user_vocab, user_vocab, parent.user_factors, len(user_vocab)
    )
    itf0 = align(
        parent.item_vocab, item_vocab, parent.item_factors, len(item_vocab)
    )
    return uf0, itf0
