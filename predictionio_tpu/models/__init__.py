"""TPU model kernels — the in-tree replacements for the MLlib algorithms the
reference's engine templates delegate to (SURVEY.md §2.9/§2.11): ALS
(implicit + explicit), classification (Naive Bayes / logistic regression),
item-similarity, cross-occurrence (CCO), Markov chain."""
