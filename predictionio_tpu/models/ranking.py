"""Shared host-side ranking helpers for serving paths.

The similarproduct and ecommerce templates rank a per-item score vector
after applying host-built business-rule masks. For single-query serving on
small-to-medium catalogs the host argpartition beats a device round trip
(the axon-tunnel dispatch dominates); models/als.py's jitted `recommend`/
`similar_items` remain the batched device path the recommendation engine
uses. One NEG_INF convention, one implementation."""

from __future__ import annotations

import numpy as np

from predictionio_tpu.ops.topk import NEG_INF


def l2_normalize(factors: np.ndarray) -> np.ndarray:
    """Row-normalize a factor matrix for cosine scoring."""
    return factors / (np.linalg.norm(factors, axis=-1, keepdims=True) + 1e-9)


def top_k_indices(scores: np.ndarray, k: int) -> np.ndarray:
    """Indices of the k best scores, sorted descending, masked entries
    (≤ NEG_INF/2) dropped."""
    k = min(k, len(scores))
    if k <= 0:
        return np.empty(0, dtype=np.int64)
    top = np.argpartition(-scores, k - 1)[:k]
    top = top[np.argsort(-scores[top])]
    return top[scores[top] > NEG_INF / 2]


def exclusion_scores(
    scores: np.ndarray, excluded: np.ndarray
) -> np.ndarray:
    return np.where(excluded, NEG_INF, scores)


def top_k_filtered(
    scores: np.ndarray,
    k: int,
    exclude_idx=None,
    include_idx=None,
    positive_only: bool = False,
) -> np.ndarray:
    """Top-k with SPARSE exclusion/inclusion — no dense (I,) bool mask.

    `exclude_idx`: small index collection (seen history, blacklist,
    unavailable items). Over-fetches k + len(exclude) candidates then
    drops excluded ones, so per-query memory is O(k + |exclude|) beyond
    the score vector itself. `include_idx`: whitelist — only these
    indices compete (scores gathered, O(|include|)). `positive_only`
    drops non-positive scores (UR: zero LLR evidence is not a
    recommendation). Returns indices sorted by descending score."""
    if k <= 0 or len(scores) == 0:
        return np.empty(0, dtype=np.int64)
    ex = (
        np.unique(np.asarray(exclude_idx, dtype=np.int64))
        if exclude_idx is not None and len(exclude_idx)
        else None
    )
    if include_idx is not None:
        cand = np.unique(np.asarray(include_idx, dtype=np.int64))
        if ex is not None:
            cand = np.setdiff1d(cand, ex, assume_unique=True)
        cand_scores = scores[cand]
    else:
        m = k + (len(ex) if ex is not None else 0)
        if m >= len(scores):
            cand = np.arange(len(scores), dtype=np.int64)
        else:
            cand = np.argpartition(-scores, m - 1)[:m].astype(np.int64)
        if ex is not None:
            cand = cand[~np.isin(cand, ex, assume_unique=False)]
        cand_scores = scores[cand]
    keep = cand_scores > (0.0 if positive_only else NEG_INF / 2)
    cand, cand_scores = cand[keep], cand_scores[keep]
    top = np.argsort(-cand_scores, kind="stable")[:k]
    return cand[top]
