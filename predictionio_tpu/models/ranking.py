"""Shared host-side ranking helpers for serving paths.

The similarproduct and ecommerce templates rank a per-item score vector
after applying host-built business-rule masks. For single-query serving on
small-to-medium catalogs the host argpartition beats a device round trip
(the axon-tunnel dispatch dominates); models/als.py's jitted `recommend`/
`similar_items` remain the batched device path the recommendation engine
uses. One NEG_INF convention, one implementation."""

from __future__ import annotations

import numpy as np

from predictionio_tpu.ops.topk import NEG_INF


def l2_normalize(factors: np.ndarray) -> np.ndarray:
    """Row-normalize a factor matrix for cosine scoring."""
    return factors / (np.linalg.norm(factors, axis=-1, keepdims=True) + 1e-9)


def top_k_indices(scores: np.ndarray, k: int) -> np.ndarray:
    """Indices of the k best scores, sorted descending, masked entries
    (≤ NEG_INF/2) dropped."""
    k = min(k, len(scores))
    if k <= 0:
        return np.empty(0, dtype=np.int64)
    top = np.argpartition(-scores, k - 1)[:k]
    top = top[np.argsort(-scores[top])]
    return top[scores[top] > NEG_INF / 2]


def exclusion_scores(
    scores: np.ndarray, excluded: np.ndarray
) -> np.ndarray:
    return np.where(excluded, NEG_INF, scores)
