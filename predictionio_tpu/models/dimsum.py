"""Column-cosine item similarity (the DIMSUM workload, computed exactly).

Reference: examples/experimental DIMSUM demo — Spark MLlib's
RowMatrix.columnSimilarities, which SAMPLES (dimension-independent matrix
sketching) because exact all-pairs column products are shuffle-bound on a
cluster. TPU-first re-design: the item-item Gram matrix of a binarized
(or weighted) user×item indicator is ONE dense MXU matmul (AᵀA), so the
similarities are computed EXACTLY — sampling was a distributed-shuffle
workaround, not part of the model. Multi-chip: shard the user dimension
over the mesh's data axis; GSPMD reduces the contraction with an ICI
all-reduce, the same pattern as models/cco.py.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from predictionio_tpu.obs import devprof as _devprof

from predictionio_tpu.ops.topk import masked_top_k


@partial(jax.jit, static_argnames=("top_n",))
def _cosine_topn(matrix: jax.Array, *, top_n: int):
    """matrix: (U, I). Returns per-column top-N cosine-similar columns."""
    gram = jax.lax.dot_general(
        matrix, matrix,
        dimension_numbers=(((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )  # (I, I) — MXU, user dim contracted (psum over dp shards)
    norms = jnp.sqrt(jnp.maximum(jnp.diagonal(gram), 1e-12))
    cos = gram / (norms[:, None] * norms[None, :])
    n_items = cos.shape[0]
    exclude = jnp.eye(n_items, dtype=bool) | (gram <= 0)
    vals, idx = masked_top_k(cos, top_n, exclude)
    idx = jnp.where(vals > 0.0, idx, -1)
    return vals, idx


_cosine_topn = _devprof.instrument("dimsum.cosine_topn", _cosine_topn)


def column_cosine_topn(
    matrix: np.ndarray,  # (U, I) interaction matrix (weighted or binarized)
    top_n: int,
    mesh: Optional[jax.sharding.Mesh] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Per item: top-N most cosine-similar other items.

    Returns (scores (I, top_n), indices (I, top_n)); -1 index padding for
    items with fewer than top_n co-rated neighbours."""
    top_n = min(top_n, max(matrix.shape[1] - 1, 1))
    if mesh is not None:
        from predictionio_tpu.parallel.mesh import pad_and_shard_rows

        (m,) = pad_and_shard_rows(mesh, matrix)
    else:
        m = jnp.asarray(matrix)
    vals, idx = _cosine_topn(m, top_n=top_n)
    return np.asarray(vals), np.asarray(idx)
