"""Correlated cross-occurrence (CCO) with log-likelihood-ratio scoring.

The compute core of the Universal Recommender (BASELINE.json configs #5;
external template actionml/template-scala-parallel-universal-recommendation,
which delegates to Mahout's SimilarityAnalysis.cooccurrences on Spark).

TPU-first design: the cross-occurrence count matrix between a primary
interaction matrix P (users × items) and a secondary indicator matrix S
(users × things) is EXACTLY PᵀS on binarized indicators — one dense MXU
matmul — instead of Mahout's sparse row-similarity shuffle. Dunning's LLR
then scores every (item, thing) pair elementwise on device, and a masked
top-k keeps each item's strongest correlators. Multi-chip: shard the user
dimension over the mesh's data axis; GSPMD reduces the matmul's user
contraction with an ICI all-reduce (psum) — user-partitioned co-occurrence
counting, the TPU-native analogue of Mahout's map-side combining.

Counts stay exact in float32 (counts ≤ U < 2²⁴) with HIGHEST precision.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.ops.topk import NEG_INF, masked_top_k


def _x_log_x(x: jax.Array) -> jax.Array:
    return jnp.where(x > 0, x * jnp.log(jnp.maximum(x, 1e-30)), 0.0)


def llr_scores(
    k11: jax.Array,  # (I, J) co-occurrence counts
    prim_totals: jax.Array,  # (I,) per-item event totals
    sec_totals: jax.Array,  # (J,) per-thing event totals
    n_users: jax.Array | float,
) -> jax.Array:
    """Dunning log-likelihood ratio of the 2×2 contingency per pair."""
    k12 = prim_totals[:, None] - k11
    k21 = sec_totals[None, :] - k11
    k22 = n_users - k11 - k12 - k21
    row_entropy = _x_log_x(k11 + k12) + _x_log_x(k21 + k22)
    col_entropy = _x_log_x(k11 + k21) + _x_log_x(k12 + k22)
    mat_entropy = (
        _x_log_x(k11) + _x_log_x(k12) + _x_log_x(k21) + _x_log_x(k22)
    )
    llr = 2.0 * (mat_entropy - row_entropy - col_entropy + _x_log_x(
        jnp.asarray(n_users, jnp.float32)
    ))
    return jnp.maximum(llr, 0.0)


@partial(jax.jit, static_argnames=("top_n", "exclude_diagonal"))
def _cco_topn(
    primary: jax.Array,  # (U, I) binarized (possibly zero-padded rows)
    secondary: jax.Array,  # (U, J) binarized
    n_users: jax.Array,  # scalar — TRUE user count (padding rows excluded)
    *,
    top_n: int,
    exclude_diagonal: bool,
):
    counts = jax.lax.dot_general(
        primary, secondary,
        dimension_numbers=(((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )  # (I, J) — MXU, user dim contracted (psum over dp shards)
    prim_totals = jnp.sum(primary, axis=0)
    sec_totals = jnp.sum(secondary, axis=0)
    llr = llr_scores(counts, prim_totals, sec_totals, n_users)
    exclude = counts <= 0  # never correlate never-co-occurring pairs
    if exclude_diagonal:
        eye = jnp.eye(llr.shape[0], llr.shape[1], dtype=bool)
        exclude = exclude | eye
    vals, idx = masked_top_k(llr, top_n, exclude)
    idx = jnp.where(vals > 0.0, idx, -1)  # llr 0 → not a correlator
    return vals, idx


def edges_to_indicator(
    rows: np.ndarray, cols: np.ndarray, n_rows: int, n_cols: int
) -> np.ndarray:
    """Binarized dense indicator matrix from an edge list."""
    m = np.zeros((n_rows, n_cols), dtype=np.float32)
    m[rows, cols] = 1.0
    return m


def cross_occurrence_topn(
    primary: np.ndarray,  # (U, I)
    secondary: np.ndarray,  # (U, J)
    top_n: int,
    self_indicator: bool = False,
    mesh: Optional[jax.sharding.Mesh] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Per primary item: top correlator columns of `secondary` by LLR.

    Returns (scores (I, top_n), indices (I, top_n)) with -1 index padding.
    `self_indicator` excludes the diagonal (an item trivially co-occurs
    with itself)."""
    top_n = min(top_n, secondary.shape[1])
    true_n_users = primary.shape[0]
    if mesh is not None:
        # pad the user dim so it shards evenly; zero rows are inert in the
        # counts/totals and the true user count is passed separately for LLR
        from predictionio_tpu.parallel.mesh import pad_and_shard_rows

        p, s = pad_and_shard_rows(mesh, primary, secondary)
    else:
        p = jnp.asarray(primary)
        s = jnp.asarray(secondary)
    vals, idx = _cco_topn(
        p, s, jnp.float32(true_n_users),
        top_n=top_n, exclude_diagonal=self_indicator,
    )
    return np.asarray(vals), np.asarray(idx)


def score_history(
    correlator_idx: np.ndarray,  # (I, top_n) int, -1 padded
    correlator_scores: np.ndarray,  # (I, top_n)
    history: np.ndarray,  # (H,) int — the user's recent things for this indicator
) -> np.ndarray:
    """Serving-side: per-item sum of LLR over correlators present in the
    user's history. Vectorized membership test — no per-item Python."""
    if len(history) == 0:
        return np.zeros(correlator_idx.shape[0], dtype=np.float32)
    hit = np.isin(correlator_idx, history) & (correlator_idx >= 0)
    return np.where(hit, correlator_scores, 0.0).sum(axis=1).astype(np.float32)
