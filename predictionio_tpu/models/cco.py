"""Correlated cross-occurrence (CCO) with log-likelihood-ratio scoring.

The compute core of the Universal Recommender (BASELINE.json configs #5;
external template actionml/template-scala-parallel-universal-recommendation,
which delegates to Mahout's SimilarityAnalysis.cooccurrences on Spark).

TPU-first design: the cross-occurrence count matrix between a primary
interaction matrix P (users × items) and a secondary indicator matrix S
(users × things) is EXACTLY PᵀS on binarized indicators — one dense MXU
matmul — instead of Mahout's sparse row-similarity shuffle. Dunning's LLR
then scores every (item, thing) pair elementwise on device, and a masked
top-k keeps each item's strongest correlators. Multi-chip: shard the user
dimension over the mesh's data axis; GSPMD reduces the matmul's user
contraction with an ICI all-reduce (psum) — user-partitioned co-occurrence
counting, the TPU-native analogue of Mahout's map-side combining.

Counts stay exact in float32 (counts ≤ U < 2²⁴) with HIGHEST precision.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.obs import devprof as _devprof
from predictionio_tpu.ops.topk import NEG_INF, masked_top_k


def _x_log_x(x: jax.Array) -> jax.Array:
    return jnp.where(x > 0, x * jnp.log(jnp.maximum(x, 1e-30)), 0.0)


def llr_scores(
    k11: jax.Array,  # (I, J) co-occurrence counts
    prim_totals: jax.Array,  # (I,) per-item event totals
    sec_totals: jax.Array,  # (J,) per-thing event totals
    n_users: jax.Array | float,
) -> jax.Array:
    """Dunning log-likelihood ratio of the 2×2 contingency per pair."""
    k12 = prim_totals[:, None] - k11
    k21 = sec_totals[None, :] - k11
    k22 = n_users - k11 - k12 - k21
    row_entropy = _x_log_x(k11 + k12) + _x_log_x(k21 + k22)
    col_entropy = _x_log_x(k11 + k21) + _x_log_x(k12 + k22)
    mat_entropy = (
        _x_log_x(k11) + _x_log_x(k12) + _x_log_x(k21) + _x_log_x(k22)
    )
    llr = 2.0 * (mat_entropy - row_entropy - col_entropy + _x_log_x(
        jnp.asarray(n_users, jnp.float32)
    ))
    return jnp.maximum(llr, 0.0)


@partial(jax.jit, static_argnames=("top_n", "exclude_diagonal"))
def _cco_topn(
    primary: jax.Array,  # (U, I_blk) binarized (possibly zero-padded rows)
    secondary: jax.Array,  # (U, J) binarized
    n_users: jax.Array,  # scalar — TRUE user count (padding rows excluded)
    diag_offset: jax.Array,  # scalar — primary block's start column
    *,
    top_n: int,
    exclude_diagonal: bool,
):
    counts = jax.lax.dot_general(
        primary, secondary,
        dimension_numbers=(((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )  # (I_blk, J) — MXU, user dim contracted (psum over dp shards)
    prim_totals = jnp.sum(primary, axis=0)
    sec_totals = jnp.sum(secondary, axis=0)
    llr = llr_scores(counts, prim_totals, sec_totals, n_users)
    exclude = counts <= 0  # never correlate never-co-occurring pairs
    if exclude_diagonal:
        # the diagonal of the GLOBAL (I, I) matrix: global row index =
        # diag_offset + local row (item blocking shifts the block)
        r = jnp.arange(llr.shape[0], dtype=jnp.int32)[:, None] + diag_offset
        c = jnp.arange(llr.shape[1], dtype=jnp.int32)[None, :]
        exclude = exclude | (r == c)
    vals, idx = masked_top_k(llr, top_n, exclude)
    idx = jnp.where(vals > 0.0, idx, -1)  # llr 0 → not a correlator
    return vals, idx


def edges_to_indicator(
    rows: np.ndarray, cols: np.ndarray, n_rows: int, n_cols: int
) -> np.ndarray:
    """Binarized dense indicator matrix from an edge list."""
    m = np.zeros((n_rows, n_cols), dtype=np.float32)
    m[rows, cols] = 1.0
    return m


_cco_topn = _devprof.instrument("cco.topn", _cco_topn)


def cross_occurrence_topn(
    primary: np.ndarray,  # (U, I)
    secondary: np.ndarray,  # (U, J)
    top_n: int,
    self_indicator: bool = False,
    mesh: Optional[jax.sharding.Mesh] = None,
    block_items: int = 8192,
) -> tuple[np.ndarray, np.ndarray]:
    """Per primary item: top correlator columns of `secondary` by LLR.

    Returns (scores (I, top_n), indices (I, top_n)) with -1 index padding.
    `self_indicator` excludes the diagonal (an item trivially co-occurs
    with itself).

    The primary item axis is processed in `block_items`-column blocks so
    the (I_blk, J) LLR intermediate stays bounded — a 100k-item catalog's
    dense (I, I) matrix alone would be 40 GB, past single-chip HBM. Rows
    are independent through LLR and top-k, so blocking is exact. (The
    Mahout reference handles this scale with sparse shuffles; blocking is
    the dense-MXU equivalent.)"""
    top_n = min(top_n, secondary.shape[1])
    true_n_users = primary.shape[0]
    n_items = primary.shape[1]
    if mesh is not None:
        # pad the user dim so it shards evenly; zero rows are inert in the
        # counts/totals and the true user count is passed separately for LLR
        from predictionio_tpu.parallel.mesh import pad_and_shard_rows

        p, s = pad_and_shard_rows(mesh, primary, secondary)
    else:
        p = jnp.asarray(primary)
        s = jnp.asarray(secondary)
    if n_items <= block_items:
        vals, idx = _cco_topn(
            p, s, jnp.float32(true_n_users), jnp.int32(0),
            top_n=top_n, exclude_diagonal=self_indicator,
        )
        return np.asarray(vals), np.asarray(idx)
    # one compiled program serves every block: pad the last block's
    # columns with zero items (counts 0 → excluded → idx -1)
    out_vals = np.empty((n_items, top_n), np.float32)
    out_idx = np.empty((n_items, top_n), np.int32)
    for lo in range(0, n_items, block_items):
        hi = min(lo + block_items, n_items)
        blk = p[:, lo:hi]
        if hi - lo < block_items:
            blk = jnp.pad(blk, ((0, 0), (0, block_items - (hi - lo))))
        vals, idx = _cco_topn(
            blk, s, jnp.float32(true_n_users), jnp.int32(lo),
            top_n=top_n, exclude_diagonal=self_indicator,
        )
        out_vals[lo:hi] = np.asarray(vals)[: hi - lo]
        out_idx[lo:hi] = np.asarray(idx)[: hi - lo]
    return out_vals, out_idx


def score_history(
    correlator_idx: np.ndarray,  # (I, top_n) int, -1 padded
    correlator_scores: np.ndarray,  # (I, top_n)
    history: np.ndarray,  # (H,) int — the user's recent things for this indicator
) -> np.ndarray:
    """Host-side single-query scoring: per-item sum of LLR over correlators
    present in the user's history. Kept as the reference implementation the
    device batch path (batch_score_topk) is tested against."""
    if len(history) == 0:
        return np.zeros(correlator_idx.shape[0], dtype=np.float32)
    hit = np.isin(correlator_idx, history) & (correlator_idx >= 0)
    return np.where(hit, correlator_scores, 0.0).sum(axis=1).astype(np.float32)


# ---------------------------------------------------------------------------
# Device-side batched serving (VERDICT r2 #5)
# ---------------------------------------------------------------------------

_SCORE_BLOCK_I = 8192  # item rows per scan step — bounds the gathered
# (block·top_n, B) intermediate at catalog scale


@partial(jax.jit, static_argnames=("j_sizes", "k", "mode", "packed"))
def _batch_score_topk_jit(
    corr_idx: tuple,  # per indicator: (I, T_m) int32, -1 padded
    corr_scores: tuple,  # per indicator: (I, T_m) float32
    histories: tuple,  # per indicator: (B, H_m) int32, -1 padded
    exclude: jax.Array,  # (B, E) int32 rows / (B, I_p/32) int32 words
    *,
    j_sizes: tuple,  # per indicator: its target-vocab size J_m (static)
    k: int,
    mode=None,  # resolved pallas mode for the fused tail (None = XLA)
    packed: bool = False,  # exclude arrived as bit-packed mask words
):
    """One device program for a whole query batch: per indicator, scatter
    each user's history into a (B, J+1) membership table, gather it at the
    correlator indices (item-row blocks scanned to bound memory), and
    accumulate weighted hits; then mask the per-query exclusion set and
    top-k. Replaces the per-(query × indicator) numpy loop — the UR
    serving hot path runs as ONE jit dispatch per micro-batch.

    The exclusion+top-k tail is the verb-agnostic fused kernel's
    precomputed-score mode (ISSUE 14): with `mode` set the accumulated
    total streams through `ops.recommend_pallas.fused_masked_topk` —
    no masked (B, I) score COPY, no (B, I) exclusion-mask
    materialization (the packed words / row list apply in registers).
    The XLA tail keeps identical semantics for exact mode parity."""
    from predictionio_tpu.ops import recommend_pallas as _rp

    n_items = corr_idx[0].shape[0]
    bsz = histories[0].shape[0]
    i_p = _rp.pad_items(n_items) if mode is not None else n_items
    total = jnp.zeros((bsz, i_p), jnp.float32)
    for idx, sc, hist, j in zip(corr_idx, corr_scores, histories, j_sizes):
        i, t = idx.shape
        hist_safe = jnp.where(hist >= 0, hist, j)
        member = jnp.zeros((bsz, j + 1), jnp.float32)
        member = member.at[
            jnp.arange(bsz)[:, None], hist_safe
        ].set(1.0)
        member = member.at[:, j].set(0.0)  # -1 padding slot is inert
        member_t = member.T  # (J+1, B) — row-gather layout
        i_pad = (-i) % _SCORE_BLOCK_I
        idx_p = jnp.pad(idx, ((0, i_pad), (0, 0)), constant_values=-1)
        sc_p = jnp.pad(sc, ((0, i_pad), (0, 0)))
        n_blk = (i + i_pad) // _SCORE_BLOCK_I
        idx_c = idx_p.reshape(n_blk, _SCORE_BLOCK_I, t)
        sc_c = sc_p.reshape(n_blk, _SCORE_BLOCK_I, t)

        def body(_, ch):
            ix, w0 = ch
            safe = jnp.where(ix >= 0, ix, j).reshape(-1)
            g = member_t[safe].reshape(_SCORE_BLOCK_I, t, bsz)
            w = jnp.where(ix >= 0, w0, 0.0)
            # HIGHEST: f32 LLR sums must match the host reference scorer —
            # default MXU bf16 would reorder close-scoring items
            return None, jnp.einsum(
                "itb,it->ib", g, w, precision=jax.lax.Precision.HIGHEST
            )

        _, outs = jax.lax.scan(body, None, (idx_c, sc_c))
        # pad rows beyond i carry only padded-correlator zeros, so the
        # i_p-wide slice is exact (they are dead in both tails anyway)
        total = total + outs.reshape(-1, bsz)[:i_p].T
    if mode is not None:
        return _rp.fused_masked_topk(
            total,
            mask_bits=exclude if packed else None,
            exclude_rows=None if packed else exclude,
            k=k, n_items=n_items, interpret=(mode == "interpret"),
        )
    if packed:
        ex_mask = _rp.unpack_mask_jnp(exclude, n_items)
    else:
        ex_safe = jnp.where(exclude >= 0, exclude, n_items)
        ex_mask = jnp.zeros((bsz, n_items + 1), bool)
        ex_mask = ex_mask.at[
            jnp.arange(bsz)[:, None], ex_safe
        ].set(True)
        ex_mask = ex_mask[:, :n_items]
    total = jnp.where(ex_mask, NEG_INF, total)
    return jax.lax.top_k(total, k)


# device profiling (ISSUE 3): the UR serving hot path is one executable
# per micro-batch shape; memory=True is safe — warmup covers the ladder
_batch_score_topk_jit = _devprof.instrument(
    "cco.batch_score_topk", _batch_score_topk_jit, memory=True
)


def batch_score_topk(
    indicator_tables: list,  # [(corr_idx jnp/np, corr_scores jnp/np, J), ...]
    histories: list,  # per indicator: (B, H) int32 np, -1 padded
    exclude: np.ndarray,  # (B, E) int32, -1 padded (item space)
    k: int,
    mode: str = "auto",
) -> tuple[np.ndarray, np.ndarray]:
    """Batched UR history scoring + exclusion + top-k in one device
    dispatch. Returns (scores (B, k), item indices (B, k)); entries with
    score <= 0 carry no LLR evidence (callers filter positive-only).

    `mode` gates the fused tail (resolve_mode contract: "auto" → tpu
    where the lowering runs / "interpret" for tests / None|"off" → the
    XLA tail). Narrow exclusion sets ride the kernel's row-list input
    untouched; wider ones bit-pack HOST-side (1/32 the f32-equivalent
    mask bytes over the wire and in HBM)."""
    from predictionio_tpu.ops import recommend_pallas as _rp

    resolved = _rp.resolve_mode(mode)
    exclude = np.asarray(exclude, np.int32)
    packed = False
    ex_dev = exclude
    if resolved is not None and exclude.shape[1] > _rp.ROWLIST_MAX:
        n_items = int(np.asarray(indicator_tables[0][0]).shape[0])
        i_p = _rp.pad_items(n_items)
        mask = np.zeros((exclude.shape[0], i_p), bool)
        for b in range(exclude.shape[0]):
            hits = exclude[b]
            hits = hits[(hits >= 0) & (hits < i_p)]
            mask[b, hits] = True
        ex_dev = _rp.pack_mask_np(mask, i_p)
        packed = True
    vals, idx = _batch_score_topk_jit(
        tuple(jnp.asarray(t[0]) for t in indicator_tables),
        tuple(jnp.asarray(t[1]) for t in indicator_tables),
        tuple(jnp.asarray(h) for h in histories),
        jnp.asarray(ex_dev),
        j_sizes=tuple(int(t[2]) for t in indicator_tables),
        k=k,
        mode=resolved,
        packed=packed,
    )
    return np.asarray(vals), np.asarray(idx)
