"""Linear (ridge) regression as an XLA program.

Parity target: the reference regression examples' delegation to MLlib
LinearRegressionWithSGD (examples/experimental/scala-parallel-regression/
Run.scala:62-64, java-local-regression, scala-local-regression).

TPU-first shape: the normal equations are TWO MXU contractions —
XᵀX (D×D) and Xᵀy (D) — followed by one tiny host-side solve; no SGD
loop at all for the D ≤ a-few-thousand regime these templates live in.
Multi-chip: the batch axis shards over the mesh's data axis and GSPMD
reduces both contractions with an ICI psum (inert weight-0 padding),
exactly the treeAggregate shape MLlib's optimizer uses on Spark."""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from predictionio_tpu.obs import devprof as _devprof


@dataclass
class LinearRegressionModel:
    weights: np.ndarray  # (D,)
    intercept: float

    def predict(self, x: np.ndarray) -> np.ndarray:
        x = np.atleast_2d(np.asarray(x, np.float32))
        return x @ self.weights + self.intercept


@jax.jit
def _normal_eq_terms(x, y, w):
    """Weighted XᵀX and Xᵀy at full f32 precision (psum over dp shards)."""
    xw = x * w[:, None]
    xtx = jax.lax.dot_general(
        xw, x,
        dimension_numbers=(((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )
    xty = jax.lax.dot_general(
        xw, y[:, None],
        dimension_numbers=(((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )[:, 0]
    return xtx, xty, jnp.sum(w), xw.sum(0), jnp.sum(w * y)


_normal_eq_terms = _devprof.instrument(
    "linreg.normal_eq_terms", _normal_eq_terms
)


def train_linear_regression(
    x: np.ndarray,
    y: np.ndarray,
    l2: float = 1e-6,
    fit_intercept: bool = True,
    mesh: Optional[jax.sharding.Mesh] = None,
) -> LinearRegressionModel:
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.float32)
    w = np.ones(len(x), np.float32)
    if mesh is not None:
        from predictionio_tpu.parallel.mesh import pad_and_shard_rows

        xj, yj, wj = pad_and_shard_rows(mesh, x, y, w)
    else:
        xj, yj, wj = jnp.asarray(x), jnp.asarray(y), jnp.asarray(w)
    xtx, xty, n, xsum, ysum = (
        np.asarray(v, np.float64) for v in _normal_eq_terms(xj, yj, wj)
    )
    xtx, xty, mu, ybar = _center_stats(xtx, xty, n, xsum, ysum, fit_intercept)
    a = xtx + l2 * n * np.eye(x.shape[1])
    weights = np.linalg.solve(a, xty).astype(np.float32)
    intercept = float(ybar - mu @ weights) if fit_intercept else 0.0
    return LinearRegressionModel(weights=weights, intercept=intercept)


def _center_stats(xtx, xty, n, xsum, ysum, fit_intercept):
    """Fold the intercept by centering the sufficient statistics:
    (X−μ)ᵀ(X−μ) = XᵀX − n μμᵀ, (X−μ)ᵀ(y−ȳ) = Xᵀy − n μ ȳ."""
    mu = xsum / n
    ybar = ysum / n
    if fit_intercept:
        xtx = xtx - np.outer(mu, mu) * n
        xty = xty - mu * ybar * n
    return xtx, xty, mu, ybar


def train_linear_regression_grid(
    x: np.ndarray,
    y: np.ndarray,
    l2_grid,
    fit_intercept: bool = True,
) -> list[LinearRegressionModel]:
    """Whole l2 grid from ONE pass: the expensive sufficient statistics
    (XᵀX, Xᵀy — the only O(N) device work) are computed once; each grid
    point is a D×D solve (VERDICT r2 #9)."""
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.float32)
    w = np.ones(len(x), np.float32)
    xtx0, xty0, n, xsum, ysum = (
        np.asarray(v, np.float64)
        for v in _normal_eq_terms(jnp.asarray(x), jnp.asarray(y), jnp.asarray(w))
    )
    d = x.shape[1]
    xtx0, xty0, mu, ybar = _center_stats(
        xtx0, xty0, n, xsum, ysum, fit_intercept
    )
    out = []
    for l2 in l2_grid:
        weights = np.linalg.solve(
            xtx0 + float(l2) * n * np.eye(d), xty0
        ).astype(np.float32)
        intercept = float(ybar - mu @ weights) if fit_intercept else 0.0
        out.append(LinearRegressionModel(weights=weights, intercept=intercept))
    return out
