"""Random forest classification as an XLA program (histogram trees).

Parity target: the reference classification template's second algorithm,
MLlib RandomForest (examples/scala-parallel-classification/add-algorithm/
src/main/scala/RandomForestAlgorithm.scala — trainClassifier with
numTrees/maxDepth/maxBins and per-node feature subsampling).

TPU-first design — nothing here is a port of MLlib's RDD logic:
- Features are quantile-binned ONCE on the host to small integer codes
  (maxBins analogue); training never touches raw floats again.
- Trees grow breadth-first with a STATIC depth: every level processes all
  2^level nodes at once, so shapes are fixed and the whole forest trains
  inside one jit with the depth loop unrolled.
- The per-level workhorse is a class-weighted histogram build: one
  segment-sum per feature (lax.scan over features) into a
  (nodes, bins, classes) tensor — scatter-adds the VPU handles natively,
  no per-node Python, no dynamic shapes.
- Split selection is a dense argmax over (feature, bin) Gini gains
  computed from cumulative histograms — pure elementwise + cumsum work
  that XLA fuses.
- The forest axis is vmapped; per-tree randomness (Poisson(1) bootstrap
  weights — the online-bagging approximation — and per-node feature
  subsets) comes from folded PRNG keys.
- Early-stopped nodes route all samples left, so their subtree collapses
  into one leaf at the bottom level; leaf class distributions then need no
  special bookkeeping for variable-depth trees.
- Multi-chip: the sample axis shards over the mesh's data axis; histogram
  segment-sums reduce per shard and GSPMD inserts the ICI psum (weight-0
  padding rows are inert), mirroring the reference's partitioned
  aggregation semantics (PEventAggregator.scala:85-191).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from predictionio_tpu.obs import devprof as _devprof

from predictionio_tpu.ops.segment import segment_sum


# ---------------------------------------------------------------------------
# Host-side quantile binning
# ---------------------------------------------------------------------------


def make_bin_edges(x: np.ndarray, n_bins: int) -> np.ndarray:
    """(D, n_bins-1) per-feature quantile edges."""
    qs = np.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    return np.quantile(x, qs, axis=0).T.astype(np.float32)


def binize(x: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """(N, D) float features → int32 bin codes in [0, n_bins)."""
    out = np.empty(x.shape, np.int32)
    for d in range(x.shape[1]):
        out[:, d] = np.searchsorted(edges[d], x[:, d], side="right")
    return out


# ---------------------------------------------------------------------------
# Device-side training (single tree; the forest axis is vmapped)
# ---------------------------------------------------------------------------


def _feature_mask(key, n_nodes: int, n_feat: int, k: int):
    """Boolean (n_nodes, D) mask selecting exactly k random features per
    node (the RF featureSubsetStrategy analogue)."""
    if k >= n_feat:
        return jnp.ones((n_nodes, n_feat), bool)
    r = jax.random.uniform(key, (n_nodes, n_feat))
    kth = -jax.lax.top_k(-r, k)[0][:, -1]
    return r <= kth[:, None]


def _histograms(xbin, wy, node, n_nodes: int, n_bins: int):
    """(D, n_nodes, n_bins, C) class-weighted histograms for one level."""
    n_classes = wy.shape[1]

    def per_feature(_, xcol):
        keys = node * n_bins + xcol
        h = segment_sum(wy, keys, n_nodes * n_bins)
        return 0, h.reshape(n_nodes, n_bins, n_classes)

    _, hs = jax.lax.scan(per_feature, 0, xbin.T)
    return hs


def _best_splits(hist, feat_mask, min_child_weight: float, n_bins: int):
    """Per-node best (feature, bin) by Gini impurity decrease.

    Returns (feature or -1 for leaf, routing feature >= 0, routing
    threshold; leaves route everything left via threshold = n_bins)."""
    d, n_nodes, _, _ = hist.shape
    eps = 1e-12
    left = jnp.cumsum(hist, axis=2)  # (D, nodes, B, C): counts with bin<=b
    tot = left[0, :, -1, :]  # (nodes, C) — identical for every feature
    right = tot[None, :, None, :] - left
    nl = left.sum(-1)
    nr = right.sum(-1)  # (D, nodes, B)
    child = (nl - (left**2).sum(-1) / jnp.maximum(nl, eps)) + (
        nr - (right**2).sum(-1) / jnp.maximum(nr, eps)
    )
    n_tot = tot.sum(-1)  # (nodes,)
    parent = n_tot - (tot**2).sum(-1) / jnp.maximum(n_tot, eps)
    gain = parent[None, :, None] - child  # (D, nodes, B)
    invalid = (
        (nl < min_child_weight)
        | (nr < min_child_weight)
        | ~feat_mask.T[:, :, None]
    )
    gain = jnp.where(invalid, -jnp.inf, gain)
    flat = gain.transpose(1, 0, 2).reshape(n_nodes, d * n_bins)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
    feat = (best // n_bins).astype(jnp.int32)
    thr = (best % n_bins).astype(jnp.int32)
    is_leaf = ~(best_gain > 0.0)  # no positive gain (or all invalid)
    feature = jnp.where(is_leaf, -1, feat)
    feat_route = jnp.where(is_leaf, 0, feat)
    thr_route = jnp.where(is_leaf, n_bins, thr)
    return feature, feat_route, thr_route


def _route(xbin, node, feat_route, thr_route):
    f = feat_route[node]
    t = thr_route[node]
    xsel = jnp.take_along_axis(xbin, f[:, None], axis=1)[:, 0]
    return node * 2 + (xsel > t).astype(jnp.int32)


def _train_tree(
    key,
    xbin,
    y1h,
    valid,
    *,
    depth: int,
    n_bins: int,
    feat_per_node: int,
    min_child_weight: float,
):
    n, d = xbin.shape
    w = jax.random.poisson(
        jax.random.fold_in(key, 0), 1.0, (n,)
    ).astype(jnp.float32) * valid
    wy = w[:, None] * y1h
    node = jnp.zeros(n, jnp.int32)
    max_nodes = 2 ** (depth - 1)
    features, routes_f, routes_t = [], [], []
    for level in range(depth):
        n_nodes = 2**level
        mask = _feature_mask(
            jax.random.fold_in(key, level + 1), n_nodes, d, feat_per_node
        )
        hist = _histograms(xbin, wy, node, n_nodes, n_bins)
        feature, feat_route, thr_route = _best_splits(
            hist, mask, min_child_weight, n_bins
        )
        node = _route(xbin, node, feat_route, thr_route)
        pad = max_nodes - n_nodes
        features.append(jnp.pad(feature, (0, pad), constant_values=-1))
        routes_f.append(jnp.pad(feat_route, (0, pad)))
        routes_t.append(jnp.pad(thr_route, (0, pad), constant_values=n_bins))
    leaf_counts = segment_sum(wy, node, 2**depth)  # (leaves, C)
    return (
        jnp.stack(features),  # (depth, max_nodes)
        jnp.stack(routes_f),
        jnp.stack(routes_t),
        leaf_counts,
    )


@partial(
    jax.jit,
    static_argnames=(
        "n_trees", "depth", "n_bins", "feat_per_node", "min_child_weight",
        "seed",
    ),
)
def _train_forest_jit(
    xbin, y1h, valid, *,
    n_trees: int, depth: int, n_bins: int, feat_per_node: int,
    min_child_weight: float, seed: int,
):
    keys = jax.random.split(jax.random.PRNGKey(seed), n_trees)
    tree = partial(
        _train_tree,
        depth=depth, n_bins=n_bins, feat_per_node=feat_per_node,
        min_child_weight=min_child_weight,
    )
    features, routes_f, routes_t, leaf_counts = jax.vmap(
        tree, in_axes=(0, None, None, None)
    )(keys, xbin, y1h, valid)
    # leaf class distributions, smoothed toward the global prior so a
    # reachable-but-empty leaf predicts sanely
    prior = y1h.sum(0) / jnp.maximum(y1h.sum(), 1.0)  # (C,)
    counts = leaf_counts + 1e-3 * prior[None, None, :]
    proba = counts / counts.sum(-1, keepdims=True)
    return features, routes_f, routes_t, proba


_train_forest_jit = _devprof.instrument(
    "forest.train", _train_forest_jit
)


def _predict_tree(routes_f, routes_t, proba, xbin, depth: int):
    node = jnp.zeros(xbin.shape[0], jnp.int32)
    for level in range(depth):
        node = _route(xbin, node, routes_f[level], routes_t[level])
    return proba[node]  # (N, C)


@partial(jax.jit, static_argnames=("depth",))
def _predict_forest_jit(routes_f, routes_t, proba, xbin, *, depth: int):
    per_tree = jax.vmap(
        partial(_predict_tree, depth=depth), in_axes=(0, 0, 0, None)
    )(routes_f, routes_t, proba, xbin)
    return per_tree.mean(0)  # (N, C) averaged class distribution


# ---------------------------------------------------------------------------
# Public model
# ---------------------------------------------------------------------------


_predict_forest_jit = _devprof.instrument(
    "forest.predict", _predict_forest_jit
)


@dataclass
class RandomForestModel:
    bin_edges: np.ndarray  # (D, n_bins-1)
    features: np.ndarray  # (T, depth, max_nodes) int32, -1 = leaf
    routes_f: np.ndarray  # (T, depth, max_nodes) routing feature
    routes_t: np.ndarray  # (T, depth, max_nodes) routing bin threshold
    leaf_proba: np.ndarray  # (T, 2^depth, C)
    depth: int

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        xbin = binize(np.atleast_2d(np.asarray(x, np.float32)), self.bin_edges)
        return np.asarray(
            _predict_forest_jit(
                jnp.asarray(self.routes_f),
                jnp.asarray(self.routes_t),
                jnp.asarray(self.leaf_proba),
                jnp.asarray(xbin),
                depth=self.depth,
            )
        )

    def predict(self, x: np.ndarray) -> np.ndarray:
        return self.predict_proba(x).argmax(axis=-1)


def train_random_forest(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    n_trees: int = 20,
    max_depth: int = 6,
    n_bins: int = 32,
    feature_fraction: Optional[float] = None,
    min_child_weight: float = 1.0,
    seed: int = 0,
    mesh: Optional[jax.sharding.Mesh] = None,
) -> RandomForestModel:
    """Train a histogram random forest.

    `feature_fraction` defaults to sqrt(D)/D (the RF "auto" strategy for
    classification). With `mesh`, samples shard over the data axis."""
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.int32)
    n, d = x.shape
    edges = make_bin_edges(x, n_bins)
    xbin = binize(x, edges)
    y1h = np.zeros((n, n_classes), np.float32)
    y1h[np.arange(n), y] = 1.0
    valid = np.ones(n, np.float32)
    if feature_fraction is None:
        feat_per_node = max(1, int(round(np.sqrt(d))))
    else:
        feat_per_node = max(1, min(d, int(round(feature_fraction * d))))
    if mesh is not None:
        from predictionio_tpu.parallel.mesh import pad_and_shard_rows

        xbin_j, y1h_j, valid_j = pad_and_shard_rows(mesh, xbin, y1h, valid)
    else:
        xbin_j, y1h_j, valid_j = (
            jnp.asarray(xbin), jnp.asarray(y1h), jnp.asarray(valid)
        )
    features, routes_f, routes_t, proba = _train_forest_jit(
        xbin_j, y1h_j, valid_j,
        n_trees=n_trees, depth=max_depth, n_bins=n_bins,
        feat_per_node=feat_per_node, min_child_weight=min_child_weight,
        seed=seed,
    )
    return RandomForestModel(
        bin_edges=edges,
        features=np.asarray(features),
        routes_f=np.asarray(routes_f),
        routes_t=np.asarray(routes_t),
        leaf_proba=np.asarray(proba),
        depth=max_depth,
    )
