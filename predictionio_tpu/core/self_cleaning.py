"""Self-cleaning data source: moving-window event trim + compaction.

Reference: core/src/main/scala/io/prediction/core/SelfCleaningDataSource.scala
:24-318 (an ActionML-fork differentiator, RELEASE.md:10-27) — a trait mixed
into DataSources that, before training, (a) folds each entity's
$set/$unset/$delete history into one fresh $set snapshot
(compressPProperties:90), (b) removes exact-duplicate regular events
(removePDuplicates:111), (c) ages out events older than the window, then
writes the cleaned stream back and deletes the replaced rows
(cleanPersistedPEvents:144). `EventWindow(duration, removeDuplicates,
compressProperties)`:314 is the config carrier.
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import re
from dataclasses import dataclass
from typing import Optional

from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.data.aggregator import aggregate_properties
from predictionio_tpu.data.event import (
    DELETE_EVENT,
    SET_EVENT,
    UNSET_EVENT,
    Event,
)
from predictionio_tpu.data.storage.base import EventQuery
from predictionio_tpu.data.store.event_store import EventStoreFacade

log = logging.getLogger(__name__)

_DURATION_RE = re.compile(r"^\s*(\d+)\s*(seconds?|minutes?|hours?|days?|weeks?)\s*$")
_UNIT_SECONDS = {
    "second": 1, "minute": 60, "hour": 3600, "day": 86400, "week": 604800,
}


def parse_duration(s: str) -> _dt.timedelta:
    """"4 days" / "12 hours" → timedelta (the reference parses Scala
    Durations from strings of this shape)."""
    m = _DURATION_RE.match(s)
    if not m:
        raise ValueError(
            f"cannot parse duration {s!r} (expected e.g. '4 days', '12 hours')"
        )
    n, unit = int(m.group(1)), m.group(2).rstrip("s")
    return _dt.timedelta(seconds=n * _UNIT_SECONDS[unit])


@dataclass(frozen=True)
class EventWindow:
    """Reference SelfCleaningDataSource.EventWindow:314."""

    duration: Optional[str] = None  # e.g. "4 days"; None = no age-out
    remove_duplicates: bool = False
    compress_properties: bool = False


class SelfCleaningDataSource:
    """Mixin for DataSources. Subclasses provide `app_name` and
    `event_window` (usually from their params) and call
    `self.clean_persisted_events(ctx)` at the top of read_training."""

    app_name: str
    event_window: Optional[EventWindow] = None

    def clean_persisted_events(self, ctx: RuntimeContext) -> dict[str, int]:
        """Apply the window to the app's stored events. Returns counters
        {compacted, deduplicated, aged_out} for observability."""
        window = self.event_window
        stats = {"compacted": 0, "deduplicated": 0, "aged_out": 0}
        if window is None:
            return stats
        facade = EventStoreFacade(ctx.storage)
        app_id, _ = facade.app_name_to_id(self.app_name)
        store = ctx.storage.get_events()
        events = list(store.find(EventQuery(app_id=app_id)))
        if not events:
            return stats

        cutoff: Optional[_dt.datetime] = None
        if window.duration is not None:
            cutoff = _dt.datetime.now(_dt.timezone.utc) - parse_duration(
                window.duration
            )

        specials = (SET_EVENT, UNSET_EVENT, DELETE_EVENT)
        special = [e for e in events if e.event in specials]
        regular = [e for e in events if e.event not in specials]

        to_delete: list[str] = []
        to_insert: list[Event] = []

        # (a) property compaction: entity's special-event history → one $set
        if window.compress_properties and special:
            by_entity: dict[tuple[str, str], list[Event]] = {}
            for e in special:
                by_entity.setdefault((e.entity_type, e.entity_id), []).append(e)
            for (etype, eid), evs in by_entity.items():
                if len(evs) <= 1:
                    continue  # nothing to compact
                pmap = aggregate_properties(evs).get(eid)
                to_delete.extend(e.event_id for e in evs if e.event_id)
                if pmap is not None:
                    to_insert.append(
                        Event(
                            event=SET_EVENT,
                            entity_type=etype,
                            entity_id=eid,
                            properties=dict(pmap.to_dict()),
                            event_time=pmap.last_updated,
                        )
                    )
                stats["compacted"] += len(evs)

        # (b) exact-duplicate removal on regular events (reference .distinct)
        if window.remove_duplicates:
            seen: set[tuple] = set()
            for e in sorted(regular, key=lambda e: e.event_time):
                # canonical JSON so list/dict-valued properties stay hashable
                key = (
                    e.event, e.entity_type, e.entity_id,
                    e.target_entity_type, e.target_entity_id,
                    json.dumps(e.properties.to_dict(), sort_keys=True),
                )
                if key in seen:
                    if e.event_id:
                        to_delete.append(e.event_id)
                        stats["deduplicated"] += 1
                else:
                    seen.add(key)

        # (c) age-out of regular events beyond the window
        if cutoff is not None:
            already = set(to_delete)
            for e in regular:
                if e.event_time < cutoff and e.event_id and e.event_id not in already:
                    to_delete.append(e.event_id)
                    stats["aged_out"] += 1

        # write snapshots first, then remove replaced rows (reference order:
        # wipe happens only after cleaned data is persisted)
        if to_insert:
            store.insert_batch(to_insert, app_id)
        store.delete_batch(to_delete, app_id)
        log.info(
            "self-cleaning %s: compacted=%d deduplicated=%d aged_out=%d",
            self.app_name, stats["compacted"], stats["deduplicated"],
            stats["aged_out"],
        )
        return stats
