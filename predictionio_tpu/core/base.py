"""Typeless DASE runtime base — the L4 layer every engine builds on.

Re-design of the reference's `core` package (BaseDataSource.scala:31,
BasePreparator.scala:30, BaseAlgorithm.scala:55, BaseServing.scala:28,
BaseEngine.scala:35, BaseEvaluator.scala:36, AbstractDoer.scala:32).

Key departures from the reference, driven by the TPU runtime model:
- The reference threads a `SparkContext` through every stage; here the
  equivalent ambient handle is a `RuntimeContext`: storage registry +
  optional device `Mesh` + workflow params. Data stages return host
  columnar structures / numpy; algorithms stage them into device arrays.
- The reference's P/L/P2L split (RDD-backed vs local models) collapses:
  every model is host-visible Python state whose array leaves may live in
  HBM. `batch_predict` is first-class (not an afterthought) because eval
  throughput on TPU comes from batching queries into one device program.
- `Doer` reflection (constructor-with-Params vs zero-arg) becomes plain
  signature inspection.
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Any, Generic, Optional, Sequence, TypeVar

TD = TypeVar("TD")  # training data
EI = TypeVar("EI")  # eval info
PD = TypeVar("PD")  # prepared data
M = TypeVar("M")  # model
Q = TypeVar("Q")  # query
P = TypeVar("P")  # predicted result
A = TypeVar("A")  # actual result
R = TypeVar("R")  # evaluator result


@dataclass
class WorkflowParams:
    """Reference WorkflowParams.scala:29."""

    batch: str = ""
    verbose: int = 2
    save_model: bool = True
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    # when set, the train run is wrapped in jax.profiler.trace(profile_dir)
    # (SURVEY §5: XLA profiler hook; `pio train --profile DIR`)
    profile_dir: Optional[str] = None


@dataclass
class RuntimeContext:
    """Ambient runtime handle passed to every DASE stage (the re-design of
    the reference's SparkContext created in WorkflowContext.scala:26-45).

    `mesh` is None for single-chip runs; train workflows construct it from
    the engine variant's `mesh` config (parallel/mesh.py:MeshConf)."""

    storage: Any = None  # data.storage.registry.Storage (untyped: layering)
    mesh: Any = None  # Optional[jax.sharding.Mesh]
    mode: str = "train"  # train | eval | serve
    workflow_params: WorkflowParams = field(default_factory=WorkflowParams)
    # the EngineInstance id of the current train run ("" outside train
    # workflows) — keys mid-training checkpoints in MODELDATA
    instance_id: str = ""
    # per-stage wall-clock seconds (read/prepare/train/persist), filled by
    # Engine.train + run_train and recorded on the EngineInstance row
    # (SURVEY §5 observability; reference had only Spark-UI visibility)
    stage_timings: dict = field(default_factory=dict)

    @property
    def is_serving(self) -> bool:
        return self.mode == "serve"


class SanityCheck:
    """Opt-in data validation hook invoked by the train workflow on
    TD/PD/models (reference controller/SanityCheck.scala, called from
    Engine.scala:649-705)."""

    def sanity_check(self) -> None:
        raise NotImplementedError


class StopAfterReadInterruption(Exception):
    """Debug stop-point: --stop-after-read (reference Engine.scala:663)."""


class StopAfterPrepareInterruption(Exception):
    """Debug stop-point: --stop-after-prepare (reference Engine.scala:684)."""


@dataclass(frozen=True)
class PersistentModelManifest:
    """Marker stored in the serialized model list for models persisted by
    the user's own PersistentModel.save (reference workflow package)."""

    class_name: str


def doer(cls: type, params: Any) -> Any:
    """Instantiate a controller class: with its Params if the constructor
    declares one, else zero-arg (reference Doer.apply, AbstractDoer.scala:32-66).

    The decision mirrors the reference's constructor-type check: a first
    positional parameter ANNOTATED as a params dataclass receives the
    params object (defaulted or not); a required positional without such
    an annotation also receives it (duck-typed templates); a constructor
    with only defaulted non-params arguments is called zero-arg."""
    from predictionio_tpu.controller.params import params_class_of

    if params_class_of(cls) is not None:
        return cls(params)
    try:
        sig = inspect.signature(cls.__init__)
    except (TypeError, ValueError):
        return cls()
    n_required = sum(
        1
        for name, p in sig.parameters.items()
        if name != "self"
        and p.kind in (p.POSITIONAL_OR_KEYWORD, p.POSITIONAL_ONLY)
        and p.default is p.empty
    )
    if n_required >= 1:
        return cls(params)
    return cls()


class BaseDataSource(Generic[TD, EI, Q, A]):
    """Reference BaseDataSource.scala:31-52."""

    def read_training(self, ctx: RuntimeContext) -> TD:
        raise NotImplementedError

    def read_eval(
        self, ctx: RuntimeContext
    ) -> list[tuple[TD, EI, list[tuple[Q, A]]]]:
        """Eval sets: (training data, eval info, [(query, actual)])."""
        return []


class BasePreparator(Generic[TD, PD]):
    """Reference BasePreparator.scala:30-42."""

    def prepare(self, ctx: RuntimeContext, td: TD) -> PD:
        raise NotImplementedError


class BaseAlgorithm(Generic[PD, M, Q, P]):
    """Reference BaseAlgorithm.scala:55-123."""

    # serving-time context injected by the deploy server so predict() can
    # read the event store live (the reference reaches the same state via
    # the global Storage singleton behind LEventStore — LEventStore.scala:32)
    _serving_ctx: Optional[RuntimeContext] = None

    def set_serving_context(self, ctx: RuntimeContext) -> None:
        self._serving_ctx = ctx

    @property
    def serving_context(self) -> RuntimeContext:
        return self._serving_ctx if self._serving_ctx is not None else RuntimeContext(mode="serve")

    def train(self, ctx: RuntimeContext, pd: PD) -> M:
        raise NotImplementedError

    def predict(self, model: M, query: Q) -> P:
        raise NotImplementedError

    def batch_predict(
        self, ctx: RuntimeContext, model: M, queries: list[tuple[int, Q]]
    ) -> list[tuple[int, P]]:
        """Bulk predict for eval. Default maps `predict` per query
        (reference P2LAlgorithm.batchPredict:65); TPU algorithms override
        to batch queries into one device program."""
        return [(qx, self.predict(model, q)) for qx, q in queries]

    def query_serializer(self) -> Optional[Any]:
        """Optional custom query/result serde (reference
        CustomQuerySerializer.scala: `querySerializer` formats attached to
        an algorithm, e.g. the regression example's VectorSerializer).
        Return an object with `query_from_json(parsed_json) -> Q` and/or
        `result_to_json(prediction) -> jsonable`; either may be absent.
        When set, the deploy server hands it the RAW parsed JSON (not
        necessarily an object) instead of dataclass extraction."""
        return None

    def query_class(self) -> Optional[type]:
        """Query type for JSON extraction at serving time (reference
        BaseAlgorithm.queryClass via TypeResolver). Resolved from the
        `predict` signature's `query` annotation when present."""
        import typing

        try:
            # get_type_hints, not raw signature annotations: under
            # `from __future__ import annotations` the latter are strings
            hints = typing.get_type_hints(self.predict)
            ann = hints.get("query")
            return ann if isinstance(ann, type) else None
        except (TypeError, ValueError, NameError):
            return None

    def make_persistent_model(
        self, model_id: str, model: M, params: Any
    ) -> Any:
        """Decide the persistence mode for a trained model (reference
        BaseAlgorithm.makePersistentModel:96-112):
        - model implements PersistentModel → user-managed save, store manifest
        - else → return model itself for automatic blob serialization
          (controller.persistent.serialize_models handles non-picklable
          models by degrading to retrain-on-deploy)."""
        save = getattr(model, "save", None)
        if callable(save) and getattr(model, "PERSISTENT", False):
            if save(model_id, params):
                return PersistentModelManifest(
                    class_name=type(model).__module__ + "." + type(model).__qualname__
                )
        return model


class BaseServing(Generic[Q, P]):
    """Reference BaseServing.scala:28-51."""

    def supplement(self, query: Q) -> Q:
        return query

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        raise NotImplementedError


class BaseEvaluatorResult:
    """Reference BaseEvaluator.scala:55-72."""

    no_save: bool = False

    def to_one_liner(self) -> str:
        return ""

    def to_html(self) -> str:
        return ""

    def to_json(self) -> str:
        return ""


class BaseEvaluator(Generic[EI, Q, P, A, R]):
    """Reference BaseEvaluator.scala:36-53."""

    def evaluate(
        self,
        ctx: RuntimeContext,
        evaluation: Any,
        engine_eval_data_set: list[
            tuple[Any, list[tuple[EI, list[tuple[Q, P, A]]]]]
        ],
        params: WorkflowParams,
    ) -> R:
        raise NotImplementedError


class BaseEngine(Generic[EI, Q, P, A]):
    """Reference BaseEngine.scala:35-100."""

    def train(self, ctx: RuntimeContext, engine_params: Any) -> list[Any]:
        raise NotImplementedError

    def eval(
        self, ctx: RuntimeContext, engine_params: Any
    ) -> list[tuple[EI, list[tuple[Q, P, A]]]]:
        """Workflow settings come from ctx.workflow_params (single source;
        the reference threads a separate WorkflowParams — BaseEngine.scala:62)."""
        raise NotImplementedError

    def batch_eval(
        self,
        ctx: RuntimeContext,
        engine_params_list: Sequence[Any],
        fold_indices: Optional[Sequence[int]] = None,
    ) -> list[tuple[Any, list[tuple[EI, list[tuple[Q, P, A]]]]]]:
        """Default: map `eval` over the params grid (reference
        BaseEngine.batchEval:81). FastEvalEngine overrides with prefix
        memoization. `fold_indices` restricts the evaluation to a subset
        of the datasource's eval sets (fleet eval shards, ISSUE 20) —
        only forwarded when set, so eval() overrides without the
        parameter keep working on the full-run path."""
        if fold_indices is None:
            return [(ep, self.eval(ctx, ep)) for ep in engine_params_list]
        return [
            (ep, self.eval(ctx, ep, fold_indices=fold_indices))
            for ep in engine_params_list
        ]

    def params_from_variant_json(self, variant: dict) -> Any:
        raise NotImplementedError
