"""L4 — typeless runtime base (reference core/src/main/scala/io/prediction/core/)."""

from predictionio_tpu.core.base import (
    BaseAlgorithm,
    BaseDataSource,
    BaseEngine,
    BaseEvaluator,
    BaseEvaluatorResult,
    BasePreparator,
    BaseServing,
    PersistentModelManifest,
    RuntimeContext,
    SanityCheck,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    WorkflowParams,
    doer,
)

__all__ = [
    "BaseAlgorithm",
    "BaseDataSource",
    "BaseEngine",
    "BaseEvaluator",
    "BaseEvaluatorResult",
    "BasePreparator",
    "BaseServing",
    "PersistentModelManifest",
    "RuntimeContext",
    "SanityCheck",
    "StopAfterPrepareInterruption",
    "StopAfterReadInterruption",
    "WorkflowParams",
    "doer",
]
