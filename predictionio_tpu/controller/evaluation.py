"""Evaluation wiring + MetricEvaluator (grid search over EngineParams).

Reference: controller/Evaluation.scala:31-122 (engine + metric(s) |
evaluator setters), MetricEvaluator.scala:113-260 (runs primary + other
metrics per variant, picks best by Ordering, writes best.json via
saveEngineJson:190), EngineParamsGenerator.scala:27."""

from __future__ import annotations

import dataclasses
import json
import logging
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.controller.metrics import Metric
from predictionio_tpu.controller.params import params_to_json
from predictionio_tpu.core.base import (
    BaseEngine,
    BaseEvaluator,
    BaseEvaluatorResult,
    RuntimeContext,
    WorkflowParams,
)

log = logging.getLogger(__name__)


@dataclass
class MetricScores:
    """One grid point's outcome (reference MetricEvaluator.scala case class)."""

    engine_params: EngineParams
    score: Any
    other_scores: list[Any] = field(default_factory=list)


class MetricEvaluatorResult(BaseEvaluatorResult):
    """Reference MetricEvaluator.scala:113 result rendering."""

    def __init__(
        self,
        best_score: MetricScores,
        best_index: int,
        metric_header: str,
        other_metric_headers: list[str],
        engine_params_scores: list[MetricScores],
    ):
        self.best_score = best_score
        self.best_index = best_index
        self.metric_header = metric_header
        self.other_metric_headers = other_metric_headers
        self.engine_params_scores = engine_params_scores

    def to_one_liner(self) -> str:
        return f"[{self.metric_header}] best: {self.best_score.score}"

    def _params_dict(self, ep: EngineParams) -> dict:
        return {
            "datasource": json.loads(params_to_json(ep.data_source_params[1])),
            "preparator": json.loads(params_to_json(ep.preparator_params[1])),
            "algorithms": [
                {"name": n, "params": json.loads(params_to_json(p))}
                for n, p in ep.algorithm_params_list
            ],
            "serving": json.loads(params_to_json(ep.serving_params[1])),
        }

    def to_json(self) -> str:
        return json.dumps(
            {
                "metric": self.metric_header,
                "otherMetrics": self.other_metric_headers,
                "bestScore": self.best_score.score,
                "bestIndex": self.best_index,
                "bestEngineParams": self._params_dict(
                    self.best_score.engine_params
                ),
                "scores": [
                    {
                        "score": s.score,
                        "otherScores": s.other_scores,
                        "engineParams": self._params_dict(s.engine_params),
                    }
                    for s in self.engine_params_scores
                ],
            }
        )

    def to_html(self) -> str:
        rows = "".join(
            f"<tr><td>{s.score}</td><td>{s.other_scores}</td>"
            f"<td><code>{self._params_dict(s.engine_params)}</code></td></tr>"
            for s in self.engine_params_scores
        )
        return (
            f"<h2>{self.metric_header}</h2>"
            f"<p>best: {self.best_score.score} (variant #{self.best_index})</p>"
            f"<table><tr><th>score</th><th>others</th><th>params</th></tr>"
            f"{rows}</table>"
        )


class MetricEvaluator(BaseEvaluator):
    """Score every grid point with the primary metric (+ others), keep the
    best (reference MetricEvaluator.scala:215 evaluateBase)."""

    def __init__(
        self,
        metric: Metric,
        other_metrics: Sequence[Metric] = (),
        output_path: Optional[str] = None,
    ):
        self.metric = metric
        self.other_metrics = list(other_metrics)
        self.output_path = output_path  # best.json target (reference :190)

    def evaluate(
        self,
        ctx: RuntimeContext,
        evaluation: Any,
        engine_eval_data_set: list[tuple[EngineParams, list]],
        params: WorkflowParams,
    ) -> MetricEvaluatorResult:
        if not engine_eval_data_set:
            raise ValueError(
                "MetricEvaluator.evaluate: empty engine_eval_data_set — "
                "the tuning grid produced no (EngineParams, eval data) "
                "pairs; check the EngineParamsGenerator"
            )
        scores: list[MetricScores] = []
        for ep, eval_data in engine_eval_data_set:
            score = self.metric.calculate(ctx, eval_data)
            others = [m.calculate(ctx, eval_data) for m in self.other_metrics]
            log.info("metric %s = %s for %s", self.metric.header(), score, ep)
            scores.append(MetricScores(ep, score, others))
        best_index = 0
        for i, s in enumerate(scores):
            if self.metric.compare(s.score, scores[best_index].score) > 0:
                best_index = i
        result = MetricEvaluatorResult(
            best_score=scores[best_index],
            best_index=best_index,
            metric_header=self.metric.header(),
            other_metric_headers=[m.header() for m in self.other_metrics],
            engine_params_scores=scores,
        )
        if self.output_path:
            self.save_best_engine_json(result)
        return result

    def save_best_engine_json(self, result: MetricEvaluatorResult) -> None:
        """Write the winning params as an engine-variant fragment
        (reference saveEngineJson → best.json, MetricEvaluator.scala:190)."""
        assert self.output_path is not None
        with open(self.output_path, "w") as f:
            json.dump(
                result._params_dict(result.best_score.engine_params), f, indent=2
            )
        log.info("best engine params written to %s", self.output_path)


class EngineParamsGenerator:
    """Holds the tuning grid (reference EngineParamsGenerator.scala:27).
    Subclass and set `engine_params_list`."""

    engine_params_list: Sequence[EngineParams] = ()


class Evaluation:
    """Binds an engine to an evaluator (reference Evaluation.scala:31).
    Subclass and set `engine` + one of: `metric` (+ `metrics`), or a full
    `evaluator`."""

    engine: Optional[BaseEngine] = None
    metric: Optional[Metric] = None
    metrics: Sequence[Metric] = ()
    evaluator: Optional[BaseEvaluator] = None
    output_path: Optional[str] = None

    def get_evaluator(self) -> BaseEvaluator:
        if self.evaluator is not None:
            return self.evaluator
        if self.metric is None:
            raise ValueError(
                "Evaluation must define `metric` (or a full `evaluator`)"
            )
        return MetricEvaluator(
            self.metric, list(self.metrics), output_path=self.output_path
        )

    def get_engine(self) -> BaseEngine:
        if self.engine is None:
            raise ValueError("Evaluation must define `engine`")
        return self.engine
