"""Typed params + engine-factory resolution.

The re-design of the reference's JVM-reflection ergonomics (SURVEY.md §7
"hard parts"): engine.json names classes as import-path strings and carries
per-stage params objects; here params are dataclasses validated on
extraction (replacing the json4s/Gson dual stack of
workflow/JsonExtractor.scala:34-164 and WorkflowUtils.extractParams:132),
and classes resolve via `load_symbol` (replacing WorkflowUtils.getEngine:62
class-vs-object reflection).
"""

from __future__ import annotations

import dataclasses
import functools
import importlib
import inspect
import json
import types as _types
import typing
from typing import Any, Optional


@dataclasses.dataclass(frozen=True)
class EmptyParams:
    """Reference controller package object `EmptyParams` (package.scala:105)."""


class ParamsError(ValueError):
    pass


def load_symbol(path: str) -> Any:
    """Resolve "pkg.module.Symbol" (or "pkg.module:Symbol") to the object."""
    if ":" in path:
        mod_name, _, sym = path.partition(":")
    else:
        mod_name, _, sym = path.rpartition(".")
    if not mod_name:
        raise ParamsError(f"not an importable path: {path!r}")
    try:
        mod = importlib.import_module(mod_name)
    except ImportError as e:
        raise ParamsError(f"cannot import module {mod_name!r} for {path!r}: {e}")
    try:
        return getattr(mod, sym)
    except AttributeError:
        raise ParamsError(f"module {mod_name!r} has no symbol {sym!r}")


def params_class_of(cls: type) -> Optional[type]:
    """The Params dataclass a controller class's constructor expects, from
    the first non-self parameter's annotation (the Python analogue of
    Doer's constructor-signature reflection, AbstractDoer.scala:32)."""
    try:
        hints = typing.get_type_hints(cls.__init__)
        sig = inspect.signature(cls.__init__)
    except (TypeError, ValueError, NameError):
        return None
    for name, p in sig.parameters.items():
        if name == "self":
            continue
        if p.kind in (p.POSITIONAL_OR_KEYWORD, p.POSITIONAL_ONLY):
            ann = hints.get(name, p.annotation)
            if isinstance(ann, type) and dataclasses.is_dataclass(ann):
                return ann
        break
    return None


def extract_params(cls: Optional[type], obj: Any) -> Any:
    """Build a params dataclass from a JSON object, strictly: unknown keys
    are errors (the reference validates params JSON against the class via
    Gson/json4s — WorkflowUtils.extractParams:132 'must be valid to your
    Params class'), missing keys fall back to dataclass defaults.
    """
    if cls is None or cls is EmptyParams:
        if obj not in (None, {}, []):
            raise ParamsError(f"params given but no params class declared: {obj!r}")
        return EmptyParams()
    if obj is None:
        obj = {}
    if not isinstance(obj, dict):
        raise ParamsError(f"params for {cls.__name__} must be an object, got {obj!r}")
    if not dataclasses.is_dataclass(cls):
        raise ParamsError(f"params class {cls.__name__} must be a dataclass")
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(obj) - names
    if unknown:
        raise ParamsError(
            f"unknown params for {cls.__name__}: {sorted(unknown)} "
            f"(valid: {sorted(names)})"
        )
    missing = [
        f.name
        for f in dataclasses.fields(cls)
        if f.name not in obj
        and f.default is dataclasses.MISSING
        and f.default_factory is dataclasses.MISSING
    ]
    if missing:
        raise ParamsError(f"missing required params for {cls.__name__}: {missing}")
    hints = _class_hints(cls)
    for key, val in obj.items():
        ann = hints.get(key)
        if ann is not None and not _value_matches(val, ann):
            raise ParamsError(
                f"param {key!r} of {cls.__name__} expects {_ann_name(ann)}, "
                f"got {type(val).__name__} ({val!r})"
            )
    try:
        return cls(**obj)
    except TypeError as e:
        raise ParamsError(f"invalid params for {cls.__name__}: {e}")


@functools.lru_cache(maxsize=256)
def _class_hints(cls: type) -> dict:
    """Resolved annotations per class, cached — extract_params runs on the
    query-serving hot path and hints never change."""
    try:
        return typing.get_type_hints(cls)
    except (TypeError, NameError):
        return {}


def _ann_name(ann: Any) -> str:
    return getattr(ann, "__name__", None) or str(ann)


def _value_matches(val: Any, ann: Any) -> bool:
    """Shallow JSON-shape check of a value against a dataclass field
    annotation — enough to turn a wrong-typed query field into a 400
    instead of a deep kernel crash. Unknown annotation forms pass."""
    origin = typing.get_origin(ann)
    if ann is Any or ann is inspect.Parameter.empty:
        return True
    if origin is typing.Union or origin is _types.UnionType:  # X | Y too
        return any(_value_matches(val, a) for a in typing.get_args(ann))
    if ann is type(None):
        return val is None
    if origin in (list, tuple, set):
        if not isinstance(val, (list, tuple)):
            return False
        args = [a for a in typing.get_args(ann) if a is not Ellipsis]
        if args:
            elem = args[0]
            return all(_value_matches(v, elem) for v in val)
        return True
    if origin is dict:
        return isinstance(val, dict)
    if ann is float:
        return isinstance(val, (int, float)) and not isinstance(val, bool)
    if ann is int:
        return isinstance(val, int) and not isinstance(val, bool)
    if ann is bool:
        return isinstance(val, bool)
    if ann is str:
        return isinstance(val, str)
    if isinstance(ann, type) and dataclasses.is_dataclass(ann):
        return isinstance(val, (dict, ann))
    return True


def params_to_json(params: Any) -> str:
    """Serialize a params dataclass for metadata records (EngineInstance
    rows store per-stage params JSON, EngineInstances.scala:43)."""
    if params is None or isinstance(params, EmptyParams):
        return "{}"
    if dataclasses.is_dataclass(params):
        return json.dumps(dataclasses.asdict(params), sort_keys=True)
    return json.dumps(params, sort_keys=True)
