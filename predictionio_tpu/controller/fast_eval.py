"""FastEvalEngine: grid evaluation with shared-prefix memoization.

Reference: controller/FastEvalEngine.scala:43 (@Experimental) — when a
tuning grid varies only algorithm params, the DataSource read and
Preparator work are identical across grid points; the reference memoizes
pipeline prefixes (prefix case classes :58-90, caches :283-310). Pure
functions + host dict caches make this trivial here; keys are the
canonical params JSON of each prefix."""

from __future__ import annotations

import logging
from typing import Any

from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.controller.params import params_to_json
from predictionio_tpu.core.base import RuntimeContext

log = logging.getLogger(__name__)


def _key(*stage_params) -> str:
    return "|".join(
        f"{name}:{params_to_json(p)}" for name, p in stage_params
    )


class FastEvalEngine(Engine):
    """Drop-in Engine whose batch_eval memoizes DataSource / Preparator /
    Algorithm prefixes across grid points. Per-stage computation counters
    are exposed for tests (reference FastEvalEngineTest counts prefix
    computations)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._ds_cache: dict[str, Any] = {}
        self._prep_cache: dict[str, Any] = {}
        self._algo_cache: dict[str, Any] = {}
        # number of times each stage actually RAN (the reference test
        # asserts computation counts — FastEvalEngineTest prefix counting)
        self.compute_counts = {"datasource": 0, "preparator": 0, "algorithms": 0}

    def _eval_sets(self, ctx: RuntimeContext, ep: EngineParams):
        key = _key(ep.data_source_params)
        if key not in self._ds_cache:
            self.compute_counts["datasource"] += 1
            self._ds_cache[key] = self.make_data_source(ep).read_eval(ctx)
        return self._ds_cache[key]

    def _prepared(self, ctx: RuntimeContext, ep: EngineParams):
        key = _key(ep.data_source_params, ep.preparator_params)
        if key not in self._prep_cache:
            self.compute_counts["preparator"] += 1
            preparator = self.make_preparator(ep)
            self._prep_cache[key] = [
                preparator.prepare(ctx, td)
                for td, _ei, _qa in self._eval_sets(ctx, ep)
            ]
        return self._prep_cache[key]

    def _models(self, ctx: RuntimeContext, ep: EngineParams):
        key = _key(
            ep.data_source_params,
            ep.preparator_params,
            *ep.algorithm_params_list,
        )
        if key not in self._algo_cache:
            self.compute_counts["algorithms"] += 1
            algorithms = self.make_algorithms(ep)
            self._algo_cache[key] = [
                [algo.train(ctx, pd) for algo in algorithms]
                for pd in self._prepared(ctx, ep)
            ]
        return self._algo_cache[key]

    def eval(self, ctx: RuntimeContext, engine_params: EngineParams):
        eval_sets = self._eval_sets(ctx, engine_params)
        fold_models = self._models(ctx, engine_params)
        algorithms = self.make_algorithms(engine_params)
        serving = self.make_serving(engine_params)
        results = []
        for (td, ei, qa), models in zip(eval_sets, fold_models):
            supplemented = [
                (qx, serving.supplement(q)) for qx, (q, _a) in enumerate(qa)
            ]
            per_algo = [
                dict(algo.batch_predict(ctx, model, supplemented))
                for algo, model in zip(algorithms, models)
            ]
            qpa = [
                (q, serving.serve(q, [pa[qx] for pa in per_algo]), a)
                for qx, (q, a) in enumerate(qa)
            ]
            results.append((ei, qpa))
        return results

    def batch_eval(self, ctx: RuntimeContext, engine_params_list):
        """Always the memoized per-point path: the base Engine's
        grid-batched route would bypass this class's prefix caches and
        compute_counts contract."""
        from predictionio_tpu.core.base import BaseEngine

        return BaseEngine.batch_eval(self, ctx, engine_params_list)

    def clear_caches(self) -> None:
        self._ds_cache.clear()
        self._prep_cache.clear()
        self._algo_cache.clear()
