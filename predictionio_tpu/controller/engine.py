"""Engine: binds DASE class maps, concrete train/eval/deploy-rehydration.

Reference controller/Engine.scala (829 LoC): class:80, train:154,
prepareDeploy:196, makeSerializableModels:283, eval:312,
jValueToEngineParams:354, object impls Engine.train:622 / Engine.eval:727;
EngineParams.scala:32,86; SimpleEngine:127; EngineFactory.scala:28.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field as dc_field
from typing import Any, Mapping, Optional, Sequence, Union

from predictionio_tpu.controller.params import (
    EmptyParams,
    ParamsError,
    extract_params,
    params_class_of,
)
from predictionio_tpu.controller.persistent import (
    RetrainOnDeploy,
    load_persistent_model,
)
from predictionio_tpu.core.base import (
    BaseEngine,
    PersistentModelManifest,
    RuntimeContext,
    SanityCheck,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    WorkflowParams,
    doer,
)

log = logging.getLogger(__name__)

# a stage binding: one class, or a name → class map (multi-variant stages)
ClassMap = Union[type, Mapping[str, type]]


@dataclass(frozen=True)
class EngineParams:
    """Named (stage-name, params) per stage + algorithm list (reference
    EngineParams.scala:32)."""

    data_source_params: tuple[str, Any] = ("", EmptyParams())
    preparator_params: tuple[str, Any] = ("", EmptyParams())
    algorithm_params_list: tuple[tuple[str, Any], ...] = ()
    serving_params: tuple[str, Any] = ("", EmptyParams())

    def copy(self, **kw) -> "EngineParams":
        from dataclasses import replace

        return replace(self, **kw)


def _select_folds(eval_sets, fold_indices: Optional[Sequence[int]]):
    """Restrict eval sets to the requested fold indices (fleet eval
    shards, ISSUE 20); identity when unset. Out-of-range folds are a
    spec error, not a silent empty evaluation."""
    if fold_indices is None:
        return eval_sets
    sets = list(eval_sets)
    want = sorted({int(i) for i in fold_indices})
    bad = [i for i in want if i < 0 or i >= len(sets)]
    if bad:
        raise ValueError(
            f"fold_indices {bad} out of range: datasource yields "
            f"{len(sets)} eval set(s)"
        )
    return [sets[i] for i in want]


def _as_classmap(cm: ClassMap) -> Mapping[str, type]:
    if isinstance(cm, Mapping):
        return cm
    return {"": cm}


def _sanity(obj: Any, what: str, wp: WorkflowParams) -> None:
    if wp.skip_sanity_check:
        return
    if isinstance(obj, SanityCheck):
        log.info("sanity check %s", what)
        obj.sanity_check()


def train_stage_histogram():
    """train_stage_seconds{stage} on the process-default registry — any
    server in this process (or `pio metrics`) exposes it on scrape. The
    single declaration point: workflow/core.py records 'persist' through
    this too, so name/labels can never drift apart."""
    from predictionio_tpu.obs import get_default_registry

    return get_default_registry().histogram(
        "train_stage_seconds",
        "train workflow stage durations (read/prepare/train/persist)",
        ("stage",),  # label-bound: literal DASE stage names
    )


def _bridge_train_stage_spans() -> None:
    """The train-stage SPANS are the single timing source (ISSUE 2):
    their durations feed train_stage_seconds{stage} through the span
    recorder's metric bridge — one observation per stage per train, same
    count the direct observe used to produce, but now the trace and the
    histogram can never disagree."""
    from predictionio_tpu.obs.spans import get_default_recorder

    recorder = get_default_recorder()
    for stage in ("read", "prepare", "train", "persist"):
        recorder.bridge(
            f"train.{stage}",
            lambda sp, _s=stage: train_stage_histogram().observe(
                sp.duration, stage=_s
            ),
        )


_bridge_train_stage_spans()


def _stage_span(name: str, **attrs):
    """A span that also snapshots jaxmon's compile counters AND the
    device-profile registry across the stage, attributing XLA
    trace/lower/compile time plus executed FLOPs / HBM bytes / derived
    MFU to the stage that paid them (SURVEY §5: compile cost is the
    train-latency wildcard; ISSUE 3: "the train stage ran at 62% MXU"
    belongs on the stage span, not in hand math)."""
    from contextlib import contextmanager

    from predictionio_tpu.obs import devprof as _devprof
    from predictionio_tpu.obs import spans as _spans
    from predictionio_tpu.obs.jaxmon import compile_snapshot

    @contextmanager
    def cm():
        c0, s0 = compile_snapshot()
        p0 = _devprof.snapshot()
        with _spans.span(name, **attrs) as sp:
            try:
                yield sp
            finally:
                c1, s1 = compile_snapshot()
                if c1 > c0 or s1 > s0:
                    sp.attrs["jit_compiles"] = c1 - c0
                    sp.attrs["jit_compile_sec"] = round(s1 - s0, 4)
                p1 = _devprof.snapshot()
                d_flops = p1.flops - p0.flops
                d_bytes = p1.bytes - p0.bytes
                d_secs = p1.device_seconds - p0.device_seconds
                if d_flops > 0 or d_secs > 0:
                    sp.attrs["device_flops"] = d_flops
                    sp.attrs["device_bytes"] = d_bytes
                    sp.attrs["device_seconds"] = round(d_secs, 4)
                    u = _devprof.mfu(d_flops, d_secs)
                    if u is not None:
                        sp.attrs["mfu"] = round(u, 6)
                    h = _devprof.hbm_fraction(d_bytes, d_secs)
                    if h is not None:
                        sp.attrs["hbm_fraction_of_roof"] = round(h, 6)

    return cm()


class Engine(BaseEngine):
    """Binds named class maps for DataSource/Preparator/Algorithms/Serving
    (reference Engine.scala:80)."""

    def __init__(
        self,
        data_source_classmap: ClassMap,
        preparator_classmap: ClassMap,
        algorithm_classmap: ClassMap,
        serving_classmap: ClassMap,
    ):
        self.data_source_classmap = _as_classmap(data_source_classmap)
        self.preparator_classmap = _as_classmap(preparator_classmap)
        self.algorithm_classmap = _as_classmap(algorithm_classmap)
        self.serving_classmap = _as_classmap(serving_classmap)

    # -- stage instantiation ----------------------------------------------
    def _stage_class(self, cm: Mapping[str, type], name: str, stage: str) -> type:
        if name in cm:
            return cm[name]
        raise ParamsError(
            f"{stage} class {name!r} not bound in engine "
            f"(available: {sorted(cm)})"
        )

    def make_data_source(self, ep: EngineParams):
        name, params = ep.data_source_params
        return doer(self._stage_class(self.data_source_classmap, name, "datasource"), params)

    def make_preparator(self, ep: EngineParams):
        name, params = ep.preparator_params
        return doer(self._stage_class(self.preparator_classmap, name, "preparator"), params)

    def make_algorithms(self, ep: EngineParams) -> list[Any]:
        return [
            doer(self._stage_class(self.algorithm_classmap, name, "algorithm"), params)
            for name, params in ep.algorithm_params_list
        ]

    def make_serving(self, ep: EngineParams):
        name, params = ep.serving_params
        return doer(self._stage_class(self.serving_classmap, name, "serving"), params)

    # -- train (reference Engine.train:154 + object Engine.train:622) ------
    def train(self, ctx: RuntimeContext, engine_params: EngineParams) -> list[Any]:
        # stage timings come FROM the spans (ISSUE 2): ctx.stage_timings
        # feeds the EngineInstance row snapshot, the bridge declared at
        # module import feeds train_stage_seconds{stage}, and the spans
        # themselves land in /debug/traces — one measurement, three views
        wp = ctx.workflow_params
        with _stage_span("train.read") as sp:
            data_source = self.make_data_source(engine_params)
            sp.attrs["datasource"] = type(data_source).__name__
            td = data_source.read_training(ctx)
            _sanity(td, "training data", wp)
        ctx.stage_timings["read"] = sp.duration
        if wp.stop_after_read:
            raise StopAfterReadInterruption()

        with _stage_span("train.prepare") as sp:
            preparator = self.make_preparator(engine_params)
            sp.attrs["preparator"] = type(preparator).__name__
            pd = preparator.prepare(ctx, td)
            _sanity(pd, "prepared data", wp)
        ctx.stage_timings["prepare"] = sp.duration
        if wp.stop_after_prepare:
            raise StopAfterPrepareInterruption()

        with _stage_span("train.train") as sp:
            algorithms = self.make_algorithms(engine_params)
            if not algorithms:
                raise ParamsError("engine has no algorithms configured")
            models = []
            for i, algo in enumerate(algorithms):
                with _stage_span(
                    "train.algorithm", index=i,
                    algorithm=type(algo).__name__,
                ):
                    model = algo.train(ctx, pd)
                _sanity(model, f"model of algorithm #{i}", wp)
                models.append(model)
        ctx.stage_timings["train"] = sp.duration
        return models

    # -- serializable models (reference makeSerializableModels:283) --------
    def make_serializable_models(
        self,
        ctx: RuntimeContext,
        models: list[Any],
        engine_params: EngineParams,
        instance_id: str,
    ) -> list[Any]:
        algorithms = self.make_algorithms(engine_params)
        return [
            algo.make_persistent_model(
                f"{instance_id}-{i}", model, engine_params.algorithm_params_list[i][1]
            )
            for i, (algo, model) in enumerate(zip(algorithms, models))
        ]

    # -- deploy-time re-hydration (reference prepareDeploy:196) ------------
    def prepare_deploy(
        self,
        ctx: RuntimeContext,
        engine_params: EngineParams,
        persisted_models: list[Any],
        instance_id: str = "deploy",
    ) -> list[Any]:
        algorithms = self.make_algorithms(engine_params)
        if len(persisted_models) != len(algorithms):
            raise ParamsError(
                f"persisted model count {len(persisted_models)} != "
                f"algorithm count {len(algorithms)}"
            )
        needs_retrain = any(
            isinstance(m, RetrainOnDeploy) or m is None for m in persisted_models
        )
        retrained: Optional[list[Any]] = None
        if needs_retrain:
            log.info("some models require retrain-on-deploy; running train")
            retrained = self.train(ctx, engine_params)
        out = []
        for i, m in enumerate(persisted_models):
            if isinstance(m, PersistentModelManifest):
                out.append(
                    load_persistent_model(
                        m,
                        f"{instance_id}-{i}",
                        engine_params.algorithm_params_list[i][1],
                    )
                )
            elif isinstance(m, RetrainOnDeploy) or m is None:
                assert retrained is not None
                out.append(retrained[i])
            else:
                out.append(m)
        return out

    # -- eval (reference Engine.eval:312 + object Engine.eval:727) ---------
    def eval(
        self,
        ctx: RuntimeContext,
        engine_params: EngineParams,
        fold_indices: Optional[Sequence[int]] = None,
    ) -> list[Any]:
        data_source = self.make_data_source(engine_params)
        preparator = self.make_preparator(engine_params)
        algorithms = self.make_algorithms(engine_params)
        serving = self.make_serving(engine_params)
        eval_sets = _select_folds(data_source.read_eval(ctx), fold_indices)
        results = []
        for td, ei, qa in eval_sets:
            pd = preparator.prepare(ctx, td)
            models = [algo.train(ctx, pd) for algo in algorithms]
            supplemented = [
                (qx, serving.supplement(q)) for qx, (q, _a) in enumerate(qa)
            ]
            # per-algo batch predict, regrouped per query (reference
            # Engine.scala:770-811 union → groupByKey → serve)
            per_algo: list[dict[int, Any]] = []
            for algo, model in zip(algorithms, models):
                preds = algo.batch_predict(ctx, model, supplemented)
                per_algo.append(dict(preds))
            qpa = []
            for qx, (q, a) in enumerate(qa):
                predictions = [pa[qx] for pa in per_algo]
                p = serving.serve(q, predictions)
                qpa.append((q, p, a))
            results.append((ei, qpa))
        return results

    # -- grid-batched tuning (VERDICT r2 #9; beats the reference's strictly
    # serial Engine.eval grid, Engine.scala:758-764) ------------------------
    def batch_eval(
        self,
        ctx: RuntimeContext,
        engine_params_list,
        fold_indices: Optional[Sequence[int]] = None,
    ):
        eps = list(engine_params_list)
        if self._grid_batchable(ctx, eps):
            return self._batch_eval_grid(ctx, eps, fold_indices=fold_indices)
        return super().batch_eval(ctx, eps, fold_indices=fold_indices)

    def _grid_batchable(self, ctx: RuntimeContext, eps: list) -> bool:
        """True when the grid varies ONLY in a single algorithm's
        hyperparams and that algorithm implements train_grid — then every
        fold trains all grid points in one device program. Mesh evals stay
        serial: the grid kernels are single-device (the per-point train
        path carries the sharding)."""
        if len(eps) < 2 or getattr(ctx, "mesh", None) is not None:
            return False
        if any(len(ep.algorithm_params_list) != 1 for ep in eps):
            return False
        if len({ep.algorithm_params_list[0][0] for ep in eps}) != 1:
            return False
        algo = self.make_algorithms(eps[0])[0]
        if not callable(getattr(algo, "train_grid", None)):
            return False
        from predictionio_tpu.controller.params import params_to_json

        def shared_key(ep):
            return tuple(
                (name, params_to_json(p))
                for name, p in (
                    ep.data_source_params,
                    ep.preparator_params,
                    ep.serving_params,
                )
            )

        key0 = shared_key(eps[0])
        return all(shared_key(ep) == key0 for ep in eps[1:])

    def _batch_eval_grid(
        self,
        ctx: RuntimeContext,
        eps: list,
        fold_indices: Optional[Sequence[int]] = None,
    ):
        ep0 = eps[0]
        data_source = self.make_data_source(ep0)
        preparator = self.make_preparator(ep0)
        serving = self.make_serving(ep0)
        algos = [self.make_algorithms(ep)[0] for ep in eps]
        params_list = [ep.algorithm_params_list[0][1] for ep in eps]
        eval_sets = _select_folds(
            list(data_source.read_eval(ctx)), fold_indices  # may be a generator
        )
        per_ep: list[list] = [[] for _ in eps]
        for td, ei, qa in eval_sets:
            pd = preparator.prepare(ctx, td)
            models = algos[0].train_grid(ctx, pd, params_list)
            supplemented = [
                (qx, serving.supplement(q)) for qx, (q, _a) in enumerate(qa)
            ]
            for i, model in enumerate(models):
                preds = dict(algos[i].batch_predict(ctx, model, supplemented))
                qpa = [
                    (q, serving.serve(q, [preds[qx]]), a)
                    for qx, (q, a) in enumerate(qa)
                ]
                per_ep[i].append((ei, qpa))
        log.info(
            "grid-batched eval: %d points x %d folds trained as %d device "
            "programs", len(eps), len(eval_sets), len(eval_sets),
        )
        return list(zip(eps, per_ep))

    # -- engine.json → EngineParams (reference jValueToEngineParams:354) ---
    @staticmethod
    def _resolve_stage_class(
        cm: Mapping[str, type], name: str, what: str
    ) -> type:
        """Name → class with the single-binding fallback: an unnamed stage
        resolves to the sole bound class."""
        cls = cm.get(name)
        if cls is None and name == "" and len(cm) == 1:
            cls = next(iter(cm.values()))
        if cls is None:
            raise ParamsError(
                f"variant {what} names {name!r}, not bound "
                f"(available: {sorted(cm)})"
            )
        return cls

    def params_from_variant_json(self, variant: dict) -> EngineParams:
        def stage(key: str, cm: Mapping[str, type]) -> tuple[str, Any]:
            obj = variant.get(key)
            if obj is None:
                name, raw = "", None
            else:
                name = obj.get("name", "")
                raw = obj.get("params")
            cls = self._resolve_stage_class(cm, name, key)
            return name, extract_params(params_class_of(cls), raw)

        ds = stage("datasource", self.data_source_classmap)
        prep = stage("preparator", self.preparator_classmap)
        serv = stage("serving", self.serving_classmap)

        algo_list = []
        for obj in variant.get("algorithms", []):
            name = obj.get("name", "")
            cls = self._resolve_stage_class(
                self.algorithm_classmap, name, "algorithm"
            )
            algo_list.append(
                (name, extract_params(params_class_of(cls), obj.get("params")))
            )
        if not algo_list:
            # default: single bound algorithm with default params
            if len(self.algorithm_classmap) == 1:
                name, cls = next(iter(self.algorithm_classmap.items()))
                algo_list = [(name, extract_params(params_class_of(cls), None))]
        return EngineParams(
            data_source_params=ds,
            preparator_params=prep,
            algorithm_params_list=tuple(algo_list),
            serving_params=serv,
        )


class SimpleEngine(Engine):
    """Single-algorithm engine with identity prep + first serving
    (reference EngineParams.scala SimpleEngine:127)."""

    def __init__(self, data_source_class: type, algorithm_class: type):
        from predictionio_tpu.controller.dase import FirstServing, IdentityPreparator

        super().__init__(
            data_source_class, IdentityPreparator, algorithm_class, FirstServing
        )


class EngineFactory:
    """Subclass with `apply()` returning an Engine (reference
    EngineFactory.scala:28); engine.json's engineFactory names it."""

    def apply(self) -> BaseEngine:
        raise NotImplementedError


def resolve_engine(factory: Any) -> BaseEngine:
    """Accept an Engine, an EngineFactory class/instance, or a callable
    returning an Engine (reference WorkflowUtils.getEngine:62 handles
    object-vs-class duality)."""
    if isinstance(factory, BaseEngine):
        return factory
    if isinstance(factory, type):
        factory = factory()
    if isinstance(factory, EngineFactory):
        return factory.apply()
    if callable(factory):
        result = factory()
        if isinstance(result, BaseEngine):
            return result
    raise ParamsError(f"cannot resolve an Engine from {factory!r}")
