"""L3 — user-facing DASE SDK (reference core/src/main/scala/io/prediction/controller/)."""

from predictionio_tpu.controller.dase import (
    Algorithm,
    AverageServing,
    DataSource,
    FirstServing,
    IdentityPreparator,
    Preparator,
    Serving,
)
from predictionio_tpu.controller.engine import (
    Engine,
    EngineFactory,
    EngineParams,
    SimpleEngine,
    resolve_engine,
)
from predictionio_tpu.controller.params import (
    EmptyParams,
    ParamsError,
    extract_params,
    load_symbol,
    params_class_of,
    params_to_json,
)
from predictionio_tpu.controller.persistent import (
    LocalFileSystemPersistentModel,
    PersistentModel,
    RetrainOnDeploy,
    deserialize_models,
    load_persistent_model,
    serialize_models,
)
from predictionio_tpu.core.base import (
    PersistentModelManifest,
    RuntimeContext,
    SanityCheck,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    WorkflowParams,
)

__all__ = [
    "Algorithm",
    "AverageServing",
    "DataSource",
    "EmptyParams",
    "Engine",
    "EngineFactory",
    "EngineParams",
    "FirstServing",
    "IdentityPreparator",
    "LocalFileSystemPersistentModel",
    "ParamsError",
    "Preparator",
    "PersistentModel",
    "PersistentModelManifest",
    "RetrainOnDeploy",
    "RuntimeContext",
    "SanityCheck",
    "Serving",
    "SimpleEngine",
    "StopAfterPrepareInterruption",
    "StopAfterReadInterruption",
    "WorkflowParams",
    "deserialize_models",
    "extract_params",
    "load_persistent_model",
    "load_symbol",
    "params_class_of",
    "params_to_json",
    "resolve_engine",
    "serialize_models",
]
