"""Model persistence: automatic blob serialization + user-managed models.

Reference: 3-mode persistence decided per-algo by
BaseAlgorithm.makePersistentModel (BaseAlgorithm.scala:96-112) —
(a) automatic Kryo blob into MODELDATA (CoreWorkflow.scala:73-79),
(b) user-managed PersistentModel.save + reflective loader
    (PersistentModel.scala:51,94; WorkflowUtils.getPersistentModel:352),
(c) Unit ⇒ retrain-on-deploy (Engine.scala:208-226).

Here (a) uses pickle (model leaves are numpy arrays — device arrays must
be pulled host-side by the algorithm before returning its model), (b) is a
`PersistentModel` subclass with save/load classmethod, (c) is a model of
`None` or a non-picklable model.
"""

from __future__ import annotations

import os
import pickle
from dataclasses import dataclass
from typing import Any, Optional

from predictionio_tpu.core.base import PersistentModelManifest
from predictionio_tpu.controller.params import load_symbol
from predictionio_tpu.utils.env import env_path


@dataclass(frozen=True)
class RetrainOnDeploy:
    """Marker stored for models that cannot/should not be serialized —
    deploy re-runs read→prepare→train (reference Engine.scala:208-226)."""

    algo_index: int


class PersistentModel:
    """User-managed persistence (reference PersistentModel.scala:51,94).

    Subclasses set PERSISTENT = True, implement `save` returning True when
    stored, and a `load(model_id, params)` classmethod."""

    PERSISTENT = True

    def save(self, model_id: str, params: Any) -> bool:
        raise NotImplementedError

    @classmethod
    def load(cls, model_id: str, params: Any) -> "PersistentModel":
        raise NotImplementedError


class LocalFileSystemPersistentModel(PersistentModel):
    """Pickle-to-PIO_FS_BASEDIR convenience base (reference
    LocalFileSystemPersistentModel.scala:40,57)."""

    @staticmethod
    def _path(model_id: str) -> str:
        base = env_path("PIO_FS_BASEDIR")
        os.makedirs(base, exist_ok=True)
        return os.path.join(base, f"pm-{model_id}.pkl")

    def save(self, model_id: str, params: Any) -> bool:
        with open(self._path(model_id), "wb") as f:
            pickle.dump(self, f)
        return True

    @classmethod
    def load(cls, model_id: str, params: Any):
        with open(cls._path(model_id), "rb") as f:
            return pickle.load(f)


def serialize_models(models: list[Any]) -> bytes:
    """Pickle the per-algo model list for MODELDATA. Non-picklable models
    degrade to RetrainOnDeploy markers (reference mode (c))."""
    out: list[Any] = []
    for i, m in enumerate(models):
        if m is None:
            out.append(RetrainOnDeploy(algo_index=i))
            continue
        if isinstance(m, PersistentModelManifest):
            out.append(m)
            continue
        try:
            pickle.dumps(m)
            out.append(m)
        except Exception:
            out.append(RetrainOnDeploy(algo_index=i))
    return pickle.dumps(out)


def deserialize_models(blob: bytes) -> list[Any]:
    return pickle.loads(blob)


def load_persistent_model(
    manifest: PersistentModelManifest, model_id: str, params: Any
) -> Any:
    """Reflectively re-load a user-persisted model (reference
    SparkWorkflowUtils.getPersistentModel, WorkflowUtils.scala:352)."""
    cls = load_symbol(manifest.class_name)
    loader: Optional[Any] = getattr(cls, "load", None)
    if loader is None:
        raise TypeError(f"{manifest.class_name} has no load() classmethod")
    return loader(model_id, params)
