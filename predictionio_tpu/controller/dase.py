"""User-facing DASE base classes + stock servings/preparators.

Reference L3 (core/src/main/scala/io/prediction/controller/): PDataSource/
LDataSource (PDataSource.scala:35, LDataSource.scala:35), PPreparator/
LPreparator/IdentityPreparator (IdentityPreparator.scala:31), PAlgorithm/
P2LAlgorithm/LAlgorithm (PAlgorithm.scala:44, P2LAlgorithm.scala:43,
LAlgorithm.scala:42), LServing/LFirstServing/LAverageServing
(LServing.scala:27, LFirstServing.scala:25, LAverageServing.scala:25).

The P/L split collapses here (see core/base.py docstring); one class per
stage. Templates subclass these four.
"""

from __future__ import annotations

from typing import Sequence

from predictionio_tpu.core.base import (
    A,
    BaseAlgorithm,
    BaseDataSource,
    BasePreparator,
    BaseServing,
    EI,
    M,
    P,
    PD,
    Q,
    RuntimeContext,
    TD,
)


class DataSource(BaseDataSource[TD, EI, Q, A]):
    """Subclass and implement `read_training` (+ `read_eval` for tuning)."""


class Preparator(BasePreparator[TD, PD]):
    """Subclass and implement `prepare`."""


class IdentityPreparator(BasePreparator[TD, TD]):
    """Pass-through TD→PD (reference IdentityPreparator.scala:31)."""

    def prepare(self, ctx: RuntimeContext, td: TD) -> TD:
        return td


class Algorithm(BaseAlgorithm[PD, M, Q, P]):
    """Subclass and implement `train` + `predict` (and override
    `batch_predict` with a device-batched version where eval throughput
    matters)."""


class Serving(BaseServing[Q, P]):
    """Subclass and implement `serve`; override `supplement` to enrich
    queries before prediction (reference LServing.scala:27)."""


class FirstServing(Serving[Q, P]):
    """Serve the first algorithm's prediction (reference LFirstServing.scala:25)."""

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        return predictions[0]


class AverageServing(Serving[Q, float]):
    """Average of numeric predictions (reference LAverageServing.scala:25)."""

    def serve(self, query: Q, predictions: Sequence[float]) -> float:
        return sum(predictions) / len(predictions)
