"""Metric family — per-(Q,P,A) scores reduced over eval sets.

Reference: controller/Metric.scala:36-266 — Metric (with Ordering),
AverageMetric:96, OptionAverageMetric:121, StdevMetric:148,
OptionStdevMetric:176, SumMetric:202, ZeroMetric:231, QPAMetric:256.
The RDD union + .mean()/.stats() reductions become numpy over the
in-memory QPA lists (eval set sizes are host-scale; the heavy compute —
training and batch predict — already ran on device)."""

from __future__ import annotations

import math
from typing import Any, Generic, Optional, Sequence, TypeVar

import numpy as np

from predictionio_tpu.core.base import A, EI, P, Q, RuntimeContext

R = TypeVar("R")

EvalData = Sequence[tuple[Any, Sequence[tuple[Any, Any, Any]]]]  # [(EI, [(Q,P,A)])]


class Metric(Generic[EI, Q, P, A, R]):
    """Subclass and implement `calculate`. `higher_is_better=False` flips
    the comparison used to pick the best engine params (the reference
    parameterizes an Ordering)."""

    higher_is_better: bool = True

    def header(self) -> str:
        return type(self).__name__

    def calculate(self, ctx: RuntimeContext, data: EvalData) -> R:
        raise NotImplementedError

    def compare(self, a: R, b: R) -> int:
        """sign(a - b) in 'betterness' space. NaN always loses — a grid
        point with no defined scores must never win best-params selection."""
        a_nan = isinstance(a, float) and math.isnan(a)
        b_nan = isinstance(b, float) and math.isnan(b)
        if a_nan or b_nan:
            return 0 if a_nan and b_nan else (-1 if a_nan else 1)
        if a == b:
            return 0
        better = a > b if self.higher_is_better else a < b  # type: ignore[operator]
        return 1 if better else -1


class QPAMetric(Metric[EI, Q, P, A, R]):
    """Per-tuple score hook (reference QPAMetric:256)."""

    def calculate_one(self, q: Q, p: P, a: A) -> R:
        raise NotImplementedError


class AverageMetric(QPAMetric[EI, Q, P, A, float]):
    """Mean of per-tuple scores across all eval sets (reference :96)."""

    def calculate(self, ctx: RuntimeContext, data: EvalData) -> float:
        scores = [
            self.calculate_one(q, p, a) for _, qpa in data for q, p, a in qpa
        ]
        return float(np.mean(scores)) if scores else float("nan")


class OptionAverageMetric(QPAMetric[EI, Q, P, A, float]):
    """Mean of the defined (non-None) scores only (reference :121)."""

    def calculate_one(self, q: Q, p: P, a: A) -> Optional[float]:  # type: ignore[override]
        raise NotImplementedError

    def calculate(self, ctx: RuntimeContext, data: EvalData) -> float:
        scores = [
            s
            for _, qpa in data
            for q, p, a in qpa
            if (s := self.calculate_one(q, p, a)) is not None
        ]
        return float(np.mean(scores)) if scores else float("nan")


class StdevMetric(QPAMetric[EI, Q, P, A, float]):
    """Population stdev of per-tuple scores (reference :148)."""

    def calculate(self, ctx: RuntimeContext, data: EvalData) -> float:
        scores = [
            self.calculate_one(q, p, a) for _, qpa in data for q, p, a in qpa
        ]
        return float(np.std(scores)) if scores else float("nan")


class OptionStdevMetric(QPAMetric[EI, Q, P, A, float]):
    """Population stdev of defined scores (reference :176)."""

    def calculate_one(self, q: Q, p: P, a: A) -> Optional[float]:  # type: ignore[override]
        raise NotImplementedError

    def calculate(self, ctx: RuntimeContext, data: EvalData) -> float:
        scores = [
            s
            for _, qpa in data
            for q, p, a in qpa
            if (s := self.calculate_one(q, p, a)) is not None
        ]
        return float(np.std(scores)) if scores else float("nan")


class SumMetric(QPAMetric[EI, Q, P, A, float]):
    """Sum of per-tuple scores (reference :202)."""

    def calculate(self, ctx: RuntimeContext, data: EvalData) -> float:
        return float(
            sum(self.calculate_one(q, p, a) for _, qpa in data for q, p, a in qpa)
        )


class ZeroMetric(Metric[EI, Q, P, A, float]):
    """Always 0 — placeholder for eval runs that only want side effects
    (reference :231)."""

    def calculate(self, ctx: RuntimeContext, data: EvalData) -> float:
        return 0.0


def is_defined_number(x: Any) -> bool:
    return isinstance(x, (int, float)) and not (
        isinstance(x, float) and math.isnan(x)
    )
