"""Replica membership for a query server (ISSUE 15).

`ReplicaMember` makes one `QueryServer` a citizen of the replicated
serving tier: it derives the durable replica identity, registers a
heartbeating `pio_query_replica` record (engines/tenants served,
serve_dtype tier, advertised URL), and implements graceful drain — the
three-step zero-drop retirement the gateway drives:

1. the record's ``draining`` flag flips (the gateway's sync pass stops
   routing new queries here within one sync interval),
2. the replica finishes its in-flight queries (tracked by the server's
   in-flight counter; late stragglers the gateway raced in still get
   answers — draining refuses nothing),
3. the server stops and the record is removed.

Attaching a member also stamps the replica id into the server, which
changes the DEFAULT online fold-in cursor name (workflow/server.py
`attach_online`): per-replica cursor identity stops being an operator
convention (the PR-9 caveat) and becomes automatic.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.gateway.identity import replica_identity
from predictionio_tpu.gateway.registry import ReplicaInfo, ReplicaRegistry
from predictionio_tpu.utils.env import env_float

log = logging.getLogger(__name__)


def _utcnow_iso() -> str:
    import datetime as _dt

    return _dt.datetime.now(_dt.timezone.utc).isoformat()


@dataclass
class ReplicaConfig:
    """Replica-membership knobs."""

    # where the durable replica id lives (a per-replica local path —
    # NOT the shared storage; two replicas sharing it would share an
    # identity, which is exactly the bug this exists to kill)
    state_dir: str = "~/.predictionio_tpu/replica"
    # explicit identity override (tests; wins over state_dir)
    replica_id: Optional[str] = None
    url: str = ""  # advertised base URL (http://host:port)
    engines: list[str] = field(default_factory=list)
    tenants: list[str] = field(default_factory=list)
    serve_dtype: str = "f32"
    heartbeat_interval_s: float = field(
        default_factory=lambda: env_float("PIO_REPLICA_HEARTBEAT_S", 1.0)
    )
    # drain: max seconds to wait for in-flight queries before stopping
    drain_timeout_s: float = 30.0
    # post-drain grace for gateway-raced stragglers to arrive
    drain_grace_s: float = 0.25


class ReplicaMember:
    """One query server's presence in the replicated tier."""

    def __init__(
        self,
        storage: Storage,
        server,
        config: Optional[ReplicaConfig] = None,
    ):
        self.storage = storage
        self.server = server
        self.config = config or ReplicaConfig()
        self.replica_id = self.config.replica_id or replica_identity(
            self.config.state_dir
        )
        self.registry = ReplicaRegistry(storage)
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_event: Optional[str] = None
        self._lock = threading.Lock()
        self._draining = False  # guarded-by: _lock
        self._drain_thread: Optional[threading.Thread] = None  # guarded-by: _lock

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        url = self.config.url
        if not url:
            # late-bound: the server's port is only known after start
            url = f"http://127.0.0.1:{self.server.port}"
        self.url = url
        # stamp replica identity on every span this server emits, so
        # the fleet trace collector's assembled tree shows WHICH
        # replica answered each gateway attempt (ISSUE 16)
        srv = getattr(self.server, "_server", None)
        if srv is not None:
            # merge, don't replace: the query server already stamped
            # its engine identity here (workflow/server.py)
            attrs = dict(getattr(srv, "span_attrs", None) or {})
            attrs["replica"] = self.replica_id
            srv.span_attrs = attrs
        self.registry.upsert(ReplicaInfo(
            id=self.replica_id,
            url=url,
            host=socket.gethostname(),
            pid=os.getpid(),
            started_at=_utcnow_iso(),
            heartbeat_at=time.time(),
            engines=list(self.config.engines),
            tenants=list(self.config.tenants),
            serve_dtype=self.config.serve_dtype,
            draining=False,
        ))
        self._stop.clear()
        self._hb_thread = threading.Thread(
            target=self._hb_loop, name="replica-heartbeat", daemon=True
        )
        self._hb_thread.start()

    def stop(self, deregister: bool = True) -> None:
        self._stop.set()
        t = self._hb_thread
        if t is not None:
            t.join(timeout=self.config.heartbeat_interval_s + 5)
            self._hb_thread = None
        with self._lock:
            dt = self._drain_thread
        if dt is not None and dt is not threading.current_thread():
            dt.join(timeout=1.0)
        if deregister:
            try:
                self.registry.remove(self.replica_id)
            except Exception:
                log.debug(
                    "replica deregister failed (non-fatal)", exc_info=True
                )

    def _hb_loop(self) -> None:
        while not self._stop.wait(self.config.heartbeat_interval_s):
            try:
                with self._lock:
                    draining = self._draining
                # only ever ASSERT draining on a beat, never deny it: a
                # gateway that flagged the record but whose drain notify
                # was lost must not have the flag erased by our next
                # last-write-wins beat (registration's upsert is the one
                # place draining legitimately resets to False)
                self._hb_event = self.registry.heartbeat(
                    self.replica_id, self._hb_event,
                    inflight=self.server.inflight_queries,
                    draining=True if draining else None,
                )
            except Exception:
                log.warning(
                    "replica heartbeat failed (storage down?); continuing",
                    exc_info=True,
                )

    # -- graceful drain ----------------------------------------------------
    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def drain(self) -> bool:
        """Begin graceful retirement; returns False when already
        draining. Flags the record (the gateway stops routing), then a
        background thread waits out in-flight queries and stops the
        server — which also deregisters this member."""
        with self._lock:
            if self._draining:
                return False
            self._draining = True
            # the thread stops the server, which joins THIS member's
            # heartbeat thread — same self-stop shape as the /stop
            # route; it exits with the process
            # lint: disable=thread-lifecycle — self-stop: drain tears
            # down the server that owns this member; joined best-effort
            # in stop() when the stop arrives from elsewhere first
            self._drain_thread = threading.Thread(
                target=self._drain_and_stop, name="replica-drain",
                daemon=True,
            )
        try:
            self.registry.set_draining(self.replica_id, True)
        except Exception:
            log.warning(
                "drain flag write failed; gateway will stop routing on "
                "the next heartbeat instead", exc_info=True,
            )
        self._drain_thread.start()
        return True

    def _drain_and_stop(self) -> None:
        deadline = time.monotonic() + self.config.drain_timeout_s
        # wait for the gateway to observe the flag and for in-flight
        # queries (including stragglers it raced in) to finish
        while time.monotonic() < deadline:
            if self.server.inflight_queries == 0:
                time.sleep(self.config.drain_grace_s)
                if self.server.inflight_queries == 0:
                    break
            else:
                time.sleep(0.05)
        log.info(
            "replica %s drained (inflight=%d); stopping",
            self.replica_id, self.server.inflight_queries,
        )
        try:
            self.server.stop()
        except Exception:
            log.exception("post-drain server stop failed")

    # -- reporting ---------------------------------------------------------
    def status(self) -> dict[str, Any]:
        return {
            "replica_id": self.replica_id,
            "url": getattr(self, "url", self.config.url),
            "draining": self.draining,
            "inflight": self.server.inflight_queries,
            "serve_dtype": self.config.serve_dtype,
            "engines": list(self.config.engines),
        }
