"""Gateway subsystem (ISSUE 15): the fault-tolerant replicated serving
tier — ROADMAP direction 2's "heavy traffic from millions of users"
availability layer.

- **registry.py** — heartbeating `pio_query_replica` records on the
  shared lifecycle record layer (the fleet worker-record mechanism),
- **identity.py** — durable per-replica identity, which also scopes
  each replica's online fold-in cursor (no shared-cursor double-fold),
- **ring.py** — consistent-hash ring with bounded-load overflow,
- **replica.py** — `ReplicaMember`: registration + heartbeats +
  zero-drop graceful drain for one QueryServer,
- **server.py** — `GatewayServer`: the L7 router (health/SLO-aware
  routing, hedged queries at the rolling p95 mark, failover, drain),
- **autoscale.py** — the closed-loop `Autoscaler` policy + the
  subprocess ReplicaManager for tests/bench,
- **replica_main.py** — the replica subprocess entry.

Import discipline: the gateway runs as a data-plane process — this
package must never import jax (CI guards it).
"""

from predictionio_tpu.gateway.autoscale import (
    Autoscaler,
    AutoscalerConfig,
    ReplicaManager,
    ScaleDecision,
    SubprocessReplicaManager,
)
from predictionio_tpu.gateway.identity import replica_identity
from predictionio_tpu.gateway.registry import (
    REPLICA_ENTITY,
    ReplicaInfo,
    ReplicaRegistry,
)
from predictionio_tpu.gateway.replica import ReplicaConfig, ReplicaMember
from predictionio_tpu.gateway.ring import HashRing
from predictionio_tpu.gateway.server import GatewayConfig, GatewayServer

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "GatewayConfig",
    "GatewayServer",
    "HashRing",
    "REPLICA_ENTITY",
    "ReplicaConfig",
    "ReplicaInfo",
    "ReplicaManager",
    "ReplicaMember",
    "ReplicaRegistry",
    "ScaleDecision",
    "SubprocessReplicaManager",
    "replica_identity",
]
