"""Durable replica identity (ISSUE 15: the replica-correctness fix).

N replicas folding the same event stream must never share an online
fold-in cursor — two writers on one single-writer cursor record
leapfrog each other's positions and double-fold events (the PR-9
caveat, until now an operator convention: "name each replica's
cursor"). The convention becomes automatic here: every replica derives
a **durable** identity persisted next to its local state, the identity
is stamped into the replica registry record, and the query server's
`attach_online` appends it to the default cursor name — a replica
restart resumes ITS cursor (crash-resume preserved), while a second
replica on the same storage gets a different one by construction.
"""

from __future__ import annotations

import logging
import os
import uuid

log = logging.getLogger(__name__)

_ID_FILE = "replica.id"


def replica_identity(state_dir: str) -> str:
    """The durable replica id persisted under `state_dir` (created on
    first call, re-read forever after). The id doubles as the online
    cursor-name suffix, so durability here IS cursor-resume
    correctness: a fresh id per boot would orphan the old cursor and
    re-fold its whole window."""
    state_dir = os.path.expanduser(state_dir)
    os.makedirs(state_dir, exist_ok=True)
    path = os.path.join(state_dir, _ID_FILE)
    try:
        with open(path) as f:
            rid = f.read().strip()
        if rid:
            return rid
    except OSError:
        pass
    rid = f"replica-{uuid.uuid4().hex[:12]}"
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(rid + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    log.info("minted durable replica identity %s at %s", rid, path)
    return rid
