"""Replica subprocess entry (`python -m predictionio_tpu.gateway.replica_main`).

The in-tree replica the SubprocessReplicaManager, the chaos e2e tests,
and `bench.py --gateway` spawn. Two modes:

- ``--stub`` (tests/bench): serves a deterministic echo engine with an
  optional straggler knob — no storage reads on the query path, no jax
  — so gateway semantics (routing, hedging, failover, drain) are
  measurable without training a model per replica,
- default: `pio deploy` semantics — loads the latest COMPLETED
  instance of ``--engine/--variant`` from shared storage and serves it.

Either way the process registers a heartbeating replica record
(storage from the standard ``PIO_STORAGE_*`` env) under a DURABLE
identity (--state-dir / --replica-id), so a kill -9 + restart rejoins
as the SAME replica — and would resume the same online cursor.
"""

from __future__ import annotations

import argparse
import datetime as _dt
import logging
import signal
import time
from typing import Any, Optional

from predictionio_tpu.data.storage.base import EngineInstance
from predictionio_tpu.data.storage.registry import Storage, StorageConfig
from predictionio_tpu.gateway.replica import ReplicaConfig, ReplicaMember
from predictionio_tpu.workflow.server import (
    EngineRuntime,
    QueryServer,
    QueryServerConfig,
    latest_completed_runtime,
)

log = logging.getLogger(__name__)


class _StubAlgo:
    """Echo algorithm: replies with the query, the replica id, and a
    deterministic straggler delay — every `slow_every`-th query sleeps
    `slow_ms` (the hedging bench's tail source)."""

    def __init__(self, replica_id: str, slow_every: int, slow_ms: float):
        self.replica_id = replica_id
        self.slow_every = slow_every
        self.slow_ms = slow_ms
        self._n = 0
        self.serving_context = None

    def predict(self, model: Any, query: Any) -> dict:
        self._n += 1
        sleep_ms = 0.0
        if isinstance(query, dict):
            sleep_ms = float(query.get("sleep_ms") or 0.0)
        if not sleep_ms and self.slow_every and (
            self._n % self.slow_every == 0
        ):
            sleep_ms = self.slow_ms
        if sleep_ms:
            time.sleep(sleep_ms / 1000.0)
        return {"echo": query, "replica": self.replica_id}


class _StubServing:
    def supplement(self, query: Any) -> Any:
        return query

    def serve(self, query: Any, predictions: list) -> Any:
        return predictions[0]


def stub_runtime(
    replica_id: str, slow_every: int = 0, slow_ms: float = 0.0
) -> EngineRuntime:
    now = _dt.datetime.now(_dt.timezone.utc)
    return EngineRuntime(
        instance=EngineInstance(
            id=f"stub-{replica_id}", status="COMPLETED",
            start_time=now, end_time=now,
            engine_id="stub", engine_version="0", engine_variant="stub",
            engine_factory="gateway.replica_main.stub",
        ),
        engine=None,
        engine_params=None,
        algorithms=[_StubAlgo(replica_id, slow_every, slow_ms)],
        models=[None],
        serving=_StubServing(),
        query_class=None,
    )


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="pio replica",
        description="One query-server replica of the gateway tier",
    )
    ap.add_argument("--ip", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--state-dir", default=None,
                    help="durable replica-identity directory")
    ap.add_argument("--replica-id", default=None,
                    help="explicit identity (overrides --state-dir)")
    ap.add_argument("--stub", action="store_true",
                    help="serve the echo stub engine (tests/bench)")
    ap.add_argument("--slow-every", type=int, default=0,
                    help="stub: every Nth query is a straggler")
    ap.add_argument("--slow-ms", type=float, default=200.0,
                    help="stub: straggler sleep in ms")
    ap.add_argument("--engine", default=None)
    ap.add_argument("--engine-version", default="0")
    ap.add_argument("--variant", default=None)
    ap.add_argument("--serve-dtype", default="f32")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    storage = Storage(StorageConfig.from_env())
    if args.stub:
        # identity has to exist before the runtime so the stub can echo
        # it; ReplicaConfig resolves the same way below
        from predictionio_tpu.gateway.identity import replica_identity

        rid = args.replica_id or replica_identity(
            args.state_dir or "~/.predictionio_tpu/replica"
        )
        runtime = stub_runtime(rid, args.slow_every, args.slow_ms)
        engines = ["stub"]
    else:
        if not args.engine:
            ap.error("--engine is required without --stub")
        rid = args.replica_id
        runtime = latest_completed_runtime(
            storage, args.engine, args.engine_version,
            args.variant or args.engine,
        )
        engines = [args.engine]

    server = QueryServer(
        storage, runtime,
        QueryServerConfig(ip=args.ip, port=args.port,
                          micro_batch=not args.stub),
    )
    port = server.start()
    member = ReplicaMember(storage, server, ReplicaConfig(
        state_dir=args.state_dir or "~/.predictionio_tpu/replica",
        replica_id=rid,
        url=f"http://{args.ip if args.ip != '0.0.0.0' else '127.0.0.1'}"
            f":{port}",
        engines=engines,
        serve_dtype=args.serve_dtype,
    ))
    server.attach_replica(member)
    log.info(
        "replica %s serving on :%d", member.replica_id, port
    )

    def _term(_sig, _frm):
        # graceful: drain (zero-drop) — the drain thread stops the
        # server, which unblocks serve_forever's join below
        if not member.drain():
            server.stop()

    signal.signal(signal.SIGTERM, _term)
    try:
        server._thread.join()  # noqa: SLF001 — the serve loop
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
