"""Replica registry: heartbeating presence records for query-server
replicas (ISSUE 15 tentpole part 1).

The exact mechanism the training fleet's `pio_fleet_worker` records
proved (fleet/coordinator.py): each replica appends a heartbeating
record to the shared lifecycle record layer, every reader folds the
entity to see who is alive, and a crashed replica simply goes stale.
Replicas get their own entity (`pio_query_replica`) rather than riding
the worker entity: a serving replica is not a claimable train worker,
and `pio fleet status` must not count one as spare train capacity.

The record carries what the GATEWAY needs to route:

- ``id`` — the durable replica identity (gateway/identity.py), which is
  also the suffix of the replica's online fold-in cursor record, so N
  replicas folding one stream never share a cursor,
- ``url`` — the advertised base URL queries proxy to,
- ``engines`` / ``tenants`` — what this replica serves (informational;
  routing today assumes a homogeneous tier per gateway),
- ``serve_dtype`` — the replica's serving-precision tier (f32/bf16/
  int8), surfaced so operators can see a mixed-tier fleet at a glance,
- ``draining`` — set during graceful drain so the gateway stops
  routing BEFORE the replica stops answering,
- ``heartbeat_at`` / ``inflight`` — liveness + load, compacted to one
  live beat event per replica (the worker-registry discipline).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Optional

from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.deploy.registry import LifecycleRecordStore

REPLICA_ENTITY = "pio_query_replica"


@dataclass
class ReplicaInfo:
    """One serving replica's presence record."""

    id: str
    url: str = ""
    host: str = ""
    pid: int = 0
    started_at: str = ""
    heartbeat_at: float = 0.0
    engines: list[str] = field(default_factory=list)
    tenants: list[str] = field(default_factory=list)
    serve_dtype: str = "f32"
    draining: bool = False
    inflight: int = 0

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id, "url": self.url, "host": self.host,
            "pid": self.pid, "started_at": self.started_at,
            "heartbeat_at": self.heartbeat_at,
            "engines": list(self.engines), "tenants": list(self.tenants),
            "serve_dtype": self.serve_dtype, "draining": self.draining,
            "inflight": self.inflight,
        }

    @staticmethod
    def from_dict(d: dict) -> "ReplicaInfo":
        r = ReplicaInfo(id=d.get("id", ""))
        for k in (
            "url", "host", "pid", "started_at", "heartbeat_at",
            "engines", "tenants", "serve_dtype", "draining", "inflight",
        ):
            if d.get(k) is not None:
                setattr(r, k, d[k])
        return r


class ReplicaRegistry:
    """CRUD + liveness over replica records (shared record layer)."""

    def __init__(self, storage: Storage):
        self._store = LifecycleRecordStore(storage)

    def upsert(self, info: ReplicaInfo) -> None:
        self._store.append(REPLICA_ENTITY, info.id, info.to_dict())

    def heartbeat(
        self, replica_id: str, prev_event_id: Optional[str],
        inflight: int = 0, draining: Optional[bool] = None,
    ) -> str:
        """Heartbeat with compaction (one live beat event per replica).
        Carries `id` for the same reason worker beats do: a record a
        peer GC'd during a connectivity gap must not resurrect
        identity-less. `draining` rides the beat when set so the drain
        flag cannot be lost to a concurrent beat's last-write-wins."""
        props: dict[str, Any] = {
            "id": replica_id,
            "heartbeat_at": time.time(),
            "inflight": int(inflight),
        }
        if draining is not None:
            props["draining"] = bool(draining)
        eid = self._store.append(REPLICA_ENTITY, replica_id, props)
        if prev_event_id:
            self._store.discard(prev_event_id)
        return eid

    def set_draining(self, replica_id: str, draining: bool = True) -> None:
        self._store.append(REPLICA_ENTITY, replica_id, {
            "id": replica_id, "draining": bool(draining),
        })

    def remove(self, replica_id: str) -> None:
        self._store.purge(REPLICA_ENTITY, replica_id)

    def get(self, replica_id: str) -> Optional[ReplicaInfo]:
        d = self._store.fold(REPLICA_ENTITY, replica_id).get(replica_id)
        return ReplicaInfo.from_dict(d) if d else None

    def list(self) -> list[ReplicaInfo]:
        return [
            ReplicaInfo.from_dict(d)
            for d in self._store.fold(REPLICA_ENTITY).values()
        ]

    def live(self, stale_after_s: float = 5.0) -> list[ReplicaInfo]:
        cutoff = time.time() - stale_after_s
        return [r for r in self.list() if r.heartbeat_at >= cutoff]

    def gc(self, stale_after_s: float = 60.0) -> list[str]:
        """Purge records of replicas dead for much longer than the
        liveness horizon (a kill -9'd replica can't deregister)."""
        cutoff = time.time() - stale_after_s
        doomed = [r.id for r in self.list() if r.heartbeat_at < cutoff]
        for rid in doomed:
            self.remove(rid)
        return doomed
