"""Closed-loop autoscaling (ISSUE 15 tentpole part 4).

The monitoring plane measures, the gateway routes — this module closes
the loop: an :class:`Autoscaler` policy object consumes the SLO
engine's burn rate and the gateway's per-replica concurrency every
evaluation pass and emits **spawn/drain decisions** against a
:class:`ReplicaManager`. Policy and actuation are deliberately split:
the in-tree :class:`SubprocessReplicaManager` spawns replica
subprocesses for tests and the bench harness, a production deployment
plugs a k8s/ASG-shaped manager into the same three-method seam —
either way every decision lands in the bounded decision log and on
``gateway_scale_events_total{action}``, so "why did the fleet grow at
3am" is answerable from /gateway/status alone.

Scale-up triggers (either):
- SLO burn: the fast-window burn rate of any tracked SLO is at or over
  ``scale_up_burn`` — the fleet is eating error budget page-fast,
- load: mean in-flight per routable replica exceeds
  ``target_inflight`` — saturation is coming even if the SLO holds.

Scale-down requires BOTH quiet burn and mean load under
``scale_down_fraction × target_inflight``, and drains (graceful,
zero-drop) rather than kills. A cooldown between actions stops the
loop hunting; min/max bounds are hard rails.
"""

from __future__ import annotations

import logging
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

log = logging.getLogger(__name__)


@dataclass
class AutoscalerConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    # mean in-flight queries per replica that means "saturating"
    target_inflight: float = 32.0
    # scale down only below this fraction of target (hysteresis band)
    scale_down_fraction: float = 0.25
    # fast-window burn rate that forces a scale-up (SLO page threshold)
    scale_up_burn: float = 14.4
    cooldown_s: float = 30.0
    # the min-floor rule ignores the full cooldown (a crashed fleet must
    # recover fast) but still waits this long after its own last spawn —
    # a replica takes a few seconds to boot and register, and re-firing
    # every evaluation pass until it shows up is a process storm
    floor_boot_grace_s: float = 5.0
    decision_log_size: int = 64


class ReplicaManager:
    """Actuation seam: how replicas come and go. Implementations must
    be idempotent-tolerant — the policy may re-decide during slow
    boots (the cooldown is the main guard, this is the backstop)."""

    def spawn(self) -> Optional[str]:
        """Start one replica; returns an opaque handle/id or None."""
        raise NotImplementedError

    def drain(self, replica_id: str, url: str) -> bool:
        """Begin graceful drain of one replica (zero-drop retirement)."""
        raise NotImplementedError

    def stop(self) -> None:
        """Release manager resources (kill test children etc.)."""


class SubprocessReplicaManager(ReplicaManager):
    """In-tree manager for tests/bench: replicas are local
    ``gateway.replica_main`` subprocesses built from an argv template.
    Every ``{n}`` in a template arg is replaced with a per-spawn
    sequence number, so templated ``--replica-id r{n}`` /
    ``--state-dir .../s{n}`` args give each child its own durable
    identity; a template naming NEITHER flag gets a unique
    ``--state-dir`` appended — N children sharing replica_main's
    default state dir would collapse into ONE registry record and the
    min-floor rule would spawn forever chasing a count that never
    rises. `drain` POSTs the replica's own /replica/drain (the replica
    exits once drained)."""

    def __init__(self, argv_template: list[str], env: Optional[dict] = None):
        self.argv_template = list(argv_template)
        self.env = env
        self._lock = threading.Lock()
        self._children: list[subprocess.Popen] = []  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._auto_state_base: Optional[str] = None  # guarded-by: _lock

    def spawn(self) -> Optional[str]:
        import os
        import tempfile

        with self._lock:
            self._seq += 1
            seq = self._seq
            argv = [a.replace("{n}", str(seq)) for a in self.argv_template]
            if (
                "--replica-id" not in argv and "--state-dir" not in argv
            ):
                if self._auto_state_base is None:
                    self._auto_state_base = tempfile.mkdtemp(
                        prefix="pio-autoscale-"
                    )
                argv += ["--state-dir", os.path.join(
                    self._auto_state_base, f"replica-{seq}"
                )]
        proc = subprocess.Popen(
            [sys.executable, *argv],
            env=self.env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        with self._lock:
            self._children.append(proc)
        return f"pid:{proc.pid}"

    def drain(self, replica_id: str, url: str) -> bool:
        import urllib.request

        try:
            req = urllib.request.Request(
                url.rstrip("/") + "/replica/drain",
                data=b"{}",
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=5):
                pass
            return True
        except Exception:
            log.warning("drain request to %s failed", url, exc_info=True)
            return False

    def stop(self) -> None:
        with self._lock:
            children, self._children = self._children, []
        for proc in children:
            try:
                proc.kill()
                proc.wait(timeout=10)
            except Exception:
                pass


@dataclass
class ScaleDecision:
    action: str  # spawn | drain | hold
    reason: str
    at: float
    replicas: int
    mean_inflight: float
    burn: Optional[float]
    target: Optional[str] = None  # drained replica id, spawn handle

    def to_dict(self) -> dict[str, Any]:
        return {
            "action": self.action, "reason": self.reason,
            "at": self.at, "replicas": self.replicas,
            "mean_inflight": round(self.mean_inflight, 2),
            "burn": None if self.burn is None else round(self.burn, 3),
            "target": self.target,
        }


class Autoscaler:
    """Pure-ish policy: `evaluate()` maps one signal snapshot to at
    most one action through the manager. The gateway's sync loop calls
    it; tests call it directly with synthetic signals."""

    def __init__(
        self,
        manager: Optional[ReplicaManager],
        config: Optional[AutoscalerConfig] = None,
        registry=None,
        clock=time.monotonic,
    ):
        self.manager = manager
        self.config = config or AutoscalerConfig()
        self._clock = clock
        self._last_action_at: Optional[float] = None
        self._last_spawn_at: Optional[float] = None
        self.decisions: deque[ScaleDecision] = deque(
            maxlen=self.config.decision_log_size
        )
        if registry is None:
            from predictionio_tpu.obs.registry import get_default_registry

            registry = get_default_registry()
        self._events = registry.counter(
            "gateway_scale_events_total",
            "autoscaler actions taken, by action",
            ("action",),  # label-bound: literal spawn|drain
        )

    # -- policy ------------------------------------------------------------
    def evaluate(
        self,
        replicas: int,
        mean_inflight: float,
        burn: Optional[float],
        drain_candidate: Optional[tuple[str, str]] = None,
    ) -> Optional[ScaleDecision]:
        """One pass: `replicas` routable now, their mean in-flight
        load, the worst tracked fast-window burn rate (None = no SLO
        signal), and the (id, url) the gateway would drain first (its
        least-loaded replica). Returns the decision taken, or None."""
        cfg = self.config
        now = self._clock()
        in_cooldown = (
            self._last_action_at is not None
            and now - self._last_action_at < cfg.cooldown_s
        )

        def act(action: str, reason: str, target: Optional[str]) -> ScaleDecision:
            d = ScaleDecision(
                action=action, reason=reason, at=time.time(),
                replicas=replicas, mean_inflight=mean_inflight,
                burn=burn, target=target,
            )
            self.decisions.append(d)
            self._events.inc(action=action)
            self._last_action_at = now
            if action == "spawn":
                self._last_spawn_at = now
            log.info("autoscaler %s: %s", action, reason)
            return d

        # hard rail first: below the floor, spawn regardless of the
        # FULL cooldown (a crashed fleet must not wait out 30 s to
        # recover) — but give our own last spawn a boot grace, or a
        # replica that takes seconds to register draws one sibling per
        # evaluation pass
        if replicas < cfg.min_replicas:
            if (
                self._last_spawn_at is not None
                and now - self._last_spawn_at < cfg.floor_boot_grace_s
            ):
                return None
            target = self.manager.spawn() if self.manager else None
            return act(
                "spawn",
                f"{replicas} routable < min_replicas {cfg.min_replicas}",
                target,
            )
        if in_cooldown:
            return None
        burning = burn is not None and burn >= cfg.scale_up_burn
        saturated = mean_inflight >= cfg.target_inflight
        if (burning or saturated) and replicas < cfg.max_replicas:
            reason = (
                f"burn {burn:.1f} >= {cfg.scale_up_burn}" if burning
                else f"mean inflight {mean_inflight:.1f} >= "
                     f"{cfg.target_inflight}"
            )
            target = self.manager.spawn() if self.manager else None
            return act("spawn", reason, target)
        idle = (
            mean_inflight < cfg.scale_down_fraction * cfg.target_inflight
        )
        if (
            idle and not burning and replicas > cfg.min_replicas
            and drain_candidate is not None
        ):
            rid, url = drain_candidate
            ok = (
                self.manager.drain(rid, url) if self.manager else True
            )
            if ok:
                return act(
                    "drain",
                    f"mean inflight {mean_inflight:.1f} < "
                    f"{cfg.scale_down_fraction:.2f}x target",
                    rid,
                )
        return None

    def status(self) -> dict[str, Any]:
        return {
            "config": {
                "min_replicas": self.config.min_replicas,
                "max_replicas": self.config.max_replicas,
                "target_inflight": self.config.target_inflight,
                "scale_up_burn": self.config.scale_up_burn,
                "cooldown_s": self.config.cooldown_s,
            },
            "decisions": [d.to_dict() for d in self.decisions],
        }
