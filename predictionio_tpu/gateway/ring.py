"""Consistent-hash ring with bounded-load overflow (ISSUE 15 part 2).

Tenants/apps hash onto replicas through a classic virtual-node ring:
each replica owns `vnodes` points on a 64-bit circle, a key routes to
the first point clockwise of its hash, and membership changes remap
only the keys adjacent to the joining/leaving replica — which is
exactly what a tenant-model cache wants (a scale-up must not shuffle
every tenant's runtime onto a cold replica).

Plain consistent hashing lets one hot tenant pin one replica at
saturation while its neighbors idle. `ordered()` therefore returns the
full ring ORDER for a key and the router walks it with the
bounded-load rule (Mirrokni et al.'s consistent hashing with bounded
loads): a replica already carrying more than ``factor ×`` the mean
in-flight load is skipped, so overflow spills to the next replica on
the ring — deterministically, preserving as much stickiness as the
load bound allows.

Stdlib only (hashlib); the gateway is a data-plane process and must
never pay the jax import.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Optional


def _h64(key: str) -> int:
    return int.from_bytes(
        hashlib.md5(key.encode()).digest()[:8], "big"
    )


class HashRing:
    """Immutable ring over a replica-id set (rebuild on membership
    change — membership is small and changes are rare)."""

    def __init__(self, replica_ids: list[str], vnodes: int = 64):
        self.vnodes = max(1, int(vnodes))
        self.replica_ids = sorted(set(replica_ids))
        points: list[tuple[int, str]] = []
        for rid in self.replica_ids:
            for v in range(self.vnodes):
                points.append((_h64(f"{rid}#{v}"), rid))
        points.sort()
        self._hashes = [p[0] for p in points]
        self._owners = [p[1] for p in points]

    def __len__(self) -> int:
        return len(self.replica_ids)

    def ordered(self, key: str) -> list[str]:
        """Every replica in ring order starting at `key`'s successor —
        position 0 is the sticky owner, the rest are the deterministic
        overflow/hedge/failover sequence."""
        if not self._hashes:
            return []
        idx = bisect.bisect_right(self._hashes, _h64(key))
        seen: set[str] = set()
        out: list[str] = []
        n = len(self._hashes)
        for i in range(n):
            rid = self._owners[(idx + i) % n]
            if rid not in seen:
                seen.add(rid)
                out.append(rid)
                if len(out) == len(self.replica_ids):
                    break
        return out

    def owner(self, key: str) -> Optional[str]:
        ordered = self.ordered(key)
        return ordered[0] if ordered else None
