"""`pio gateway`: the L7 router in front of N query-server replicas
(ISSUE 15 tentpole; ROADMAP direction 2).

One replica crash must never be a tenant-visible outage. The gateway:

- **discovers** replicas from the shared replica registry (heartbeating
  ``pio_query_replica`` records, the worker-record mechanism),
- **routes** each query by consistent hash — tenant id (model-cache
  locality) or, untenanted, the request's own crc32 bucket — with
  bounded-load overflow to the next replica on the ring, and forwards
  the routing bucket as ``X-PIO-Route-Hash`` so sticky canary routing
  holds end-to-end no matter which replica (or hedge) answers,
- treats **health as a first-class signal**: a per-replica circuit
  breaker (resilience/breaker.py) fed by real proxy outcomes, active
  ``/health`` probes for traffic-free re-admission, and the passive
  ``up{instance}`` + SLO burn-rate series from an embedded
  :class:`FleetScraper` — any of stale heartbeat / open breaker /
  scrape-down / firing per-instance SLO ejects a replica from routing;
  recovery on any probe path re-admits it,
- **hedges**: a query still unanswered at the replica's rolling p95
  mark is speculatively re-sent to the next replica on the ring; the
  first good answer wins and the loser is bounded by the same
  propagated ``X-PIO-Deadline`` (no post-deadline device work). Network
  failures fail over the ring the same way — queries are idempotent,
  which is why ONLY the query routes hedge/retry,
- **drains** zero-drop: flag the registry record, stop routing, let
  the replica answer its in-flight queries, then it stops itself,
- **autoscales** closed-loop: the :class:`Autoscaler` policy consumes
  SLO burn + per-replica concurrency each sync pass and spawns/drains
  through a ReplicaManager, with tenant-prefetch hints POSTed to
  joining replicas so a scale-up doesn't cold-start every tenant.

Import discipline: the gateway is a data-plane process — stdlib +
obs/resilience only, never jax.
"""

from __future__ import annotations

import http.client
import json
import logging
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from dataclasses import dataclass, field
from typing import Any, Optional

import predictionio_tpu.resilience.deadline as _deadline
import predictionio_tpu.obs.tracing as _tracing
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.deploy.rollout import route_bucket
from predictionio_tpu.gateway.autoscale import Autoscaler
from predictionio_tpu.gateway.registry import ReplicaInfo, ReplicaRegistry
from predictionio_tpu.gateway.ring import HashRing
from predictionio_tpu.obs import server_registry
from predictionio_tpu.obs import spans as _spans
from predictionio_tpu.obs.monitor import (
    FleetScraper,
    TraceCollector,
    get_monitor,
)
from predictionio_tpu.resilience.breaker import CLOSED, CircuitBreaker
from predictionio_tpu.utils.env import (
    env_bool,
    env_float,
    env_int,
)
from predictionio_tpu.utils.http import (
    JsonHandler,
    ServerProcess,
    ThreadedServer,
)

log = logging.getLogger(__name__)


@dataclass
class GatewayConfig:
    ip: str = "0.0.0.0"
    port: int = 8100
    # discovery/health cadence
    sync_interval_s: float = field(
        default_factory=lambda: env_float("PIO_GATEWAY_SYNC_S", 0.5)
    )
    # heartbeat age past which a replica stops being routable
    replica_stale_after_s: float = field(
        default_factory=lambda: env_float("PIO_GATEWAY_STALE_S", 3.0)
    )
    # hedging: on by default, floor on the speculative delay so a cold
    # latency window doesn't hedge every single query
    hedge: bool = field(
        default_factory=lambda: env_bool("PIO_GATEWAY_HEDGE")
    )
    hedge_min_ms: float = field(
        default_factory=lambda: max(
            1.0, env_float("PIO_GATEWAY_HEDGE_MIN_MS", 25.0)
        )
    )
    # bounded-load consistent hashing: skip a replica carrying more
    # than factor x the mean in-flight load
    load_factor: float = field(
        default_factory=lambda: env_float("PIO_GATEWAY_LOAD_FACTOR", 1.5)
    )
    vnodes: int = field(
        default_factory=lambda: env_int("PIO_GATEWAY_VNODES", 64)
    )
    # per-attempt socket timeout (the deadline budget caps it further)
    attempt_timeout_s: float = 30.0
    # passive scrape cadence (up{instance} + burn-rate inputs)
    scrape_interval_s: float = field(
        default_factory=lambda: env_float("PIO_SCRAPE_INTERVAL_S", 10.0)
    )
    scrape: bool = True
    # breaker knobs for the per-replica circuits
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 2.0
    # proxy worker pool (hedges double up, so >= 2x expected clients
    # is unnecessary — attempts are short and the pool queues)
    pool_size: int = 32
    # how many recently-routed tenants to remember for prefetch hints
    prefetch_tenants: int = 256


class _ReplicaState:
    """The gateway's live view of one replica."""

    __slots__ = (
        "info", "breaker", "lock", "inflight", "lat",
        "scrape_down", "slo_firing", "alive", "last_probe_at",
    )

    def __init__(self, info: ReplicaInfo, breaker: CircuitBreaker):
        self.info = info
        self.breaker = breaker
        self.lock = threading.Lock()
        self.inflight = 0  # guarded-by: lock
        self.lat: deque[float] = deque(maxlen=64)  # guarded-by: lock
        self.scrape_down = False
        self.slo_firing = False
        self.alive = True  # heartbeat fresh as of the last sync pass
        self.last_probe_at = 0.0

    # -- accounting (called from proxy worker threads) --------------------
    def enter(self) -> None:
        with self.lock:
            self.inflight += 1

    def exit(self, latency_s: Optional[float]) -> None:
        with self.lock:
            self.inflight -= 1
            if latency_s is not None:
                self.lat.append(latency_s)

    def inflight_now(self) -> int:
        with self.lock:
            return self.inflight

    def p95_s(self) -> Optional[float]:
        with self.lock:
            if len(self.lat) < 8:
                return None  # too cold to trust
            vs = sorted(self.lat)
        return vs[min(len(vs) - 1, int(0.95 * len(vs)))]

    def routable(self) -> bool:
        return (
            self.alive
            and not self.info.draining
            and not self.scrape_down
            and not self.slo_firing
            # anything but CLOSED stays out of the ring: the sync
            # loop's active /health probe pays the half-open recovery
            # attempt, never a real query
            and self.breaker.state == CLOSED
        )

    def eject_reasons(self) -> list[str]:
        reasons = []
        if not self.alive:
            reasons.append("stale_heartbeat")
        if self.info.draining:
            reasons.append("draining")
        if self.scrape_down:
            reasons.append("scrape_down")
        if self.slo_firing:
            reasons.append("slo_burn")
        state = self.breaker.state
        if state != CLOSED:
            reasons.append(f"breaker_{state}")
        return reasons


class _AttemptFailed(Exception):
    """One proxy attempt failed at the transport layer (the failover
    trigger); HTTP answers of any status are NOT this."""


class _GatewayHandler(JsonHandler):
    server: "_GatewayHttp"  # type: ignore[assignment]

    def do_GET(self):
        self._drain_body()
        path = self.path.split("?")[0].rstrip("/") or "/"
        gw = self.server.owner
        try:
            if path in ("/", "/gateway/status"):
                self._respond(200, gw.status())
            elif path == "/health":
                self._respond(200, {"status": "alive"})
            elif path == "/metrics":
                self._serve_metrics()
            elif path == "/alerts":
                self._serve_alerts()
            elif path == "/debug/tsdb":
                self._serve_debug_tsdb()
            elif path == "/debug/traces":
                self._serve_debug_traces()
            elif path == "/debug/faults":
                self._serve_debug_faults()
            else:
                self._respond(404, {"message": "Not Found"})
        except Exception as e:
            log.exception("GET %s failed", path)
            self._respond(500, {"message": str(e)})

    def do_POST(self):
        self._drain_body()
        path = self.path.split("?")[0].rstrip("/")
        gw = self.server.owner
        if path == "/queries.json":
            self._proxy_query(path, self.headers.get("X-PIO-Tenant") or None)
        elif path.startswith("/tenants/") and path.endswith("/queries.json"):
            parts = [p for p in path.split("/") if p]
            if len(parts) == 3:
                self._proxy_query(path, parts[1])
            else:
                self._respond(404, {"message": "Not Found"})
        elif path == "/gateway/drain":
            body = self._json_body()
            rid = body.get("replica") if isinstance(body, dict) else None
            if not rid:
                self._respond(400, {"message": "'replica' is required"})
                return
            try:
                result = gw.drain_replica(str(rid))
            except KeyError:
                self._respond(404, {"message": f"no replica {rid!r}"})
            else:
                self._respond(202, result)
        else:
            self._respond(404, {"message": "Not Found"})

    def _proxy_query(self, path: str, tenant_id: Optional[str]) -> None:
        gw = self.server.owner
        status, body, headers = gw.proxy(path, self._raw_body, tenant_id)
        self._respond(status, body, headers=headers)


class _GatewayHttp(ThreadedServer):
    owner: "GatewayServer"


class GatewayServer(ServerProcess):
    """The gateway process: routing state + the HTTP front."""

    _name = "gateway"

    def __init__(
        self,
        storage: Storage,
        config: Optional[GatewayConfig] = None,
        autoscaler: Optional[Autoscaler] = None,
    ):
        super().__init__()
        self.storage = storage
        self.config = config or GatewayConfig()
        self.registry = ReplicaRegistry(storage)
        self.autoscaler = autoscaler
        self.metrics = server_registry()
        self._requests = self.metrics.counter(
            "gateway_requests_total",
            "queries through the gateway, by outcome",
            ("outcome",),  # label-bound: literal outcome set
        )
        self._hedges = self.metrics.counter(
            "gateway_hedges_total",
            "speculative hedge requests, by outcome",
            ("outcome",),  # label-bound: literal sent|won
        )
        self._failovers = self.metrics.counter(
            "gateway_failover_total",
            "attempts re-sent to the next replica after a transport "
            "failure",
        )
        self._ejections = self.metrics.counter(
            "gateway_ejections_total",
            "replica ejections from routing, by reason",
            ("reason",),  # label-bound: literal eject-reason set
        )
        self._routing_hist = self.metrics.histogram(
            "gateway_routing_seconds",
            "gateway-added routing overhead: request read to first "
            "attempt dispatched",
        )
        self._replicas_gauge = self.metrics.gauge(
            "gateway_replicas", "replicas known / routable",
            ("state",),  # label-bound: literal known|routable
        )
        # routing state: the sync thread REPLACES these references
        # atomically; proxy threads snapshot them without a lock
        self._state_lock = threading.Lock()
        self._replicas: dict[str, _ReplicaState] = {}  # guarded-by: _state_lock
        self._ring = HashRing([], vnodes=self.config.vnodes)
        self._recent_tenants: "OrderedDict[str, bool]" = OrderedDict()  # guarded-by: _state_lock
        self._pool = ThreadPoolExecutor(
            max_workers=self.config.pool_size,
            thread_name_prefix="gateway-proxy",
        )
        self._tl = threading.local()  # per-thread conns, keyed by url
        self._stop = threading.Event()
        self._sync_thread: Optional[threading.Thread] = None
        self._scraper: Optional[FleetScraper] = None
        self._collector: Optional[TraceCollector] = None
        # in-flight hint/drain-notify threads, joined on stop
        self._hint_lock = threading.Lock()
        self._hint_threads: set[threading.Thread] = set()  # guarded-by: _hint_lock

    # -- lifecycle ---------------------------------------------------------
    def _make_server(self) -> _GatewayHttp:
        server = _GatewayHttp((self.config.ip, self.config.port),
                              _GatewayHandler)
        server.owner = self
        server.metrics = self.metrics
        server.metrics_label = "gateway"
        return server

    def start(self) -> int:
        port = super().start()
        if self.config.scrape:
            self._scraper = FleetScraper(
                get_monitor().tsdb, [],
                interval_s=self.config.scrape_interval_s,
            )
            self._scraper.start()
            if env_bool("PIO_TRACE_COLLECT"):
                # same cadence as the scrape pass: both lists sync from
                # the replica registry, and the trace hold window only
                # has to cover one poll of skew
                self._collector = TraceCollector(
                    interval_s=self.config.scrape_interval_s,
                )
                get_monitor().set_collector(self._collector)
                self._collector.start()
        self._stop.clear()
        self.sync_once()  # route from the first request, not the first tick
        self._sync_thread = threading.Thread(
            target=self._sync_loop, name="gateway-sync", daemon=True
        )
        self._sync_thread.start()
        return port

    def stop(self) -> None:
        self._stop.set()
        t = self._sync_thread
        if t is not None:
            t.join(timeout=self.config.sync_interval_s + 5)
            self._sync_thread = None
        if self._scraper is not None:
            self._scraper.stop()
            self._scraper = None
        if self._collector is not None:
            self._collector.stop()
            mon = get_monitor()
            if mon.collector is self._collector:
                mon.set_collector(None)
            self._collector = None
        if self.autoscaler is not None and self.autoscaler.manager:
            self.autoscaler.manager.stop()
        self._pool.shutdown(wait=False)
        with self._hint_lock:
            pending = list(self._hint_threads)
        for ht in pending:
            ht.join(timeout=5)
        super().stop()

    # -- discovery / health sync -------------------------------------------
    def _sync_loop(self) -> None:
        while not self._stop.wait(self.config.sync_interval_s):
            try:
                self.sync_once()
            except Exception:
                log.exception("gateway sync pass failed; will retry")

    def sync_once(self) -> None:
        """One discovery+health pass. Public so tests drive it without
        the thread."""
        try:
            records = self.registry.list()
        except Exception:
            # storage blip: keep routing on the last-known state — the
            # breakers still protect against actually-dead replicas
            log.warning(
                "replica registry read failed; serving last-known fleet",
                exc_info=True,
            )
            return
        now = time.time()
        cutoff = now - self.config.replica_stale_after_s
        tsdb = get_monitor().tsdb
        with self._state_lock:
            states = dict(self._replicas)
        prev_routable = {
            rid for rid, st in states.items() if st.routable()
        }
        seen: set[str] = set()
        for info in records:
            if not info.id or not info.url:
                continue
            seen.add(info.id)
            st = states.get(info.id)
            if st is None:
                st = _ReplicaState(info, CircuitBreaker(
                    f"replica:{info.id}",
                    failure_threshold=self.config.breaker_threshold,
                    cooldown_s=self.config.breaker_cooldown_s,
                    registry=self.metrics,
                ))
                states[info.id] = st
            before = st.routable()
            st.info = info
            st.alive = info.heartbeat_at >= cutoff
            up = tsdb.latest("up", {"instance": info.id})
            st.scrape_down = up is not None and up <= 0.0
            st.slo_firing = self._slo_firing(info.id)
            if before and not st.routable():
                for reason in st.eject_reasons():
                    self._ejections.inc(reason=reason)
                log.warning(
                    "replica %s ejected from routing: %s",
                    info.id, ",".join(st.eject_reasons()),
                )
        for rid in list(states):
            if rid not in seen:
                del states[rid]  # record GC'd / deregistered
        # traffic-free re-admission: actively probe non-routable
        # replicas whose record still heartbeats — a breaker opened by
        # a transient outage must not stay open forever just because
        # routing (rightly) sends it no traffic to recover on
        for st in states.values():
            if (
                st.alive and not st.info.draining and not st.routable()
                and now - st.last_probe_at
                >= self.config.breaker_cooldown_s
            ):
                st.last_probe_at = now
                self._probe(st)
        routable = sorted(
            rid for rid, st in states.items() if st.routable()
        )
        ring = HashRing(routable, vnodes=self.config.vnodes)
        with self._state_lock:
            self._replicas = states
            self._ring = ring
        self._replicas_gauge.set(float(len(states)), state="known")
        self._replicas_gauge.set(float(len(routable)), state="routable")
        if self._scraper is not None:
            targets = sorted(
                (st.info.id, st.info.url) for st in states.values()
            )
            if targets != sorted(self._scraper.targets):
                self._scraper.targets = list(targets)
            if self._collector is not None and targets != sorted(
                self._collector.targets
            ):
                self._collector.targets = list(targets)
        # scale-up warm-start: tell JOINING replicas which of the
        # recently-routed tenants now hash onto them
        joined = set(routable) - prev_routable
        if joined:
            self._send_prefetch_hints(joined, ring, states)
        if self.autoscaler is not None:
            self._autoscale(routable, states)

    def _slo_firing(self, replica_id: str) -> bool:
        """A firing SLO whose spec names this replica's instance ejects
        it (burn-rate-aware routing: the monitoring plane's verdict,
        not just liveness)."""
        engine = get_monitor().engine
        if engine is None:
            return False
        try:
            for row in engine.payload()["slos"]:
                if (
                    row["state"] == "firing"
                    and row["spec"].get("instance") == replica_id
                ):
                    return True
        except Exception:
            return False
        return False

    def _probe(self, st: _ReplicaState) -> None:
        """Active /health probe through the replica's breaker — success
        closes it (re-admission), failure re-arms the cooldown."""
        if not st.breaker.allow():
            return
        ok = False
        try:
            conn = http.client.HTTPConnection(
                *self._host_port(st.info.url), timeout=2
            )
            try:
                conn.request("GET", "/health")
                resp = conn.getresponse()
                resp.read()
                ok = resp.status == 200
            finally:
                conn.close()
        except (http.client.HTTPException, OSError):
            # same failure scope as _attempt: a socket that accepts but
            # talks garbage (BadStatusLine) is a failed probe, not an
            # escape that would leave the half-open slot claimed forever
            ok = False
        if ok:
            st.breaker.record_success()
            log.info("replica %s re-admitted (health probe ok)", st.info.id)
        else:
            st.breaker.record_failure()

    @staticmethod
    def _host_port(url: str) -> tuple[str, int]:
        from urllib.parse import urlsplit

        parts = urlsplit(url if "://" in url else f"http://{url}")
        return parts.hostname or "127.0.0.1", parts.port or 80

    def _send_prefetch_hints(
        self, joined: set, ring: HashRing, states: dict
    ) -> None:
        with self._state_lock:
            recent = list(self._recent_tenants)
        if not recent:
            return
        hints: dict[str, list[str]] = {}
        for tenant in recent:
            owner = ring.owner(tenant)
            if owner in joined:
                hints.setdefault(owner, []).append(tenant)
        for rid, tenants in hints.items():
            url = states[rid].info.url

            def send(url=url, tenants=tenants, rid=rid):
                try:
                    conn = http.client.HTTPConnection(
                        *self._host_port(url), timeout=5
                    )
                    try:
                        conn.request(
                            "POST", "/replica/prefetch",
                            body=json.dumps({"tenants": tenants}).encode(),
                            headers={"Content-Type": "application/json"},
                        )
                        conn.getresponse().read()
                    finally:
                        conn.close()
                    log.info(
                        "prefetch hint sent to joining replica %s "
                        "(%d tenants)", rid, len(tenants),
                    )
                except Exception:
                    log.debug(
                        "prefetch hint to %s failed", rid, exc_info=True
                    )
                finally:
                    with self._hint_lock:
                        self._hint_threads.discard(
                            threading.current_thread()
                        )

            t = threading.Thread(
                target=send, name="gateway-hint", daemon=True
            )
            with self._hint_lock:
                self._hint_threads.add(t)
            t.start()

    def _autoscale(self, routable: list[str], states: dict) -> None:
        n = len(routable)
        total = sum(states[rid].inflight_now() for rid in routable)
        mean = total / n if n else 0.0
        burn = None
        engine = get_monitor().engine
        if engine is not None:
            burns = [
                st.fast_burn
                for st in (engine.status(s.name) for s in engine.specs())
                if st is not None and st.fast_burn is not None
            ]
            if burns:
                burn = max(burns)
        drain_candidate = None
        if n > 1:
            rid = min(routable, key=lambda r: states[r].inflight_now())
            drain_candidate = (rid, states[rid].info.url)
        try:
            self.autoscaler.evaluate(
                replicas=n, mean_inflight=mean, burn=burn,
                drain_candidate=drain_candidate,
            )
        except Exception:
            log.exception("autoscaler evaluation failed")

    # -- routing -----------------------------------------------------------
    def _route_snapshot(self) -> tuple[HashRing, dict[str, _ReplicaState]]:
        with self._state_lock:
            return self._ring, self._replicas

    def candidates(self, key: str) -> list[str]:
        """Replica ids to try, in order: ring order from the key's
        owner, bounded-load overloaded replicas demoted to the back
        (still reachable as failover/hedge targets)."""
        ring, states = self._route_snapshot()
        ordered = [
            rid for rid in ring.ordered(key)
            if rid in states and states[rid].routable()
        ]
        if len(ordered) <= 1:
            return ordered
        loads = {rid: states[rid].inflight_now() for rid in ordered}
        cap = max(
            1.0,
            self.config.load_factor
            * (sum(loads.values()) + 1) / len(ordered),
        )
        light = [rid for rid in ordered if loads[rid] <= cap]
        heavy = [rid for rid in ordered if loads[rid] > cap]
        return light + heavy

    def note_tenant(self, tenant_id: str) -> None:
        with self._state_lock:
            self._recent_tenants.pop(tenant_id, None)
            self._recent_tenants[tenant_id] = True
            while len(self._recent_tenants) > self.config.prefetch_tenants:
                self._recent_tenants.popitem(last=False)

    # -- the proxy hot path -------------------------------------------------
    def proxy(
        self, path: str, body: bytes, tenant_id: Optional[str]
    ) -> tuple[int, Any, dict]:
        """Route one query: returns (status, json-able body, headers)."""
        t0 = time.perf_counter()
        if _deadline.expired():
            self._requests.inc(outcome="shed")
            return 503, {"message": "deadline expired; request shed"}, {
                "Retry-After": "1",
            }
        bucket = route_bucket(body)
        key = tenant_id if tenant_id is not None else f"q{bucket}"
        if tenant_id is not None:
            self.note_tenant(tenant_id)
        candidates = self.candidates(key)
        if not candidates:
            self._requests.inc(outcome="no_replica")
            return 503, {"message": "no routable replica"}, {
                "Retry-After": "1",
            }
        headers = {"Content-Type": "application/json",
                   "X-PIO-Route-Hash": str(bucket)}
        if tenant_id is not None and not path.startswith("/tenants/"):
            headers["X-PIO-Tenant"] = tenant_id
        tid = _tracing.current_trace_id()
        if tid:
            headers["X-Request-ID"] = tid
        self._routing_hist.observe(time.perf_counter() - t0)
        # the root of the cross-process trace this side of the handler:
        # one gateway.request per proxied query, one gateway.attempt
        # child per primary/hedge/failover try (recorded off-thread by
        # _attempt — pool threads don't inherit this context)
        with _spans.get_default_recorder().span(
            "gateway.request", server="gateway", path=path,
        ) as gsp:
            status, payload, fwd = self._dispatch(
                path, body, headers, candidates, gsp
            )
            gsp.attrs["status"] = status
            if status >= 500:
                gsp.error = True
        return status, payload, fwd

    def _dispatch(
        self, path: str, body: bytes, headers: dict,
        candidates: list[str], gsp: Optional[_spans.Span] = None,
    ) -> tuple[int, Any, dict]:
        """Primary + hedge + failover race over `candidates`. At most
        two attempts are ever in flight (the primary and one hedge);
        transport failures walk further down the ring. Every attempt
        carries the REMAINING deadline budget, so an abandoned loser
        can't do post-deadline work downstream."""
        _ring, states = self._route_snapshot()
        inflight: dict = {}  # future -> (rid, is_hedge)
        next_i = 0
        hedged = False
        last_answer: Optional[tuple[int, bytes, dict]] = None

        def launch(is_hedge: bool) -> None:
            nonlocal next_i
            rid = candidates[next_i]
            kind = (
                "primary" if next_i == 0
                else "hedge" if is_hedge else "failover"
            )
            ring_pos = next_i
            next_i += 1
            fut = self._pool.submit(
                self._attempt, states.get(rid), path, body, dict(headers),
                gsp, kind, ring_pos,
            )
            inflight[fut] = (rid, is_hedge)

        launch(False)
        hedge_delay = self._hedge_delay_s(candidates[0], states)
        hedge_at = time.monotonic() + hedge_delay
        while True:
            rem = _deadline.remaining()
            if rem is not None and rem <= 0:
                # the client stopped waiting; in-flight attempts are
                # bounded by the budget they carry
                self._requests.inc(outcome="shed")
                return 503, {
                    "message": "deadline expired during dispatch",
                }, {"Retry-After": "1"}
            timeout = 0.25
            if not hedged and self.config.hedge and next_i < len(candidates):
                timeout = min(timeout, max(0.0, hedge_at - time.monotonic()))
            if rem is not None:
                timeout = min(timeout, rem)
            done, _pending = wait(
                list(inflight), timeout=timeout,
                return_when=FIRST_COMPLETED,
            )
            for fut in done:
                rid, is_hedge = inflight.pop(fut)
                try:
                    status, rbody, rheaders = fut.result()
                except _AttemptFailed:
                    # transport failure: fail over to the next replica
                    # on the ring (the breaker already recorded it)
                    if next_i < len(candidates):
                        self._failovers.inc()
                        launch(False)
                    elif not inflight:
                        self._requests.inc(outcome="error")
                        return 502, {
                            "message": "all replicas failed",
                        }, {"Retry-After": "1"}
                    continue
                if status < 500:
                    if is_hedge:
                        self._hedges.inc(outcome="won")
                    self._requests.inc(
                        outcome="hedged" if hedged else "ok"
                    )
                    return self._relay(status, rbody, rheaders)
                # a 5xx answer: keep it as the fallback, prefer any
                # other attempt still running or launchable
                last_answer = (status, rbody, rheaders)
                if not inflight and next_i < len(candidates):
                    self._failovers.inc()
                    launch(False)
                elif not inflight:
                    self._requests.inc(outcome="error")
                    return self._relay(*last_answer)
            if (
                not hedged
                and self.config.hedge
                and next_i < len(candidates)
                and inflight
                and time.monotonic() >= hedge_at
            ):
                hedged = True
                self._hedges.inc(outcome="sent")
                launch(True)
            if not inflight:
                if last_answer is not None:
                    self._requests.inc(outcome="error")
                    return self._relay(*last_answer)
                self._requests.inc(outcome="error")
                return 502, {"message": "no replica answered"}, {
                    "Retry-After": "1",
                }

    def _hedge_delay_s(
        self, rid: str, states: dict[str, _ReplicaState]
    ) -> float:
        st = states.get(rid)
        p95 = st.p95_s() if st is not None else None
        floor = self.config.hedge_min_ms / 1000.0
        return max(floor, p95) if p95 is not None else floor

    @staticmethod
    def _relay(status: int, rbody: bytes, rheaders: dict) -> tuple:
        try:
            payload = json.loads(rbody.decode() or "null")
        except ValueError:
            payload = {"message": rbody.decode(errors="replace")}
        fwd = {}
        if rheaders.get("Retry-After"):
            fwd["Retry-After"] = rheaders["Retry-After"]
        return status, payload, fwd

    def _attempt(
        self, st: Optional[_ReplicaState], path: str, body: bytes,
        headers: dict, gsp: Optional[_spans.Span] = None,
        kind: str = "primary", ring_pos: int = 0,
    ) -> tuple[int, bytes, dict]:
        """One proxied attempt against one replica — fully
        self-accounting (breaker verdict, in-flight count, latency
        window), so the dispatch race can abandon it safely."""
        # attempt span built by hand: this runs on a pool thread, where
        # the handler's ContextVars don't exist — trace identity comes
        # explicitly from the gateway.request span, and the headers
        # carry it onward so the replica's server span parents here
        sp: Optional[_spans.Span] = None
        p0 = time.perf_counter()
        if gsp is not None:
            sp = _spans.Span(
                trace_id=gsp.trace_id,
                span_id=_spans.new_span_id(),
                name="gateway.attempt",
                parent_span_id=gsp.span_id,
                start=time.time(),
                attrs={
                    "server": "gateway",
                    "kind": kind,
                    "ring_pos": ring_pos,
                    "replica": st.info.id if st is not None else None,
                },
            )
            headers["X-Request-ID"] = gsp.trace_id
            headers["X-Parent-Span"] = sp.span_id

        def finish(outcome: str, error: bool) -> None:
            if sp is None:
                return
            sp.duration = time.perf_counter() - p0
            sp.attrs["outcome"] = outcome
            sp.error = error
            _spans.get_default_recorder().record(sp, finalize=False)

        if st is None:
            finish("vanished", True)
            raise _AttemptFailed("replica vanished from routing state")
        breaker = st.breaker
        if not breaker.allow():
            finish("breaker_open", True)
            raise _AttemptFailed(f"breaker open for {st.info.id}")
        # re-stamp the REMAINING budget at send time (not dispatch
        # time): a hedge fired 200 ms in hands the replica 200 ms less
        rem = _deadline.remaining()
        if rem is not None:
            if rem <= 0:
                breaker.release_probe()
                finish("deadline", True)
                raise _AttemptFailed("deadline expired before attempt")
            headers[_deadline.HEADER] = str(max(0, int(rem * 1000)))
        st.enter()
        t0 = time.perf_counter()
        verdict = False
        latency: Optional[float] = None
        try:
            try:
                # connect() lives inside the failure scope too: a
                # refused connection to a crashed replica is exactly
                # the failover trigger
                conn = self._replica_conn(st.info.id, st.info.url)
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                rheaders = {
                    k: v for k, v in resp.getheaders()
                    if k.lower() == "retry-after"
                }
            except (http.client.HTTPException, OSError) as e:
                self._drop_conn(st.info.id)
                breaker.record_failure()
                verdict = True
                finish("transport_error", True)
                raise _AttemptFailed(str(e)) from e
            breaker.record_success()
            verdict = True
            latency = time.perf_counter() - t0
            finish(str(resp.status), resp.status >= 500)
            return resp.status, data, rheaders
        finally:
            if not verdict:
                breaker.release_probe()
            st.exit(latency)

    # per-thread keep-alive connections, one per replica (the
    # RemoteClient pattern — proxy threads are pooled, so the map stays
    # bounded at pool_size x replicas). Keyed by (rid, url): a replica
    # that re-registers at a new URL after a crash-restart must not be
    # reached through a cached conn to its old address — every pooled
    # thread would fail over, re-tripping the breaker the health probe
    # just closed.
    def _replica_conn(self, rid: str, url: str) -> http.client.HTTPConnection:
        conns = getattr(self._tl, "conns", None)
        if conns is None:
            conns = self._tl.conns = {}
        cached = conns.get(rid)
        if cached is not None and cached[0] == url:
            return cached[1]
        if cached is not None:
            try:
                cached[1].close()
            except Exception:
                pass
        import socket as _socket

        host, port = self._host_port(url)
        conn = http.client.HTTPConnection(
            host, port, timeout=self.config.attempt_timeout_s
        )
        conn.connect()
        conn.sock.setsockopt(
            _socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1
        )
        conns[rid] = (url, conn)
        return conn

    def _drop_conn(self, rid: str) -> None:
        conns = getattr(self._tl, "conns", None)
        if conns is not None:
            cached = conns.pop(rid, None)
            if cached is not None:
                try:
                    cached[1].close()
                except Exception:
                    pass

    # -- drain / status ----------------------------------------------------
    def drain_replica(self, replica_id: str) -> dict:
        """Operator-initiated graceful drain: flag the record so every
        gateway stops routing, then tell the replica to finish its
        in-flight queries and stop."""
        _ring, states = self._route_snapshot()
        st = states.get(replica_id)
        if st is None:
            raise KeyError(replica_id)
        st.info.draining = True  # local effect now, record next sync
        try:
            self.registry.set_draining(replica_id, True)
        except Exception:
            log.warning(
                "drain flag write failed; relying on the replica's own "
                "record update", exc_info=True,
            )
        url = st.info.url

        def tell():
            try:
                conn = http.client.HTTPConnection(
                    *self._host_port(url), timeout=5
                )
                try:
                    conn.request(
                        "POST", "/replica/drain", body=b"{}",
                        headers={"Content-Type": "application/json"},
                    )
                    conn.getresponse().read()
                finally:
                    conn.close()
            except Exception:
                log.warning(
                    "drain notify to %s failed (replica may already be "
                    "down)", replica_id, exc_info=True,
                )
            finally:
                with self._hint_lock:
                    self._hint_threads.discard(threading.current_thread())

        t = threading.Thread(target=tell, name="gateway-hint", daemon=True)
        with self._hint_lock:
            self._hint_threads.add(t)
        t.start()
        self.sync_once()
        return {"replica": replica_id, "draining": True}

    def status(self) -> dict[str, Any]:
        ring, states = self._route_snapshot()
        replicas = []
        for rid in sorted(states):
            st = states[rid]
            p95 = st.p95_s()
            replicas.append({
                "id": rid,
                "url": st.info.url,
                "routable": st.routable(),
                "eject_reasons": st.eject_reasons(),
                "breaker": st.breaker.state,
                "inflight": st.inflight_now(),
                "p95_ms": None if p95 is None else round(p95 * 1e3, 2),
                "draining": st.info.draining,
                "serve_dtype": st.info.serve_dtype,
                "engines": list(st.info.engines),
                "heartbeat_age_s": round(
                    max(0.0, time.time() - st.info.heartbeat_at), 1
                ),
            })
        out: dict[str, Any] = {
            "replicas": replicas,
            "routable": sum(1 for r in replicas if r["routable"]),
            "ring_size": len(ring),
            "hedge": {
                "enabled": self.config.hedge,
                "min_ms": self.config.hedge_min_ms,
            },
            "load_factor": self.config.load_factor,
        }
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.status()
        return out
