"""Evaluation workflow: grid batch-eval → evaluator → EvaluationInstance.

Reference: CoreWorkflow.runEvaluation (CoreWorkflow.scala:101-163) +
EvaluationWorkflow.runEvaluation (EvaluationWorkflow.scala:29-41) +
CreateWorkflow eval branch (CreateWorkflow.scala:253-272)."""

from __future__ import annotations

import datetime as _dt
import logging
import uuid
from typing import Any, Optional, Sequence

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.controller.evaluation import Evaluation
from predictionio_tpu.core.base import RuntimeContext, WorkflowParams
from predictionio_tpu.data.storage.base import EvaluationInstance
from predictionio_tpu.data.storage.registry import Storage

log = logging.getLogger(__name__)


def run_evaluation(
    storage: Storage,
    evaluation: Evaluation,
    engine_params_list: Optional[Sequence[EngineParams]] = None,
    workflow_params: Optional[WorkflowParams] = None,
    mesh: Any = None,
) -> tuple[EvaluationInstance, Any]:
    """Evaluate every grid point and store the evaluator's rendered results.

    Returns (EVALCOMPLETED instance row, evaluator result)."""
    wp = workflow_params or WorkflowParams()
    engine = evaluation.get_engine()
    evaluator = evaluation.get_evaluator()
    if engine_params_list is None:
        engine_params_list = getattr(evaluation, "engine_params_list", None)
    if not engine_params_list:
        raise ValueError(
            "no engine params to evaluate — pass engine_params_list or use "
            "an EngineParamsGenerator"
        )

    instances = storage.get_meta_data_evaluation_instances()
    now = _dt.datetime.now(_dt.timezone.utc)
    instance = EvaluationInstance(
        id=str(uuid.uuid4()),
        status="INIT",
        start_time=now,
        end_time=now,
        evaluation_class=type(evaluation).__module__
        + "."
        + type(evaluation).__qualname__,
        batch=wp.batch,
    )
    instance_id = instances.insert(instance)
    instance.id = instance_id

    ctx = RuntimeContext(storage=storage, mesh=mesh, mode="eval", workflow_params=wp)
    try:
        import time as _time

        instance.status = "EVALRUNNING"
        instances.update(instance)
        eps = list(engine_params_list)  # materialize once (generators)
        t0 = _time.perf_counter()
        engine_eval_data = engine.batch_eval(ctx, eps)
        eval_wall = _time.perf_counter() - t0
        instance.env = dict(instance.env or {})
        instance.env["eval_wall_sec"] = f"{eval_wall:.3f}"
        instance.env["grid_points"] = str(len(eps))
        result = evaluator.evaluate(ctx, evaluation, engine_eval_data, wp)
        if not getattr(result, "no_save", False):
            instance.evaluator_results = result.to_one_liner()
            instance.evaluator_results_html = result.to_html()
            instance.evaluator_results_json = result.to_json()
        instance.status = "EVALCOMPLETED"
        instance.end_time = _dt.datetime.now(_dt.timezone.utc)
        instances.update(instance)
        log.info("evaluation completed: %s — %s", instance_id, result.to_one_liner())
        return instance, result
    except Exception:
        instance.status = "EVALABORTED"
        instance.end_time = _dt.datetime.now(_dt.timezone.utc)
        instances.update(instance)
        raise
