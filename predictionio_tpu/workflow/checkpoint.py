"""Mid-training checkpointing into MODELDATA.

Goes beyond the reference, which only persists COMPLETED models
(core/.../core/BaseAlgorithm.scala:96-112 / Engine.prepareDeploy): here a
long ALS run snapshots factor state every N iterations so an interrupted
train resumes where it stopped. ALS iterations are memoryless in the
factor state (each half-step is a pure function of the current factors
and the fixed edge data), so resuming k segments of m iterations
reproduces an uninterrupted k·m run.

Checkpoints live in the MODELDATA repository under `ckpt:<instance_id>`
— the same store every process shares (memory/sqlite/localfs/remote), so
a retry on another host finds them.
"""

from __future__ import annotations

import io
import json
import logging
from dataclasses import replace
from typing import Any, Callable, Optional

import numpy as np

from predictionio_tpu.data.storage.base import Model

log = logging.getLogger(__name__)


class CheckpointManager:
    """One checkpoint slot per engine-instance id (latest wins)."""

    def __init__(self, storage: Any, instance_id: str):
        if not instance_id:
            raise ValueError("checkpointing requires a non-empty instance id")
        self._models = storage.get_model_data_models()
        self._key = f"ckpt:{instance_id}"

    def save(self, iteration: int, payload: bytes) -> None:
        buf = io.BytesIO()
        header = json.dumps({"iteration": iteration}).encode()
        buf.write(len(header).to_bytes(4, "big"))
        buf.write(header)
        buf.write(payload)
        self._models.insert(Model(id=self._key, models=buf.getvalue()))
        log.info("checkpoint saved at iteration %d (%s)", iteration, self._key)

    def load(self) -> Optional[tuple[int, bytes]]:
        rec = self._models.get(self._key)
        if rec is None:
            return None
        data = rec.models
        hlen = int.from_bytes(data[:4], "big")
        header = json.loads(data[4 : 4 + hlen])
        return header["iteration"], data[4 + hlen :]

    def clear(self) -> None:
        self._models.delete(self._key)


def train_als_checkpointed(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_users: int,
    n_items: int,
    params: Any,  # models.als.ALSParams
    manager: Optional[CheckpointManager],
    checkpoint_every: int,
    on_segment: Optional[Callable[[int], None]] = None,
    **train_kwargs: Any,
):
    """ALS train in `checkpoint_every`-iteration segments with warm
    starts; resumes from the manager's latest snapshot when one exists.
    Returns the final ALSFactors. The checkpoint is cleared on success."""
    from predictionio_tpu.models import als

    # warm start (ISSUE 9): a caller-provided init (e.g. the parent
    # version's factors mapped onto the new vocab) seeds the first
    # segment; a resumed checkpoint still wins — it is strictly newer
    init = train_kwargs.pop("init_factors", None)

    if manager is None or checkpoint_every <= 0:
        return als.train(
            rows, cols, vals, n_users, n_items, params,
            init_factors=init, **train_kwargs
        )

    done = 0
    factors = None
    resumed = manager.load()
    if resumed is not None:
        done, payload = resumed
        factors = als.ALSFactors.from_bytes(payload)
        init = (factors.user_factors, factors.item_factors)
        log.info("resuming ALS from checkpoint at iteration %d", done)
    while done < params.iterations:
        step = min(checkpoint_every, params.iterations - done)
        seg_params = replace(params, iterations=step)
        factors = als.train(
            rows, cols, vals, n_users, n_items, seg_params,
            init_factors=init, **train_kwargs,
        )
        done += step
        if done < params.iterations:
            manager.save(done, factors.to_bytes())
        init = (factors.user_factors, factors.item_factors)
        if on_segment is not None:
            on_segment(done)
    manager.clear()
    return factors
