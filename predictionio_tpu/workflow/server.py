"""Deploy server: REST query serving from TPU-resident model state.

Reference: core/.../workflow/CreateServer.scala:80-713 — MasterActor
(bind/stop/reload orchestration :277), ServerActor spray route :402:
POST /queries.json (:490) does extract → supplement → per-algo predictBase
→ serve → JSON (:499-525), feedback loop (:534-596), plugin chain
(:598-601), request bookkeeping (:603-610), HTML status page (:461-489),
/reload hot-swap (:337-358), /stop.

Re-design: the actor system becomes a threaded HTTP server sharing an
atomically-swapped `EngineRuntime` reference — queries in flight keep the
old runtime during /reload (the MasterActor hot-swap semantic), and model
arrays stay device-resident across queries."""

from __future__ import annotations

import dataclasses
import datetime as _dt
import html as _html
import json
import logging
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Optional

import predictionio_tpu.obs.spans as _spans
import predictionio_tpu.obs.tracing as _tracing
import predictionio_tpu.resilience.deadline as _deadline
import predictionio_tpu.resilience.faults as _faults
from predictionio_tpu.controller.params import ParamsError, extract_params
from predictionio_tpu.resilience.deadline import DeadlineExceeded
from predictionio_tpu.obs import BATCH_SIZE_BUCKETS, server_registry
from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.data.storage.base import EngineInstance, StorageError
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.utils.http import (
    HttpError as _HttpError,
    JsonHandler,
    ServerProcess,
    ThreadedServer,
)
from predictionio_tpu.workflow.core import prepare_deploy_models

log = logging.getLogger(__name__)
from predictionio_tpu.analysis import tsan as _tsan

OUTPUT_BLOCKER = "outputblocker"
OUTPUT_SNIFFER = "outputsniffer"


@dataclass
class QueryServerConfig:
    ip: str = "0.0.0.0"
    port: int = 8000
    # feedback loop (reference CreateServer.scala:534-596)
    feedback: bool = False
    event_server_url: Optional[str] = None  # e.g. http://127.0.0.1:7070
    access_key: Optional[str] = None
    plugins: list = field(default_factory=list)
    # micro-batching: coalesce concurrent queries into one device program
    # (the "one model, many queries → batched inference queue" hard part,
    # SURVEY.md §7 — no reference analogue; JVM serving was per-request).
    # ON by default — the measured fast path IS the default path. The
    # window adapts between batch_window_ms and max_window_ms: it grows
    # when drains saturate max_batch (queue pressure) and decays back
    # when traffic is light, so a single idle query still sees ~2 ms
    # added latency while a 32-client burst batches deeply.
    micro_batch: bool = True
    batch_window_ms: float = 2.0
    max_window_ms: float = 60.0
    max_batch: int = 64
    # in-flight device batches (VERDICT r3 #3): the dispatcher loop hands
    # each drained batch to a worker pool and immediately collects the
    # next one, so batch N+1's device dispatch overlaps batch N's result
    # fetch and serve/JSON — XLA queues programs on the device stream.
    # 1 restores the old strictly-serial behavior.
    pipeline_depth: int = 4
    # continuous batching (ISSUE 11): while device buckets are in
    # flight, newly-arrived queries keep joining the ASSEMBLING bucket,
    # which dispatches the moment an in-flight bucket retires (a
    # pipeline slot actually frees) instead of when a fixed window
    # expires — the old windowed drain could close a bucket at the
    # window bound and then sit blocked on the semaphore while new
    # arrivals queued behind it unbatched. "windowed" restores the
    # PR-2 adaptive-window behavior (bench.py A/Bs the two under load).
    batching: str = "continuous"
    # adaptive continuous-batching admission (ISSUE 14 satellite,
    # carried serving-kernel follow-up): while a bucket ASSEMBLES in
    # continuous mode with more than one tenant stream active, each
    # tenant may claim at most `admission_cap` slots of it (0 = auto:
    # max_batch // active streams, floor 1) — a hog's backlog cannot
    # fill the whole assembling bucket ahead of other tenants'
    # still-arriving queries; its overflow simply waits for the next
    # bucket. Untenanted traffic counts as one stream.
    admission_cap: int = 0
    # tenant-aware drain (ISSUE 11 satellite, carried tenancy
    # follow-up): with tenants active, stop lingering for full depth as
    # soon as every still-backlogged tenant is represented in the
    # assembling bucket — fairness needs one group per tenant per
    # round, not a full bucket. Windowed mode only (continuous mode's
    # retirement signal supersedes it); kept a separate knob so it is
    # testable in isolation.
    tenant_drain: bool = True
    # remote log shipping (reference CreateServer.scala:441-452 --log-url):
    # server log records POST to this collector as JSON lines, best-effort
    log_url: Optional[str] = None


@dataclass
class EngineRuntime:
    """Everything needed to answer queries; swapped atomically on /reload."""

    instance: EngineInstance
    engine: Any
    engine_params: Any
    algorithms: list[Any]
    models: list[Any]
    serving: Any
    query_class: Optional[type]
    query_serializer: Optional[Any] = None
    started_at: _dt.datetime = field(
        default_factory=lambda: _dt.datetime.now(_dt.timezone.utc)
    )


def build_runtime(storage: Storage, instance: EngineInstance) -> EngineRuntime:
    """Re-hydrate a COMPLETED instance into a servable runtime (reference
    createServerActorWithEngine, CreateServer.scala:206)."""
    from predictionio_tpu.obs.jaxmon import ensure_compile_listener

    # fault point (ISSUE 4): a failed model load/rehydration must leave
    # the PREVIOUS runtime serving (reload() swaps only on success) —
    # chaos tests inject here to prove the query server keeps answering
    # from the last-loaded model when storage/model data is unreachable
    _faults.fire("model.load")

    # hook BEFORE rehydration/warmup: those jit-compile, and the compile
    # gauges must count them even though no server exists yet
    ensure_compile_listener()
    engine, engine_params, models = prepare_deploy_models(storage, instance)
    algorithms = engine.make_algorithms(engine_params)
    serving = engine.make_serving(engine_params)
    serving_ctx = RuntimeContext(storage=storage, mode="serve")
    for algo, model in zip(algorithms, models):
        algo.set_serving_context(serving_ctx)
        warmup = getattr(algo, "warmup", None)
        if callable(warmup):
            try:
                warmup(model)
            except Exception:
                log.exception("algorithm warmup failed; serving continues")
    query_class = algorithms[0].query_class() if algorithms else None
    query_serializer = (
        algorithms[0].query_serializer() if algorithms else None
    )
    return EngineRuntime(
        instance=instance,
        engine=engine,
        engine_params=engine_params,
        algorithms=algorithms,
        models=models,
        serving=serving,
        query_class=query_class,
        query_serializer=query_serializer,
    )


def latest_completed_runtime(
    storage: Storage, engine_id: str, engine_version: str, variant_id: str
) -> EngineRuntime:
    instance = storage.get_meta_data_engine_instances().get_latest_completed(
        engine_id, engine_version, variant_id
    )
    if instance is None:
        raise RuntimeError(
            f"no COMPLETED engine instance for {engine_id}/{engine_version}/"
            f"{variant_id} — run train first"
        )
    return build_runtime(storage, instance)


def _to_jsonable(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: _to_jsonable(v) for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, dict):
        return {k: _to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(v) for v in obj]
    if hasattr(obj, "item") and not isinstance(obj, (str, bytes)):
        try:
            return obj.item()  # numpy scalar → python
        except Exception:
            pass
    return obj


class _Handler(JsonHandler):
    server: "_Server"  # type: ignore[assignment]

    def do_GET(self):
        self._drain_body()
        path = self.path.split("?")[0].rstrip("/") or "/"
        try:
            if path == "/":
                self._respond(200, self.server.owner.status_html(), "text/html")
            elif path == "/rollout/status":
                self._respond(200, self.server.owner.rollout_status())
            elif path == "/online/status":
                self._respond(200, self.server.owner.online_status())
            elif path == "/fleet/status":
                self._respond(200, self.server.owner.fleet_serving_status())
            elif path == "/health":
                # cheap liveness for the gateway's active probes (the
                # status page renders HTML and walks the runtime; a
                # probe must cost neither)
                self._respond(200, {"status": "alive"})
            elif path == "/replica/status":
                self._respond(200, self.server.owner.replica_status())
            elif path == "/tenants" or path.startswith("/tenants/"):
                self._tenants_get(path)
            elif path == "/metrics":
                self._serve_metrics()
            elif path == "/alerts":
                self._serve_alerts()
            elif path == "/debug/traces":
                self._serve_debug_traces()
            elif path == "/debug/tsdb":
                self._serve_debug_tsdb()
            elif path == "/debug/profile":
                self._serve_debug_profile()
            elif path == "/debug/faults":
                self._serve_debug_faults()
            elif path == "/reload":
                try:
                    self.server.owner.reload()
                except RolloutConflict as e:
                    self._respond(409, {"message": str(e)})
                else:
                    self._respond(200, {"message": "Reload successful"})
            elif path == "/stop":
                self._respond(200, {"message": "Shutting down"})
                # lint: disable=thread-lifecycle — self-stop: the server
                # cannot join the thread that tears it down (stop() joins
                # THIS handler's pool); the thread exits with the process
                threading.Thread(
                    target=self.server.owner.stop,
                    name="server-self-stop", daemon=True,
                ).start()
            else:
                self._respond(404, {"message": "Not Found"})
        except Exception as e:
            log.exception("GET %s failed", path)
            self._respond(500, {"message": str(e)})

    def do_POST(self):
        self._drain_body()
        path = self.path.split("?")[0].rstrip("/")
        if path == "/queries.json":
            # tenant-tagged queries also ride the plain route via the
            # X-PIO-Tenant header (the path form is canonical); an
            # EMPTY header value means untenanted, not tenant ""
            self._queries(
                tenant_id=self.headers.get("X-PIO-Tenant") or None
            )
        elif path.startswith("/tenants/"):
            self._tenants_post(path)
        elif path == "/reload":
            try:
                self.server.owner.reload()
                self._respond(200, {"message": "Reload successful"})
            except RolloutConflict as e:
                self._respond(409, {"message": str(e)})
            except Exception as e:
                log.exception("reload failed")
                self._respond(500, {"message": str(e)})
        elif path == "/replica/drain":
            # graceful drain (ISSUE 15): the gateway (or an operator)
            # retires this replica — flag the registry record so
            # routing stops, finish in-flight queries, then stop
            owner = self.server.owner
            if owner.replica is None:
                self._respond(
                    404, {"message": "not a replica (no member attached)"}
                )
            elif owner.replica.drain():
                self._respond(202, owner.replica_status())
            else:
                self._respond(409, {"message": "already draining"})
        elif path == "/replica/prefetch":
            # scale-up warm-start (ISSUE 15): the gateway tells a
            # JOINING replica which tenants will hash onto it, so the
            # first real query is a cache hit instead of a model load
            owner = self.server.owner
            body = self._json_body()
            tenants = (
                body.get("tenants") if isinstance(body, dict) else None
            ) or []
            if not isinstance(tenants, list):
                self._respond(400, {"message": "'tenants' must be a list"})
            else:
                accepted = owner.prefetch_tenants(
                    [str(t) for t in tenants]
                )
                self._respond(200, {"accepted": accepted})
        elif path in ("/online/pause", "/online/resume"):
            owner = self.server.owner
            if owner.online is None:
                self._respond(
                    404, {"message": "no online consumer attached"}
                )
            elif path == "/online/pause":
                body = self._json_body()
                reason = (
                    body.get("reason") if isinstance(body, dict) else None
                ) or "operator pause"
                owner.online.pause(reason)
                self._respond(200, owner.online_status())
            else:
                owner.online.resume()
                self._respond(200, owner.online_status())
        elif path in ("/rollout/start", "/rollout/abort"):
            try:
                body = self._json_body()
                if not isinstance(body, dict):
                    body = {}
                if path == "/rollout/start":
                    self._respond(
                        200, self.server.owner.start_rollout(body)
                    )
                else:
                    self._respond(
                        200, self.server.owner.abort_rollout(
                            body.get("reason") or "operator abort"
                        )
                    )
            except _HttpError as e:
                self._respond(e.status, {"message": e.message})
            except ValueError as e:
                self._respond(400, {"message": str(e)})
            except RolloutConflict as e:
                self._respond(409, {"message": str(e)})
            except Exception as e:
                log.exception("rollout request failed")
                self._respond(500, {"message": str(e)})
        elif path == "/debug/traces/capture":
            # arm "trace the next N batches" (ISSUE 8 satellite): only
            # meaningful where a dispatcher exists to consume the arm
            try:
                if self.server.owner.dispatcher is None:
                    self._respond(409, {
                        "message": "micro-batching is disabled: no "
                                   "dispatcher to capture batches from"
                    })
                else:
                    self._serve_traces_capture()
            except _HttpError as e:
                self._respond(e.status, {"message": e.message})
        elif path == "/debug/profile/capture":
            try:
                self._serve_profile_capture()
            except _HttpError as e:
                self._respond(e.status, {"message": e.message})
            except Exception as e:
                log.exception("profiler capture failed")
                self._respond(500, {"message": str(e)})
        elif path == "/debug/faults":
            try:
                self._serve_debug_faults_set()
            except _HttpError as e:
                self._respond(e.status, {"message": e.message})
        else:
            self._respond(404, {"message": "Not Found"})

    # -- multi-tenant control surface (ISSUE 6) ----------------------------
    def _tenants_get(self, path: str) -> None:
        from predictionio_tpu.tenancy import UnknownTenant

        mux = self.server.owner.tenancy
        if mux is None:
            self._respond(
                404, {"message": "multi-tenant serving is not enabled"}
            )
            return
        parts = [p for p in path.split("/") if p]
        try:
            if len(parts) == 1:
                self._respond(200, mux.status())
            elif len(parts) == 2:
                self._respond(200, mux.tenant_status(parts[1]))
            elif len(parts) in (3, 4) and parts[2] == "rollout" and (
                len(parts) == 3 or parts[3] == "status"
            ):
                self._respond(200, mux.rollout_status(parts[1]))
            else:
                self._respond(404, {"message": "Not Found"})
        except UnknownTenant:
            self._respond(404, {"message": f"no tenant {parts[1]!r}"})

    def _tenants_post(self, path: str) -> None:
        from predictionio_tpu.tenancy import UnknownTenant

        owner = self.server.owner
        mux = owner.tenancy
        parts = [p for p in path.split("/") if p]
        if mux is None:
            self._respond(
                404, {"message": "multi-tenant serving is not enabled"}
            )
            return
        if len(parts) == 3 and parts[2] == "queries.json":
            self._queries(tenant_id=parts[1])
            return
        if len(parts) == 4 and parts[2] == "rollout" and parts[3] in (
            "start", "abort"
        ):
            try:
                body = self._json_body()
                if not isinstance(body, dict):
                    body = {}
                if parts[3] == "start":
                    self._respond(200, mux.start_rollout(parts[1], body))
                else:
                    self._respond(200, mux.abort_rollout(
                        parts[1], body.get("reason") or "operator abort"
                    ))
            except _HttpError as e:
                self._respond(e.status, {"message": e.message})
            except UnknownTenant:
                self._respond(404, {"message": f"no tenant {parts[1]!r}"})
            except RolloutConflict as e:
                self._respond(409, {"message": str(e)})
            except ValueError as e:
                self._respond(400, {"message": str(e)})
            except Exception as e:
                log.exception("tenant rollout request failed")
                self._respond(500, {"message": str(e)})
            return
        self._respond(404, {"message": "Not Found"})

    def _queries(self, tenant_id: Optional[str] = None):
        """In-flight accounting wrapper: graceful drain (ISSUE 15)
        waits for this count to reach zero before the replica stops,
        so a retiring replica answers everything it admitted."""
        owner = self.server.owner
        owner.inflight_enter()
        try:
            self._queries_inner(tenant_id)
        finally:
            owner.inflight_exit()

    def _queries_inner(self, tenant_id: Optional[str] = None):
        """The serving hot path (reference CreateServer.scala:490-613)."""
        owner = self.server.owner
        t0 = time.perf_counter()
        # sticky routing bucket (ISSUE 15): a gateway fronting this
        # replica computes crc32(body) % 10000 ONCE and forwards it, so
        # every replica (and every hedged retry) makes the same canary
        # decision; absent the header, the replica hashes locally
        bucket: Optional[int] = None
        rh = self.headers.get("X-PIO-Route-Hash")
        if rh:
            try:
                bucket = int(rh) % 10_000
            except ValueError:
                bucket = None
        # load shedding (ISSUE 4): a query whose propagated deadline
        # (X-PIO-Deadline, set as the ambient deadline by JsonHandler)
        # already passed is refused BEFORE parsing, batching, or device
        # time — the client stopped waiting, any work is pure waste
        if _deadline.expired():
            owner.count_shed("deadline")
            self._respond(
                503,
                {"message": "deadline expired; request shed"},
                headers={"Retry-After": "1"},
            )
            return
        # tenant admission (ISSUE 6): resolve the tenant and enforce its
        # quotas BEFORE parse/batch/device time — an over-quota request
        # is the tenant's doing and gets 429 + Retry-After, deliberately
        # distinct from the deadline/overload 503 above
        mux = owner.tenancy
        tenant = None
        lease = None
        dl_token = None  # tenant deadline-floor clamp (reset in finally)
        if tenant_id is not None:
            from predictionio_tpu.tenancy import (
                QuotaExceeded,
                UnknownTenant,
            )

            if mux is None:
                self._respond(
                    404,
                    {"message": "multi-tenant serving is not enabled"},
                )
                return
            try:
                tenant = mux.admit(tenant_id)
            except UnknownTenant:
                self._respond(
                    404, {"message": f"no tenant {tenant_id!r}"}
                )
                return
            except QuotaExceeded as e:
                owner.count_shed("quota")
                self._respond(
                    429,
                    {"message": str(e)},
                    headers={
                        "Retry-After": str(
                            max(1, int(e.retry_after_s + 0.999))
                        )
                    },
                )
                return
            # per-tenant X-PIO-Deadline floor (ISSUE 10 satellite):
            # clamp the ambient deadline AT ADMIT — a request with no
            # deadline (or a longer one) gets the tenant's budget, so
            # this tenant's slow clients can't hold dispatcher leases
            # past it (the dispatcher submit + shed paths below read
            # the ambient deadline)
            floor_ms = getattr(tenant, "deadline_floor_ms", None)
            if floor_ms:
                cap = time.monotonic() + floor_ms / 1000.0
                cur = _deadline.current()
                if cur is None or cur > cap:
                    dl_token = _deadline.set_deadline(cap)
        variant: Optional[str] = None  # set once routing lands
        variant_booked = False

        def _book(seconds: float, error: bool) -> None:
            if tenant is not None:
                mux.bookkeep(tenant_id, variant, seconds, error)
            else:
                owner.bookkeep_variant(variant, seconds, error)

        try:
            raw = self._raw_body.decode()
            try:
                query_json = json.loads(raw or "null")
            except json.JSONDecodeError as e:
                raise _HttpError(400, f"invalid query JSON: {e}")
            # canary routing (ISSUE 5): sticky hash-of-request fraction
            # goes to the candidate runtime; snapshot semantics match
            # /reload — the query is extracted and served against ONE
            # runtime even if a swap lands mid-flight. Tenant queries
            # (ISSUE 6) route through the model cache instead — a miss
            # is a transparent model load, and the returned lease keeps
            # the runtime un-evictable until bookkeeping finishes.
            if tenant is not None:
                from predictionio_tpu.tenancy import ModelLoadError

                try:
                    rt, variant, lease = mux.route(
                        tenant, self._raw_body, bucket=bucket
                    )
                except ModelLoadError as e:
                    raise _HttpError(503, str(e))
            else:
                rt, variant = owner.pick_runtime(
                    self._raw_body, bucket=bucket
                )
            custom_from = getattr(
                rt.query_serializer, "query_from_json", None
            )
            if custom_from is None and not isinstance(query_json, dict):
                raise _HttpError(400, "query must be a JSON object")
            try:
                if custom_from is not None:
                    query = custom_from(query_json)
                elif rt.query_class is not None:
                    query = extract_params(rt.query_class, query_json)
                else:
                    query = query_json
            except ParamsError as e:
                raise _HttpError(400, str(e))
            except ValueError as e:
                raise _HttpError(400, f"query serializer rejected: {e}")

            supplemented = rt.serving.supplement(query)
            try:
                if owner.dispatcher is not None:
                    prediction = owner.dispatcher.submit(
                        supplemented, rt, deadline=_deadline.current(),
                        tenant=tenant_id if tenant is not None else None,
                    )
                else:
                    tp = time.perf_counter()
                    predictions = [
                        algo.predict(model, supplemented)
                        for algo, model in zip(rt.algorithms, rt.models)
                    ]
                    dt_predict = time.perf_counter() - tp
                    owner.bookkeep_predict(dt_predict, 1)
                    if tenant is not None:
                        # no dispatcher → no batch-level charge site:
                        # debit the measured inline predict time here so
                        # the device-seconds quota enforces either way
                        owner.charge_device_seconds(tenant_id, dt_predict)
                    prediction = rt.serving.serve(supplemented, predictions)
            except ValueError as e:
                # algorithms raise ValueError for query-level contract
                # violations (e.g. category filter without category data)
                raise _HttpError(400, str(e))
            custom_to = getattr(rt.query_serializer, "result_to_json", None)
            result = (
                custom_to(prediction) if custom_to is not None
                else _to_jsonable(prediction)
            )
            # shadow agreement compares the SERIALIZED result before
            # output blockers run — blockers may stamp per-request data
            # (ids, timestamps) that would read as disagreement
            shadow_reference = result

            for plugin in owner.output_blockers:
                result = plugin.process(query_json, result, {})

            owner.bookkeep(time.perf_counter() - t0)
            _book(time.perf_counter() - t0, error=False)
            variant_booked = True
            if tenant is None:
                # server-level shadow mirroring and the feedback loop
                # are single-tenant surfaces; tenant traffic must not
                # leak into the server rollout's agreement windows
                owner.maybe_shadow(
                    self._raw_body, query_json, shadow_reference,
                    bucket=bucket,
                )
                owner.feedback_async(query_json, result)
            for plugin in owner.output_sniffers:
                try:
                    plugin.process(query_json, result, {})
                except Exception:
                    log.exception("output sniffer failed")
            self._respond(200, result)
        except _HttpError as e:
            # post-routing 4xx DO feed the verdict windows: a candidate
            # whose stricter query class 400s its whole traffic
            # fraction — while live serves the same bodies 200 — shows
            # up as a candidate-only error delta and triggers the
            # rollback it deserves (without this it never reaches
            # min_requests and fails its fraction forever). PRE-routing
            # failures (undecodable body, malformed JSON) stay out of
            # BOTH windows — they never reached either variant, and
            # booking them to one side would skew the delta.
            if variant is not None:
                _book(time.perf_counter() - t0, error=True)
            self._respond(e.status, {"message": e.message})
        except DeadlineExceeded as e:
            # expired in the queue or dispatch outran its budget: the
            # honest answer is "retry later", not a 500 (the dispatcher's
            # drain loop counts the shed, so no double counting here).
            # Sheds feed the windows too: global overload sheds both
            # variants proportionally (delta ≈ 0), but a pathologically
            # slow candidate shedding only ITS fraction must be judged.
            if variant is not None:
                _book(time.perf_counter() - t0, error=True)
            self._respond(
                503, {"message": str(e)}, headers={"Retry-After": "1"}
            )
        except Exception as e:
            log.exception("query failed")
            if variant is not None and not variant_booked:
                # a failure AFTER the success bookkeeping (broken pipe
                # writing the 200) must not record the same request a
                # second time as an error — the canary verdict would
                # see inflated candidate error rates on client hangups
                _book(time.perf_counter() - t0, error=True)
            self._respond(500, {"message": str(e)})
        finally:
            if dl_token is not None:
                _deadline.reset(dl_token)
            if tenant is not None:
                # release the cache lease (the runtime becomes evictable
                # again) and the tenant's concurrency slot
                mux.done(tenant_id, lease)


class _Pending:
    """One queued query awaiting a device batch. `deadline` is an
    absolute time.monotonic() bound (None = unbounded); `cancelled` is
    set by the submitting handler when its client stopped waiting, so
    the drain loop skips the entry instead of burning a device dispatch
    on an answer nobody will read (ISSUE 4 satellite: the old tuple
    entries had no way to be withdrawn). `tenant` (ISSUE 6) tags the
    entry for the fair scheduler's per-tenant sub-queue and for the
    dispatcher's device-seconds accounting."""

    __slots__ = (
        "query", "runtime", "fut", "t_submit", "tctx", "deadline",
        "cancelled", "tenant",
    )

    def __init__(
        self, query, runtime, fut, t_submit, tctx, deadline, tenant=None
    ):
        self.query = query
        self.runtime = runtime
        self.fut = fut
        self.t_submit = t_submit
        self.tctx = tctx
        self.deadline = deadline
        self.cancelled = False
        self.tenant = tenant


class _BatchDispatcher:
    """Coalesces concurrent queries into one batch_predict device call.

    Handler threads submit a supplemented query and block on a Future; a
    single dispatcher thread drains the queue every `window_ms` (or at
    `max_batch`) and hands the batch to a `pipeline_depth`-wide worker
    pool. The pool is the pipelining seam (VERDICT r3 #3): while worker
    A blocks fetching batch N's device results (the GIL is released in
    the transfer wait), worker B dispatches batch N+1 onto the device
    stream and the dispatcher thread is already collecting batch N+2 —
    the device never idles waiting for serve/JSON of a finished batch.
    A semaphore bounds in-flight batches so queue pressure backs up into
    the drain loop (deeper adaptive windows) instead of unbounded device
    memory. The reference never solved this (its serving hot path keeps
    the "TODO: Parallelize" comment, CreateServer.scala:514-517)."""

    def __init__(
        self,
        owner: "QueryServer",
        window_ms: float,
        max_batch: int,
        max_window_ms: Optional[float] = None,
        pipeline_depth: int = 4,
        batching: str = "continuous",
        tenant_drain: bool = True,
        admission_cap: int = 0,
    ):
        from concurrent.futures import ThreadPoolExecutor

        from predictionio_tpu.tenancy.fair import FairQueue

        if batching not in ("continuous", "windowed"):
            raise ValueError(
                f"batching must be continuous|windowed, got {batching!r}"
            )
        self.owner = owner
        self.min_window_s = window_ms / 1000.0
        self.max_window_s = (
            max_window_ms / 1000.0 if max_window_ms else self.min_window_s
        )
        self.window_s = self.min_window_s
        self.max_batch = max_batch
        self.batching = batching
        self.tenant_drain = tenant_drain
        self.admission_cap = max(0, int(admission_cap))
        self.pipeline_depth = max(1, pipeline_depth)
        self._retired = 0  # buckets retired — continuous mode's signal
        self._pool = ThreadPoolExecutor(
            max_workers=self.pipeline_depth, thread_name_prefix="query-batch"
        )
        self._inflight = threading.BoundedSemaphore(self.pipeline_depth)
        self._active_lock = threading.Lock()
        self._active = 0
        # weighted-fair queueing (ISSUE 6): per-tenant sub-queues drained
        # by deficit round robin replace the single FIFO, so one hog
        # tenant's backlog cannot starve the batch assembler. With no
        # tenants (every entry untenanted) this degenerates to FIFO.
        self._queue = FairQueue(
            weight_of=getattr(owner, "tenant_weight", None)
        )
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="query-batcher", daemon=True
        )
        self._thread.start()

    def submit(
        self, query: Any, runtime: "EngineRuntime", timeout: float = 30.0,
        deadline: Optional[float] = None, tenant: Optional[str] = None,
    ) -> Any:
        """Submit with the runtime snapshot the handler extracted the query
        against — a /reload mid-window must not serve an old-typed query
        with the new model. The handler thread's trace/span context rides
        along so the dispatcher can attribute its queue/device/serve child
        spans to the right request.

        `deadline` (absolute time.monotonic()) caps the wait; when it
        passes — or `timeout` elapses — the entry is marked cancelled so
        the drain loop skips it instead of still dispatching it to the
        device (the old timeout leak), and DeadlineExceeded surfaces to
        the handler as a 503 + Retry-After."""
        import time as _t
        from concurrent.futures import Future, TimeoutError as _FutTimeout

        fut: Future = Future()
        tctx = (_tracing.current_trace_id(), _spans.current_span_id())
        p = _Pending(
            query, runtime, fut, time.perf_counter(), tctx, deadline, tenant
        )
        self._queue.put(p)
        wait = timeout
        if deadline is not None:
            wait = min(wait, max(0.0, deadline - _t.monotonic()))
        try:
            return fut.result(timeout=wait)
        except _FutTimeout:
            p.cancelled = True  # drain must not burn device time on this
            raise DeadlineExceeded(
                "query abandoned: deadline passed while queued for dispatch"
            )

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=1.0)
        self._pool.shutdown(wait=False)
        # fail any waiters still queued so their handler threads don't
        # block out the full submit timeout
        import queue as _q

        while True:
            try:
                p = self._queue.get_nowait()
            except _q.Empty:
                break
            if not p.fut.done():
                p.fut.set_exception(RuntimeError("query server stopped"))

    def _run_group(self, rt: "EngineRuntime", group: list) -> None:
        # last-chance shed: entries can be cancelled (or expire) while
        # the batch waits on the backpressure semaphore — re-filter at
        # the moment device time is about to be spent (ISSUE 4)
        group = self._shed_dead(group)
        if not group:
            return
        queries = [(i, p.query) for i, p in enumerate(group)]
        t0 = time.perf_counter()
        now_wall = time.time()
        registry = getattr(self.owner, "metrics", None)
        recorder = _spans.get_default_recorder()
        # query-triggered capture (ISSUE 8 satellite): an armed
        # POST /debug/traces/capture spends one batch credit here and
        # force-retains every trace riding this batch — the operator's
        # "trace the next N batches" regardless of PIO_TRACE_SAMPLE
        capture_id = recorder.consume_capture()
        if capture_id is not None:
            for p in group:
                if p.tctx[0]:
                    recorder.force_keep(p.tctx[0], capture_id)
        first_submit = min(p.t_submit for p in group)
        # pre-mint the per-query device span ids: storage RPCs issued
        # DURING batch_predict (e.g. UR history fetches) must parent
        # under a device span, so its id has to exist before the call
        dev_ids = [
            _spans.new_span_id() if p.tctx[0] else None for p in group
        ]

        def _child(i: int, name: str, start: float, dur: float,
                   span_id: Optional[str] = None, error: bool = False,
                   **attrs: Any) -> None:
            tid, parent = group[i].tctx
            if tid is None:
                return
            recorder.record(_spans.Span(
                trace_id=tid,
                span_id=span_id or _spans.new_span_id(),
                parent_span_id=parent,
                name=name, start=start, duration=dur,
                attrs={"server": "query", "batch_size": len(group), **attrs},
                error=error,
            ))

        for i, p in enumerate(group):
            t_submit = p.t_submit
            # queue-wait: submit() to device dispatch — the cost the
            # adaptive window adds, isolated from device time so batching
            # PRs can trade one against the other on measured numbers.
            # The span feeds batch_queue_wait_seconds via the recorder's
            # metric bridge (declared in QueryServer.__init__) — one
            # observation per query, same as the old direct observe.
            _child(i, "batch.queue_wait",
                   now_wall - (t0 - t_submit), t0 - t_submit)
            # batch-assemble: the drain window, first arrival to dispatch
            _child(i, "batch.assemble",
                   now_wall - (t0 - first_submit), t0 - first_submit)
        if registry is not None:
            registry.histogram(
                "batch_size", "queries per coalesced device batch",
                buckets=BATCH_SIZE_BUCKETS, lower_bound=1,
            ).observe(len(group))
        # batch-level work (one device program for the whole group) runs
        # under the FIRST traced query's context: its device span adopts
        # any storage RPC spans the batch's predict issues. One batch,
        # many traces — the representative trace gets the full picture,
        # the rest still see their own queue/device/serve timings.
        rep = next((i for i, d in enumerate(dev_ids) if d), None)
        tok_t = tok_s = None
        if rep is not None:
            tok_t = _tracing.set_trace_id(group[rep].tctx[0])
            tok_s = _spans.set_current_span(dev_ids[rep])
        # padding-waste accounting (ISSUE 3) is recorded at the PAD SITES
        # this dispatch drives (engines' _predict_batch, the only places
        # that know the vocab-known row count and the actual bucket) —
        # each batch_predict below lands batch_padding_ratio samples and
        # wasted-FLOPs on the process-default registry.
        # the group's variant scopes the fault point below and attributes
        # fallback errors to the right canary window (ISSUE 5); duck-typed
        # like count_shed — test harnesses drive this loop with minimal
        # owner doubles
        variant_of = getattr(self.owner, "variant_of", None)
        variant = variant_of(rt) if variant_of is not None else "live"
        # groups are keyed by runtime snapshot and each tenant serves its
        # own runtime, so a group is (at most) one tenant's batch — its
        # id scopes the fault point and the device-seconds charge below
        group_tenant = group[0].tenant if group else None
        try:
            try:
                # fault point (ISSUE 4): "error" fails the batch into the
                # per-query fallback below; "delay" simulates a slow
                # device, which is what deadline shedding exists for.
                # The scope label (ISSUE 5) lets chaos tests target one
                # rollout variant: `dispatch.device@candidate:...` flips
                # only canary batches bad while live batches sail through
                _faults.fire("dispatch.device", scope=variant)
                if group_tenant:
                    # per-tenant fault scope (ISSUE 6): chaos tests flip
                    # ONE tenant's batches bad
                    # (`dispatch.device@tenant/acme:...`) while every
                    # other tenant keeps serving
                    _faults.fire(
                        "dispatch.device",
                        scope=f"tenant/{group_tenant}", scoped_only=True,
                    )
                per_algo = [
                    dict(algo.batch_predict(
                        algo.serving_context, model, queries
                    ))
                    for algo, model in zip(rt.algorithms, rt.models)
                ]
                self.last_batch_sec = time.perf_counter() - t0
                for i in range(len(group)):
                    _child(i, "batch.device_dispatch", now_wall,
                           self.last_batch_sec, span_id=dev_ids[i])
                if registry is not None:
                    # device-time histogram stays per coalesced BATCH
                    # (the per-query device spans above share its wall
                    # time; bridging them would inflate the count)
                    registry.histogram(
                        "batch_device_seconds",
                        "device time per coalesced batch (dispatch to fetch)",
                    ).observe(self.last_batch_sec)
                self.owner.bookkeep_predict(self.last_batch_sec, len(group))
                # per-tenant device-seconds accounting (ISSUE 6): each
                # tenant in the batch is charged its per-query share of
                # the measured device time — the post-paid debit the
                # device-seconds quota enforces at the next admission
                charge = getattr(
                    self.owner, "charge_device_seconds", None
                )
                if charge is not None and group_tenant is not None:
                    per_query = self.last_batch_sec / len(group)
                    counts: dict[str, int] = {}
                    for p in group:
                        if p.tenant:
                            counts[p.tenant] = counts.get(p.tenant, 0) + 1
                    for tid, n in counts.items():
                        charge(tid, per_query * n)
                for i, p in enumerate(group):
                    t_s = time.perf_counter()
                    try:
                        result = rt.serving.serve(
                            p.query, [pa[i] for pa in per_algo]
                        )
                    except Exception as e:  # serve failure is per-query
                        dur = time.perf_counter() - t_s
                        _child(i, "batch.result_transfer",
                               time.time() - dur, dur, error=True)
                        p.fut.set_exception(e)
                        continue
                    dur = time.perf_counter() - t_s
                    # result-transfer/serve: per-query fetch + combinator
                    _child(i, "batch.result_transfer",
                           time.time() - dur, dur)
                    p.fut.set_result(result)
            except Exception:
                # one bad query must not poison the batch: retry
                # individually so each waiter gets its own result or its
                # own error. The failed device span is recorded errored
                # so tail sampling always retains these traces.
                for i in range(len(group)):
                    _child(i, "batch.device_dispatch", now_wall,
                           time.perf_counter() - t0, span_id=dev_ids[i],
                           error=True)
                charge = getattr(
                    self.owner, "charge_device_seconds", None
                )
                for p in group:
                    if p.cancelled:  # client gone mid-batch: skip retry
                        continue
                    t_q = time.perf_counter()
                    try:
                        # scoped_only: a scope-less dispatch.device spec
                        # keeps the PR-4 semantic (batch fails, per-query
                        # fallback succeeds); a variant-scoped spec also
                        # fails the fallback so the targeted variant's
                        # queries error visibly — the canary verdict's
                        # error-rate input
                        _faults.fire(
                            "dispatch.device", scope=variant,
                            scoped_only=True,
                        )
                        if p.tenant:
                            _faults.fire(
                                "dispatch.device",
                                scope=f"tenant/{p.tenant}",
                                scoped_only=True,
                            )
                        predictions = [
                            algo.predict(model, p.query)
                            for algo, model in zip(rt.algorithms, rt.models)
                        ]
                        p.fut.set_result(
                            rt.serving.serve(p.query, predictions)
                        )
                    except Exception as e:
                        if not p.fut.done():
                            p.fut.set_exception(e)
                    finally:
                        # fallback predicts are real device work: debit
                        # the post-paid device-seconds bucket here too,
                        # or a tenant whose queries poison every batch
                        # (forcing this path) would bypass the exact
                        # quota meant to contain it
                        if charge is not None and p.tenant:
                            charge(
                                p.tenant, time.perf_counter() - t_q
                            )
        finally:
            if tok_s is not None:
                _spans.reset_current_span(tok_s)
            if tok_t is not None:
                _tracing.reset_trace_id(tok_t)

    def _loop(self) -> None:
        import queue as _q
        import time as _t

        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.2)
            except _q.Empty:
                continue
            # Drain policy (VERDICT r3 #3, measured on the axon tunnel):
            # grab everything already queued; once the queue is dry,
            # dispatch IMMEDIATELY if nothing is in flight (the pipeline
            # is idle — any wait is pure dead time, and a lone idle
            # query sees zero added window latency). With buckets in
            # flight the two modes differ (ISSUE 11):
            #
            # - continuous (default): keep ADMITTING arrivals into this
            #   assembling bucket until an in-flight bucket actually
            #   RETIRES — then ours is next onto the freed slot. No
            #   fixed window: a bucket never sits closed at the
            #   semaphore while new arrivals queue behind it. The
            #   max_window/1.2×batch-time bound survives only as a
            #   wedged-batch backstop.
            # - windowed: linger up to that bound for more arrivals
            #   (the PR-2 behavior, kept for the bench A/B). With
            #   tenants active, the tenant_drain knob ends the linger
            #   as soon as every still-backlogged tenant is represented
            #   in the bucket — one group per tenant per round beats a
            #   full bucket for fairness latency.
            batch = [first]
            retired_mark = self._retired
            round_t0 = _t.monotonic()
            hard_deadline = _t.monotonic() + max(
                self.max_window_s,
                getattr(self, "last_batch_sec", 0.0) * 1.2,
            )
            # continuous mode's backstop exists ONLY for a wedged
            # in-flight batch (device hang, in-flight accounting leak):
            # closing early never serves anyone sooner — the bucket
            # just parks at the semaphore while later arrivals fragment
            # into a second device round-trip. Before the FIRST batch
            # retires there is no last_batch_sec measurement, so give
            # an unmeasured flight several windows before declaring it
            # wedged; shed_dead and the clients' own deadlines still
            # bound how long any held query can suffer.
            wedge_deadline = _t.monotonic() + max(
                10.0 * self.max_window_s,
                getattr(self, "last_batch_sec", 0.0) * 1.2,
            )
            while len(batch) < self.max_batch:
                skip = self._admission_skip(batch)
                try:
                    batch.append(self._queue.get_nowait(skip=skip))
                    continue
                except _q.Empty:
                    pass
                with self._active_lock:
                    active = self._active
                if active == 0:
                    # pipeline idle: dispatch once the arrival stream
                    # pauses. Under recent load the pause threshold
                    # scales with the measured batch time (a closed-loop
                    # response burst spreads over tens of ms; splitting
                    # it costs a full device round-trip per fragment);
                    # after a quiet second it drops back to min_window so
                    # sporadic queries keep near-zero added latency.
                    patience = self.min_window_s
                    if (
                        _t.monotonic() - getattr(self, "_last_dispatch", 0.0)
                        < 1.0
                    ):
                        patience = max(
                            patience,
                            min(
                                0.1 * getattr(self, "last_batch_sec", 0.0),
                                0.02,
                            ),
                        )
                    try:
                        # the admission cap still applies: a capped
                        # tenant's overflow waits for the next bucket
                        # even when the pipeline just went idle
                        batch.append(
                            self._queue.get(timeout=patience, skip=skip)
                        )
                        continue
                    except _q.Empty:
                        break
                if self.batching == "continuous":
                    if self._retired != retired_mark:
                        break  # a bucket retired — dispatch onto the slot
                    if _t.monotonic() >= wedge_deadline:
                        break  # wedged in-flight batch: don't hold queries
                    try:
                        batch.append(
                            self._queue.get(timeout=0.002, skip=skip)
                        )
                    except _q.Empty:
                        pass
                    continue
                if self.tenant_drain and (
                    _t.monotonic() - round_t0 >= self.min_window_s
                ):
                    # only after the base window: closing on a
                    # momentarily-dry queue would ship one-tenant
                    # rounds before the other tenants' arrivals land
                    backlog = self._queue.backlogged()
                    present = {p.tenant for p in batch}
                    tenancy_active = bool(
                        (present | backlog) - {None}
                    )
                    if tenancy_active and backlog <= present:
                        break  # every backlogged tenant has a group
                remaining = hard_deadline - _t.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(
                        self._queue.get(timeout=min(remaining, 0.002))
                    )
                except _q.Empty:
                    pass
            self.window_s = self.min_window_s  # status display only
            # drain-time shedding (ISSUE 4): entries whose client already
            # gave up (cancelled) or whose deadline passed while queued
            # are dropped HERE — before the backpressure semaphore and
            # the device dispatch, which is exactly the time they'd waste
            ready = self._shed_dead(batch)
            # group by runtime snapshot: queries spanning a /reload are
            # served by the runtime they were extracted against
            groups: dict[int, tuple[Any, list]] = {}
            for p in ready:
                groups.setdefault(id(p.runtime), (p.runtime, []))[1].append(p)
            for rt, group in groups.values():
                # poll the semaphore so a stop() during backpressure
                # doesn't leave this thread blocked forever
                acquired = False
                while not self._stop.is_set():
                    if self._inflight.acquire(timeout=0.2):
                        acquired = True
                        break
                if acquired:
                    try:
                        with self._active_lock:
                            self._active += 1
                        self._last_dispatch = _t.monotonic()
                        self._pool.submit(
                            self._run_group_released, rt, group
                        )
                        continue
                    except RuntimeError:  # pool already shut down
                        with self._active_lock:
                            self._active -= 1
                        self._inflight.release()
                for p in group:
                    if not p.fut.done():
                        p.fut.set_exception(
                            RuntimeError("query server stopped")
                        )

    def _admission_skip(self, batch: list) -> Optional[set]:
        """Tenants whose slots in the ASSEMBLING bucket are used up
        (ISSUE 14 satellite — adaptive continuous-batching admission).
        Only continuous mode caps, and only with more than one active
        stream: a solo tenant (or untenanted traffic alone) keeps the
        whole bucket. Auto cap = max_batch // active streams."""
        if self.batching != "continuous":
            return None
        counts: dict = {}
        for p in batch:
            counts[p.tenant] = counts.get(p.tenant, 0) + 1
        active = set(counts) | self._queue.backlogged()
        if len(active) <= 1 or not (active - {None}):
            return None
        cap = self.admission_cap or max(
            1, self.max_batch // len(active)
        )
        skip = {t for t, c in counts.items() if c >= cap}
        return skip or None

    def _shed_dead(self, entries: list) -> list:
        """Drop cancelled/deadline-expired entries, failing their futures
        with DeadlineExceeded (→ 503 + Retry-After at the handler) and
        counting the shed. Returns the still-live entries."""
        import time as _t

        now_m = _t.monotonic()
        live = []
        for p in entries:
            if p.cancelled or (
                p.deadline is not None and now_m >= p.deadline
            ):
                if not p.fut.done():
                    p.fut.set_exception(DeadlineExceeded(
                        "deadline expired before device dispatch"
                    ))
                shed = getattr(self.owner, "count_shed", None)
                if shed is not None:
                    shed("cancelled" if p.cancelled else "expired_in_queue")
                continue
            live.append(p)
        return live

    def _run_group_released(self, rt: "EngineRuntime", group: list) -> None:
        try:
            self._run_group(rt, group)
        finally:
            with self._active_lock:
                self._active -= 1
                self._retired += 1  # continuous drain's dispatch signal
            self._inflight.release()


class RolloutConflict(RuntimeError):
    """A rollout operation conflicts with the server's current state
    (one already active, or none to abort) — a 409 at the HTTP edge."""


class _Server(ThreadedServer):
    owner: "QueryServer"


class QueryServer(ServerProcess):
    """Deploy-server process: serves one engine variant's latest model."""

    _name = "query-server"

    def __init__(
        self,
        storage: Storage,
        runtime: EngineRuntime,
        config: Optional[QueryServerConfig] = None,
    ):
        super().__init__()
        self.storage = storage
        self.runtime = runtime
        self.config = config or QueryServerConfig()
        self.output_blockers = [
            p for p in self.config.plugins
            if getattr(p, "plugin_type", "") == OUTPUT_BLOCKER
        ]
        self.output_sniffers = [
            p for p in self.config.plugins
            if getattr(p, "plugin_type", "") == OUTPUT_SNIFFER
        ]
        # observability (ISSUE 1): registry histograms replace the
        # reference's lossy running averages (CreateServer.scala:603-610)
        # — the old request_count/avg_* attributes survive as properties
        # derived from the histograms, so nothing downstream loses its API
        self.metrics = server_registry()
        self._serve_hist = self.metrics.histogram(
            "serve_seconds",
            "end-to-end query serve time (parse to response JSON built)",
        )
        self._predict_hist = self.metrics.histogram(
            "predict_seconds",
            "device-side predict time per query (model compute + fetch)",
        )
        # span→metric bridge (ISSUE 2): the dispatcher's queue-wait SPAN
        # is the single source — its duration feeds this histogram, so
        # /metrics aggregates and /debug/traces exemplars can't drift
        self._queue_wait_hist = self.metrics.histogram(
            "batch_queue_wait_seconds",
            "micro-batch queue wait, submit to device dispatch",
        )
        # one bridge per span name on the process recorder: with two
        # live QueryServers in one process the newest wins; stop()
        # unregisters so a stopped server's registry isn't kept alive
        self._queue_wait_bridge = (
            lambda sp, _h=self._queue_wait_hist: _h.observe(sp.duration)
        )
        _spans.get_default_recorder().bridge(
            "batch.queue_wait", self._queue_wait_bridge
        )
        # load shedding (ISSUE 4): expired/abandoned queries refused
        # before device time, by reason
        self._shed_counter = self.metrics.counter(
            "queries_shed_total",
            "queries shed before device dispatch (503 + Retry-After)",
            ("reason",),  # label-bound: literal shed-reason set
        )
        # canary rollout (ISSUE 5): per-variant serve/error metrics under
        # a `variant` label — p99s come from the labeled histogram, the
        # verdict loop reads its own sliding windows
        self._variant_serve_hist = self.metrics.histogram(
            "variant_serve_seconds",
            "end-to-end serve time by rollout variant",
            ("variant",),  # label-bound: literal live|candidate
        )
        self._variant_requests = self.metrics.counter(
            "variant_requests_total", "queries served by rollout variant",
            ("variant",),  # label-bound: literal live|candidate
        )
        self._variant_errors = self.metrics.counter(
            "variant_errors_total",
            "failed queries (4xx/5xx/shed) by rollout variant",
            ("variant",),  # label-bound: literal live|candidate
        )
        # runtime-swap lock (ISSUE 5 satellite): /reload and rollout
        # promote/abort all mutate the served-runtime references; the
        # lock serializes them so two concurrent reloads cannot
        # interleave build_runtime with the swap
        self._swap_lock = threading.RLock()
        # sanitizer: reload/promote intentionally hold the swap lock
        # across the candidate's device-staging build (two concurrent
        # reloads must serialize); the SERVING path never takes this
        # lock — queries ride runtime snapshots — so nothing user-facing
        # blocks behind it
        _tsan.allow_blocking_lock(self._swap_lock)
        self.candidate: Optional[EngineRuntime] = None  # guarded-by: _swap_lock
        self.rollout = None  # Optional[RolloutController]  # guarded-by: _swap_lock
        self.tenancy = None  # Optional[TenantMux] (ISSUE 6)
        self.online = None  # Optional[OnlineConsumer] (ISSUE 9)
        self.replica = None  # Optional[ReplicaMember] (ISSUE 15)
        # in-flight query count (ISSUE 15): graceful drain waits on it
        self._inflight_lock = threading.Lock()
        self._inflight = 0  # guarded-by: _inflight_lock
        # in-flight tenant-prefetch warm threads (ISSUE 15): tracked so
        # stop() joins them, same discipline as the feedback threads
        self._prefetch_lock = threading.Lock()
        self._prefetch_threads: set[threading.Thread] = set()  # guarded-by: _prefetch_lock
        self.last_serving_sec = 0.0
        self.last_predict_sec = 0.0
        # in-flight feedback POST threads: tracked so stop() joins them
        # (ISSUE 12 thread-lifecycle — the old per-feedback spawn could
        # outlive the server and POST into a torn-down event server)
        self._feedback_lock = threading.Lock()
        self._feedback_threads: set[threading.Thread] = set()  # guarded-by: _feedback_lock
        self.dispatcher: Optional[_BatchDispatcher] = None
        if self.config.micro_batch:
            self.dispatcher = _BatchDispatcher(
                self,
                self.config.batch_window_ms,
                self.config.max_batch,
                self.config.max_window_ms,
                self.config.pipeline_depth,
                batching=getattr(self.config, "batching", "continuous"),
                tenant_drain=getattr(self.config, "tenant_drain", True),
                admission_cap=getattr(self.config, "admission_cap", 0),
            )

    def start(self) -> int:
        port = super().start()
        # rollout re-adoption (ISSUE 6 satellite, PR-5 follow-up): a
        # restart mid-canary re-adopts the persisted bake instead of
        # silently dropping it (tenant rollouts re-adopt in the mux's
        # sync pass; this covers the server's own engine variant)
        try:
            from predictionio_tpu.deploy.rollout import resume_rollout

            resume_rollout(self)
        except Exception:
            log.exception("rollout re-adoption failed; serving continues")
        return port

    def stop(self) -> None:
        if self.replica is not None:
            # deregister + join the heartbeat thread BEFORE the server
            # goes down, so the gateway never routes to a dead port
            # that still looks alive in the registry
            self.replica.stop()
        if self.online is not None:
            # the consumer thread joins on server stop — same discipline
            # as the monitor/mux/dispatcher threads (ISSUE 9 CI guard)
            self.online.stop()
        if self.tenancy is not None:
            self.tenancy.stop()
        if self.rollout is not None:
            self.rollout.stop()
        if self.dispatcher is not None:
            self.dispatcher.stop()
        _spans.get_default_recorder().unbridge(
            "batch.queue_wait", self._queue_wait_bridge
        )
        with self._feedback_lock:
            pending_feedback = list(self._feedback_threads)
        for t in pending_feedback:
            t.join(timeout=11)  # POST timeout is 10s
        with self._prefetch_lock:
            pending_prefetch = list(self._prefetch_threads)
        for t in pending_prefetch:
            t.join(timeout=5)
        super().stop()  # also detaches the log shipper (ServerProcess)

    def _make_server(self) -> _Server:
        server = _Server((self.config.ip, self.config.port), _Handler)
        server.owner = self
        server.metrics = self.metrics
        server.metrics_label = "query"
        # identity attrs for every server span this process emits
        # (ISSUE 16): after the fleet collector stitches this replica's
        # spans into a cross-process tree, "which engine answered" must
        # survive without a lookup. ReplicaMember.start merges the
        # replica id into this same dict.
        inst = getattr(self.runtime, "instance", None)
        if inst is not None:
            server.span_attrs = {
                "engine": f"{inst.engine_id}/{inst.engine_variant}",
            }
        return server

    # -- reload (reference MasterActor ReloadServer, CreateServer.scala:337) --
    def reload(self) -> None:
        """Hot-swap to the latest COMPLETED instance; in-flight queries keep
        the old runtime snapshot. Serialized under the runtime-swap lock
        (ISSUE 5 satellite): two concurrent reloads — or a reload racing
        a rollout promote — must not interleave build_runtime with the
        reference swap."""
        with self._swap_lock:
            rollout = self.rollout
            if rollout is not None and rollout.st.state in (
                "starting", "canary"
            ):
                # a reload would silently change the verdict baseline
                # mid-bake AND be overwritten by the promote swap —
                # abort the canary first, then reload
                raise RolloutConflict(
                    f"rollout of {rollout.st.version.id} is active; "
                    "abort it before reloading"
                )
            inst = self.runtime.instance
            new_runtime = latest_completed_runtime(
                self.storage, inst.engine_id, inst.engine_version,
                inst.engine_variant,
            )
            self.runtime = new_runtime  # atomic reference swap

    def count_shed(self, reason: str) -> None:
        self._shed_counter.inc(reason=reason)

    # -- canary rollout (ISSUE 5) ------------------------------------------
    def pick_runtime(
        self, raw_request: bytes, bucket: Optional[int] = None
    ) -> tuple[EngineRuntime, str]:
        """Route one request: a sticky hash-of-request fraction lands on
        the candidate while a non-shadow rollout is active. Snapshot the
        references ONCE — a concurrent swap must not split a request
        across two runtimes. `bucket` (ISSUE 15) is the gateway's
        pre-computed routing hash when one fronts this replica."""
        from predictionio_tpu.deploy.rollout import sticky_candidate

        candidate, rollout = self.candidate, self.rollout
        if (
            candidate is not None
            and rollout is not None
            and not rollout.config.shadow
            and sticky_candidate(
                raw_request, rollout.config.fraction, bucket=bucket
            )
        ):
            return candidate, "candidate"
        return self.runtime, "live"

    def variant_of(self, rt: EngineRuntime) -> str:
        if rt is self.candidate:
            return "candidate"
        mux = self.tenancy
        if mux is not None and mux.is_candidate(rt):
            return "candidate"
        return "live"

    # -- replica membership (ISSUE 15) -------------------------------------
    def inflight_enter(self) -> None:
        with self._inflight_lock:
            self._inflight += 1

    def inflight_exit(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1

    @property
    def inflight_queries(self) -> int:
        with self._inflight_lock:
            return self._inflight

    def attach_replica(self, member) -> None:
        """Join the replicated serving tier: register the heartbeating
        replica record and adopt the durable replica identity — which
        also scopes any online fold-in cursor attached afterwards, so N
        replicas on one stream never share a cursor."""
        self.replica = member
        member.start()

    def replica_status(self) -> dict:
        if self.replica is None:
            return {"state": "detached"}
        return dict(self.replica.status(), state="attached")

    def prefetch_tenants(self, tenant_ids: list[str]) -> list[str]:
        """Warm the tenant model cache off the serving path (the
        gateway's scale-up hint). Best-effort: unknown tenants and
        failed loads are skipped — the replica must come up regardless."""
        mux = self.tenancy
        if mux is None or not tenant_ids:
            return []
        accepted = [str(t) for t in tenant_ids[:64]]

        def warm():
            try:
                for tid in accepted:
                    try:
                        tenant = mux.admit(tid)
                    except Exception:
                        log.debug(
                            "prefetch warm of tenant %r failed", tid,
                            exc_info=True,
                        )
                        continue
                    # admit holds a concurrency slot until done — a
                    # failed model load must still release it or the
                    # tenant's quota leaks one slot per failed warm
                    lease = None
                    try:
                        _rt, _variant, lease = mux.route(tenant, b"")
                    except Exception:
                        log.debug(
                            "prefetch warm of tenant %r failed", tid,
                            exc_info=True,
                        )
                    finally:
                        mux.done(tid, lease)
            finally:
                with self._prefetch_lock:
                    self._prefetch_threads.discard(
                        threading.current_thread()
                    )

        t = threading.Thread(
            target=warm, name="tenant-prefetch", daemon=True
        )
        with self._prefetch_lock:
            self._prefetch_threads.add(t)
        t.start()
        return accepted

    # -- online learning (ISSUE 9) -----------------------------------------
    def attach_online(
        self, app_id: int, config=None, channel_id: Optional[int] = None,
        consumer=None,
    ):
        """Attach a streaming fold-in consumer: events for `app_id` tail
        into this server's live runtime between retrains. Pass a
        pre-built `consumer` to override the default wiring (tests).

        With a replica member attached (ISSUE 15), the DEFAULT cursor
        record name gains the durable replica id — two replicas folding
        the same stream automatically use distinct single-writer
        cursors instead of relying on the operator to name them."""
        from predictionio_tpu.online import (
            OnlineConsumer,
            OnlineConsumerConfig,
            ServerApplyHost,
        )

        if self.online is not None:
            self.online.stop()
            if not self.online.stopped():
                # a wedged tick survived the stop timeout: starting a
                # replacement would put TWO writers on the same
                # single-writer cursor record
                raise RuntimeError(
                    "previous online consumer did not stop (wedged "
                    "tick?); refusing to start a second writer on its "
                    "cursor"
                )
        if consumer is None:
            config = config or OnlineConsumerConfig()
            if config.name is None and self.replica is not None:
                config = dataclasses.replace(
                    config,
                    name=(
                        f"online/{app_id}/server"
                        f"@{self.replica.replica_id}"
                    ),
                    # one-shot adoption of the pre-replica-scoped record
                    # (ISSUE 19 satellite): a server upgraded in place
                    # resumes exactly where its un-scoped cursor stood
                    migrate_from=(
                        config.migrate_from
                        or f"online/{app_id}/server"
                    ),
                )
            consumer = OnlineConsumer(
                self.storage, ServerApplyHost(self), app_id,
                config=config, channel_id=channel_id,
                metrics=self.metrics,
            )
        self.online = consumer
        self.online.start()
        return self.online

    def online_status(self) -> dict:
        if self.online is None:
            return {"state": "detached"}
        return dict(self.online.status(), state="attached")

    # -- sharded serving status (ISSUE 10) ---------------------------------
    def fleet_serving_status(self) -> dict:
        """GET /fleet/status: the sharded-serving layout of every served
        runtime (live + candidate). Snapshotted under the runtime-swap
        lock so a /reload or rollout promote mid-read can't tear the
        variant→model mapping."""
        with self._swap_lock:
            variants = {"live": self.runtime}
            if self.candidate is not None:
                variants["candidate"] = self.candidate
            out: dict = {"variants": {}}
            for name, rt in variants.items():
                models = []
                for model in getattr(rt, "models", ()) or ():
                    info_fn = getattr(model, "sharded_info", None)
                    info = info_fn() if callable(info_fn) else None
                    models.append(
                        {"sharded": info is not None, **(info or {})}
                    )
                out["variants"][name] = {"models": models}
            out["sharded"] = any(
                m["sharded"]
                for v in out["variants"].values()
                for m in v["models"]
            )
            return out

    # -- multi-tenant serving (ISSUE 6) ------------------------------------
    def attach_tenancy(self, mux) -> None:
        """Attach a TenantMux: /tenants/* routes go live, tenant-tagged
        queries flow through the weighted-fair scheduler and the model
        cache, and the mux's background sync (tenant refresh, rollout
        re-adoption, registry-driven prefetch) starts."""
        if self.dispatcher is None:
            log.warning(
                "tenancy attached with micro-batching disabled: "
                "weighted-fair scheduling is unavailable (quotas and "
                "the model cache still enforce)"
            )
        self.tenancy = mux
        mux.start()

    def tenant_weight(self, tenant_id: Optional[str]) -> float:
        """Fair-queue weight lookup the dispatcher calls per drain."""
        mux = self.tenancy
        return 1.0 if mux is None else mux.tenant_weight(tenant_id)

    def charge_device_seconds(self, tenant_id: str, seconds: float) -> None:
        """Dispatcher hook: post-paid device-time debit per tenant."""
        mux = self.tenancy
        if mux is not None:
            mux.charge_device_seconds(tenant_id, seconds)

    def bookkeep_variant(
        self, variant: str, seconds: float, error: bool
    ) -> None:
        self._variant_serve_hist.observe(seconds, variant=variant)
        self._variant_requests.inc(variant=variant)
        if error:
            self._variant_errors.inc(variant=variant)
        rollout = self.rollout
        if rollout is not None:
            rollout.record(variant, seconds, error)

    def maybe_shadow(
        self, raw: bytes, query_json: Any, result: Any,
        bucket: Optional[int] = None,
    ) -> None:
        """Shadow mode: mirror a fraction of live traffic to the
        candidate OFF the response path and score result agreement.
        The mirror runs the CANDIDATE's full serving path — its own
        query extraction and serving.supplement, not live's — so a
        candidate whose supplement/serializer is broken (or legitimately
        different) is judged on its own behavior. Bounded concurrency;
        mirror failures count as candidate errors."""
        from predictionio_tpu.deploy.rollout import sticky_candidate

        candidate, rollout = self.candidate, self.rollout
        if (
            candidate is None
            or rollout is None
            or not rollout.config.shadow
            or not sticky_candidate(
                raw, rollout.config.fraction, bucket=bucket
            )
            or not rollout.try_shadow()
        ):
            return

        def mirror():
            t0 = time.perf_counter()
            try:
                custom_from = getattr(
                    candidate.query_serializer, "query_from_json", None
                )
                if custom_from is not None:
                    query = custom_from(query_json)
                elif candidate.query_class is not None:
                    query = extract_params(candidate.query_class, query_json)
                else:
                    query = query_json
                supplemented = candidate.serving.supplement(query)
                if self.dispatcher is not None:
                    prediction = self.dispatcher.submit(
                        supplemented, candidate
                    )
                else:
                    predictions = [
                        algo.predict(model, supplemented)
                        for algo, model in zip(
                            candidate.algorithms, candidate.models
                        )
                    ]
                    prediction = candidate.serving.serve(
                        supplemented, predictions
                    )
                # serialize exactly as the live path does (custom
                # serializer included) so agreement compares like with
                # like — raw _to_jsonable vs a custom result_to_json
                # would read as 100% disagreement on such engines
                custom_to = getattr(
                    candidate.query_serializer, "result_to_json", None
                )
                shadow_result = (
                    custom_to(prediction) if custom_to is not None
                    else _to_jsonable(prediction)
                )
                rollout.record(
                    "candidate", time.perf_counter() - t0, error=False
                )
                rollout.record_agreement(shadow_result == result)
            except Exception:
                rollout.record(
                    "candidate", time.perf_counter() - t0, error=True
                )
                rollout.record_agreement(False)
            finally:
                rollout.shadow_done()

        rollout.run_shadow(mirror)

    def attach_rollout(self, controller, candidate: EngineRuntime) -> None:
        """Called by RolloutController.start() once the candidate runtime
        built successfully."""
        with self._swap_lock:
            if self.rollout is not None and self.rollout.st.state in (
                "starting", "canary"
            ):
                raise RolloutConflict(
                    f"rollout of {self.rollout.st.version.id} is already "
                    "active"
                )
            self.candidate = candidate
            self.rollout = controller

    def complete_rollout(self, controller, promote: bool) -> None:
        """Atomic end of a canary: promote hot-swaps candidate → live
        (the old runtime drains — in-flight queries keep their snapshot,
        zero dropped); rollback just detaches the candidate."""
        with self._swap_lock:
            if self.rollout is not controller:
                return  # stale controller (a newer rollout replaced it)
            if promote and self.candidate is not None:
                self.runtime = self.candidate
            self.candidate = None

    def start_rollout(self, body: dict) -> dict:
        """POST /rollout/start: canary a registered model version. With
        no explicit version, the newest `trained` version of the served
        engine variant is used."""
        from predictionio_tpu.deploy.registry import ModelRegistry
        from predictionio_tpu.deploy.rollout import (
            RolloutConfig,
            RolloutController,
        )

        registry = ModelRegistry(self.storage)
        vid = body.get("version")
        if vid:
            version = registry.get(vid)
            if version is None:
                raise ValueError(f"no model version {vid!r}")
        else:
            inst = self.runtime.instance
            trained = registry.list(
                inst.engine_id, inst.engine_variant, status="trained"
            )
            if not trained:
                raise ValueError(
                    f"no trained model version for {inst.engine_id}/"
                    f"{inst.engine_variant} — train (or `pio jobs submit`) "
                    "first"
                )
            version = trained[0]
        overrides = {
            k: body[k]
            for k in (
                "fraction", "window_s", "interval_s", "min_requests",
                "max_error_delta", "max_p99_ratio", "bake_s", "shadow",
                "min_agreement",
            )
            if k in body
        }
        config = RolloutConfig.from_env(**overrides)
        controller = RolloutController(self, version, config)
        try:
            controller.start()
        except (RolloutConflict, StorageError):
            # conflicts map to 409; a storage outage is the SERVER's
            # trouble (500), not a malformed request — automation that
            # treats 4xx as non-retryable must not be told 400 for it
            raise
        except Exception as e:
            # candidate build failed (model.load fault, missing blob):
            # the canary never started and live serving is untouched
            raise ValueError(f"canary start failed: {e}")
        return controller.status()

    def abort_rollout(self, reason: str) -> dict:
        rollout = self.rollout
        if rollout is None or rollout.st.state != "canary":
            raise RolloutConflict("no active rollout to abort")
        # stop the verdict thread FIRST, then re-check: the loop may
        # have promoted/rolled back between our check and the join — an
        # abort after that must not mark the now-live version rolled_back
        rollout.stop()
        if rollout.st.state != "canary":
            raise RolloutConflict(
                f"rollout already {rollout.st.state}; nothing to abort"
            )
        rollout.abort(reason)
        return rollout.status()

    def rollout_status(self) -> dict:
        rollout = self.rollout
        if rollout is None:
            return {"state": "none"}
        return rollout.status()

    # -- bookkeeping (registry-backed; the averages are now derived) -------
    def bookkeep(self, seconds: float) -> None:
        self.last_serving_sec = seconds
        self._serve_hist.observe(seconds)

    def bookkeep_predict(self, seconds: float, batch_size: int) -> None:
        """Device-side (model compute incl. result fetch) time per query,
        isolated from HTTP/queue overhead so tunnel-RTT-dominated
        end-to-end numbers don't mask device latency."""
        per_query = seconds / max(1, batch_size)
        self.last_predict_sec = per_query
        self._predict_hist.observe(per_query)

    @property
    def request_count(self) -> int:
        return self._serve_hist.count

    @property
    def avg_serving_sec(self) -> float:
        return self._serve_hist.mean

    @property
    def predict_count(self) -> int:
        return self._predict_hist.count

    @property
    def avg_predict_sec(self) -> float:
        return self._predict_hist.mean

    # -- feedback loop (reference CreateServer.scala:534-596) --------------
    def feedback_async(self, query_json: dict, result: Any) -> None:
        if not self.config.feedback:
            return
        if not (self.config.event_server_url and self.config.access_key):
            log.warning("feedback enabled but event server url/key missing")
            return

        def post():
            try:
                pr_id = (
                    result.get("pr_id")
                    if isinstance(result, dict) and result.get("pr_id")
                    else self.runtime.instance.id
                )
                event = {
                    "event": "predict",
                    "entityType": "pio_pr",
                    "entityId": pr_id,
                    "properties": {"query": query_json, "prediction": result},
                    "prId": pr_id,
                }
                url = (
                    f"{self.config.event_server_url}/events.json"
                    f"?accessKey={self.config.access_key}"
                )
                req = urllib.request.Request(
                    url,
                    data=json.dumps(event).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                urllib.request.urlopen(req, timeout=10).read()
            except Exception:
                log.exception("feedback event POST failed")
            finally:
                with self._feedback_lock:
                    self._feedback_threads.discard(
                        threading.current_thread()
                    )

        t = threading.Thread(target=post, name="feedback-post", daemon=True)
        with self._feedback_lock:
            self._feedback_threads.add(t)
        t.start()

    # -- status page (reference CreateServer.scala:461-489 Twirl html) -----
    def status_html(self) -> str:
        """Rendered FROM the metrics registry (averages + p50/p95/p99
        come from the serve/predict histograms). All engine/instance
        fields and params reprs are escaped — they carry user-authored
        strings (engine.json), same as tools/dashboard.py already did."""
        esc = _html.escape
        rt = self.runtime
        inst = rt.instance
        serve, predict = self._serve_hist, self._predict_hist
        count = serve.count
        avg, avg_p = serve.mean, predict.mean
        last, last_p = self.last_serving_sec, self.last_predict_sec
        q = lambda h, p: h.quantile(p) * 1000.0  # noqa: E731
        window_ms = (
            self.dispatcher.window_s * 1000.0 if self.dispatcher else 0.0
        )
        algo_rows = "".join(
            f"<tr><td>{esc(type(a).__name__)}</td><td>{esc(name)}</td>"
            f"<td><code>{esc(repr(params))}</code></td></tr>"
            for a, (name, params) in zip(
                rt.algorithms, rt.engine_params.algorithm_params_list
            )
        )
        return f"""<!DOCTYPE html><html><head><title>{esc(inst.engine_id)} — predictionio_tpu</title></head>
<body>
<h1>Engine {esc(inst.engine_id)} ({esc(inst.engine_variant)})</h1>
<table>
<tr><td>Instance</td><td>{esc(inst.id)}</td></tr>
<tr><td>Factory</td><td>{esc(inst.engine_factory)}</td></tr>
<tr><td>Trained</td><td>{esc(str(inst.end_time))}</td></tr>
<tr><td>Serving since</td><td>{esc(str(rt.started_at))}</td></tr>
<tr><td>Requests</td><td>{count}</td></tr>
<tr><td>Average serve time</td><td>{avg * 1000:.3f} ms</td></tr>
<tr><td>Serve p50 / p95 / p99</td><td>{q(serve, 0.5):.3f} / {q(serve, 0.95):.3f} / {q(serve, 0.99):.3f} ms</td></tr>
<tr><td>Last serve time</td><td>{last * 1000:.3f} ms</td></tr>
<tr><td>Average device predict time</td><td>{avg_p * 1000:.3f} ms</td></tr>
<tr><td>Predict p50 / p95 / p99</td><td>{q(predict, 0.5):.3f} / {q(predict, 0.95):.3f} / {q(predict, 0.99):.3f} ms</td></tr>
<tr><td>Last device predict time</td><td>{last_p * 1000:.3f} ms</td></tr>
<tr><td>Serve − predict = HTTP/queue/transport overhead</td><td>{(avg - avg_p) * 1000:.3f} ms</td></tr>
<tr><td>Micro-batch window (adaptive)</td><td>{window_ms:.2f} ms</td></tr>
</table>
<h2>Algorithms</h2>
<table><tr><th>class</th><th>name</th><th>params</th></tr>{algo_rows}</table>
<p><a href="/reload">reload model</a> · <a href="/metrics">prometheus metrics</a></p>
</body></html>"""
