"""Train workflow driver: variant JSON → engine → models → MODELDATA.

Reference: CreateWorkflow.main (CreateWorkflow.scala:133) +
CoreWorkflow.runTrain (CoreWorkflow.scala:42-99). The Spark driver process
becomes a plain function call (the CLI spawns it in-process or as a child
python, not via spark-submit); the SparkContext becomes a RuntimeContext
carrying the storage registry and an optional device mesh built from the
variant's `mesh` config (the re-design of `sparkConf` pass-through,
WorkflowUtils.extractSparkConf:316).
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import uuid
from typing import Any, Optional

from predictionio_tpu.controller.engine import EngineParams, resolve_engine
from predictionio_tpu.controller.params import load_symbol, params_to_json
from predictionio_tpu.controller.persistent import serialize_models
from predictionio_tpu.core.base import (
    RuntimeContext,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    WorkflowParams,
)
from predictionio_tpu.data.storage.base import EngineInstance, Model
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.obs import get_default_registry
from predictionio_tpu.obs import spans as _spans

log = logging.getLogger(__name__)


def _stage_json(stage: tuple[str, Any]) -> str:
    """Persist a (stage-name, params) pair — the name matters: deploy must
    rebind the same named class the train run used (the reference stores
    name+params per stage, EngineInstances.scala:43)."""
    name, params = stage
    return json.dumps(
        {"name": name, "params": json.loads(params_to_json(params))},
        sort_keys=True,
    )


def load_variant(path: str) -> dict:
    """Load an engine variant JSON file (engine.json)."""
    with open(path) as f:
        variant = json.load(f)
    for key in ("id", "engineFactory"):
        if key not in variant:
            raise ValueError(f"engine variant is missing {key!r} ({path})")
    return variant


def runtime_context_from_variant(
    storage: Storage,
    variant: dict,
    mode: str = "train",
    workflow_params: Optional[WorkflowParams] = None,
    use_mesh: bool = True,
) -> RuntimeContext:
    mesh = None
    if use_mesh and variant.get("mesh"):
        from predictionio_tpu.parallel.mesh import MeshConf

        mesh = MeshConf.from_json(variant["mesh"]).build()
    return RuntimeContext(
        storage=storage,
        mesh=mesh,
        mode=mode,
        workflow_params=workflow_params or WorkflowParams(),
    )


def run_train(
    storage: Storage,
    variant: dict,
    workflow_params: Optional[WorkflowParams] = None,
    engine_params: Optional[EngineParams] = None,
    engine_id: Optional[str] = None,
    engine_version: str = "0",
) -> EngineInstance:
    """The whole `pio train` data path (reference call stack SURVEY.md §3.1):
    resolve factory → params from variant → EngineInstance INIT row →
    engine.train → serializable models → MODELDATA blob → COMPLETED.

    Returns the COMPLETED EngineInstance row.
    """
    wp = workflow_params or WorkflowParams()
    from predictionio_tpu.obs.jaxmon import ensure_compile_listener

    ensure_compile_listener()  # count this run's jit compiles on scrape
    engine = resolve_engine(load_symbol(variant["engineFactory"]))
    if engine_params is None:
        engine_params = engine.params_from_variant_json(variant)

    instances = storage.get_meta_data_engine_instances()
    now = _dt.datetime.now(_dt.timezone.utc)
    instance = EngineInstance(
        id=str(uuid.uuid4()),
        status="INIT",
        start_time=now,
        end_time=now,
        engine_id=engine_id or variant["id"],
        engine_version=engine_version,
        engine_variant=variant["id"],
        engine_factory=variant["engineFactory"],
        batch=wp.batch,
        data_source_params=_stage_json(engine_params.data_source_params),
        preparator_params=_stage_json(engine_params.preparator_params),
        algorithms_params=json.dumps(
            [
                {"name": name, "params": json.loads(params_to_json(p))}
                for name, p in engine_params.algorithm_params_list
            ]
        ),
        serving_params=_stage_json(engine_params.serving_params),
        mesh_conf=variant.get("mesh") or {},
    )
    import contextlib

    profile_cm: Any = contextlib.nullcontext()
    if wp.profile_dir:
        # SURVEY §5: XLA profiler hook — the whole train runs under a
        # jax.profiler trace; inspect with tensorboard/xprof. Built BEFORE
        # the instance row is inserted so a failure here can't strand a
        # row in INIT.
        import jax

        profile_cm = jax.profiler.trace(wp.profile_dir)

    instance_id = instances.insert(instance)
    instance.id = instance_id

    ctx = runtime_context_from_variant(storage, variant, "train", wp)
    ctx.instance_id = instance_id

    def _record_timings() -> None:
        # the EngineInstance blob stays as a point-in-time snapshot of
        # what the unified registry recorded live (ISSUE 1)
        instance.env = dict(instance.env or {})
        instance.env["stage_timings"] = json.dumps(
            {k: round(v, 4) for k, v in ctx.stage_timings.items()}
        )

    def _count_run(status: str) -> None:
        get_default_registry().counter(
            "train_runs_total", "train workflows by final status",
            ("status",),  # label-bound: literal status set
        ).inc(status=status)

    try:
        # root span of the whole train (ISSUE 2): opens a trace if the
        # caller didn't (CLI `pio train`), parents every DASE stage span
        # engine.train emits, and — because an aborted train marks it
        # errored — guarantees tail sampling retains failed runs
        with _spans.span(
            "train", server="train", instance_id=instance_id,
            engine=instance.engine_id, variant=instance.engine_variant,
        ):
            instance.status = "TRAINING"
            instances.update(instance)
            with profile_cm:
                try:
                    models = engine.train(ctx, engine_params)
                except (
                    StopAfterReadInterruption, StopAfterPrepareInterruption
                ) as e:
                    # intentional debug stop-points, not failures
                    # (reference CoreWorkflow.scala:88-93 logs
                    # "Training interrupted")
                    log.info("training interrupted by %s", type(e).__name__)
                    instance.status = "INTERRUPTED"
                    instance.end_time = _dt.datetime.now(_dt.timezone.utc)
                    _record_timings()
                    _count_run("INTERRUPTED")
                    instances.update(instance)
                    return instance
                if wp.save_model:
                    from predictionio_tpu.controller.engine import (
                        _stage_span,
                    )

                    with _stage_span("train.persist") as persist_sp:
                        serializable = engine.make_serializable_models(
                            ctx, models, engine_params, instance_id
                        )
                        storage.get_model_data_models().insert(
                            Model(
                                id=instance_id,
                                models=serialize_models(serializable),
                            )
                        )
                    # the histogram observation comes from the span via
                    # the bridge in controller/engine.py; the row snapshot
                    # keeps reading ctx.stage_timings
                    ctx.stage_timings["persist"] = persist_sp.duration
        instance.status = "COMPLETED"
        instance.end_time = _dt.datetime.now(_dt.timezone.utc)
        _record_timings()
        _count_run("COMPLETED")
        instances.update(instance)
        _register_manifest(storage, instance, variant)
        log.info(
            "training completed: instance %s (stages: %s)",
            instance_id,
            {k: round(v, 3) for k, v in ctx.stage_timings.items()},
        )
        return instance
    except Exception:
        instance.status = "ABORTED"
        instance.end_time = _dt.datetime.now(_dt.timezone.utc)
        _record_timings()  # partial timings show WHERE the failed run spent time
        _count_run("ABORTED")
        instances.update(instance)
        raise


def _register_manifest(
    storage: Storage, instance: EngineInstance, variant: dict
) -> None:
    """Upsert the EngineManifest row for a successfully trained engine.

    Reference RegisterEngine.scala:32 writes the manifest at `pio build`;
    here there is no build step (engines are Python entry points named in
    engine.json), so registration happens at the first successful train —
    the moment the factory provably resolves and runs. `pio status` lists
    the registered engines."""
    from predictionio_tpu.data.storage.base import EngineManifest

    try:
        factory = load_symbol(instance.engine_factory)
        description = (factory.__doc__ or "").strip().splitlines()
        storage.get_meta_data_engine_manifests().update(
            EngineManifest(
                id=instance.engine_id,
                version=instance.engine_version,
                name=variant.get("id", instance.engine_id),
                description=description[0] if description else None,
                files=(instance.engine_factory.rsplit(".", 1)[0],),
                engine_factory=instance.engine_factory,
            ),
            upsert=True,
        )
    except Exception:
        log.exception("engine manifest registration failed (non-fatal)")


def prepare_deploy_models(
    storage: Storage,
    instance: EngineInstance,
    engine: Any = None,
    engine_params: Optional[EngineParams] = None,
    use_mesh: bool = True,
) -> tuple[Any, EngineParams, list[Any]]:
    """Re-hydrate a COMPLETED instance's models for serving (reference
    CreateServer.createServerActorWithEngine:206 → Engine.prepareDeploy:196).

    When `use_mesh` and the train run recorded a mesh config, the deploy
    context rebuilds it — so retrain-on-deploy models retrain with the
    same sharding the train run used.

    Returns (engine, engine_params, models)."""
    if engine is None:
        engine = resolve_engine(load_symbol(instance.engine_factory))
    if engine_params is None:
        engine_params = engine_instance_to_engine_params(engine, instance)
    blob = storage.get_model_data_models().get(instance.id)
    if blob is None:
        raise RuntimeError(f"no model blob stored for instance {instance.id}")
    from predictionio_tpu.controller.persistent import deserialize_models

    persisted = deserialize_models(blob.models)
    mesh = None
    if use_mesh and instance.mesh_conf:
        from predictionio_tpu.parallel.mesh import MeshConf

        mesh = MeshConf.from_json(instance.mesh_conf).build()
    ctx = RuntimeContext(storage=storage, mesh=mesh, mode="serve")
    models = engine.prepare_deploy(
        ctx, engine_params, persisted, instance_id=instance.id
    )
    return engine, engine_params, models


def _stage_from_json(raw: str) -> Optional[dict]:
    """Invert _stage_json → a variant stage object, or None when empty."""
    if not raw or raw == "{}":
        return None
    obj = json.loads(raw)
    if "name" not in obj:  # legacy bare-params form
        return {"params": obj} if obj else None
    if not obj["name"] and not obj.get("params"):
        return None
    return {"name": obj["name"], "params": obj.get("params") or None}


def engine_instance_to_engine_params(engine: Any, instance: EngineInstance) -> EngineParams:
    """Rebuild EngineParams from the name+params JSON recorded on the
    instance row (reference Engine.engineInstanceToEngineParams:419)."""
    variant = {
        "id": instance.engine_variant,
        "engineFactory": instance.engine_factory,
    }
    for key, raw in (
        ("datasource", instance.data_source_params),
        ("preparator", instance.preparator_params),
        ("serving", instance.serving_params),
    ):
        stage = _stage_from_json(raw)
        if stage is not None:
            variant[key] = stage
    if instance.algorithms_params:
        variant["algorithms"] = json.loads(instance.algorithms_params)
    return engine.params_from_variant_json(variant)
