"""FakeWorkflow: run an arbitrary function under the full eval
environment without persisting anything.

Parity target: core/src/main/scala/io/prediction/workflow/
FakeWorkflow.scala:25-106 — a @DeveloperApi harness that wraps a
user function in a fake engine/evaluator pair so it executes inside the
real evaluation machinery (context construction, workflow params, result
rendering) with `noSave` semantics: no EvaluationInstance row is
written. Used for experimentation and for testing workflow plumbing
itself."""

from __future__ import annotations

import logging
from typing import Any, Callable, Optional

from predictionio_tpu.core.base import RuntimeContext, WorkflowParams

log = logging.getLogger(__name__)


class FakeEvalResult:
    """Result wrapper with noSave semantics (FakeWorkflow.scala:37-44)."""

    no_save = True

    def __init__(self, value: Any):
        self.value = value

    def to_one_liner(self) -> str:
        return f"FakeEvalResult({self.value!r})"

    def to_html(self) -> str:
        return f"<pre>{self.to_one_liner()}</pre>"

    def to_json(self) -> str:
        import json

        try:
            return json.dumps({"value": self.value})
        except TypeError:
            return json.dumps({"value": repr(self.value)})


def run_fake_workflow(
    fn: Callable[[RuntimeContext], Any],
    storage: Any = None,
    mesh: Any = None,
    workflow_params: Optional[WorkflowParams] = None,
) -> FakeEvalResult:
    """Execute `fn(ctx)` under a fully-constructed eval RuntimeContext.

    Nothing is persisted: no EvaluationInstance, no models — the
    reference's `FakeRunner` + noSave path. The function's return value
    comes back wrapped in a FakeEvalResult."""
    wp = workflow_params or WorkflowParams()
    ctx = RuntimeContext(
        storage=storage, mesh=mesh, mode="eval", workflow_params=wp
    )
    log.info("fake workflow: running %s", getattr(fn, "__name__", fn))
    value = fn(ctx)
    return FakeEvalResult(value)
