"""L5 — workflow drivers (reference core/src/main/scala/io/prediction/workflow/)."""

from predictionio_tpu.workflow.core import (
    load_variant,
    run_train,
    runtime_context_from_variant,
)

__all__ = ["load_variant", "run_train", "runtime_context_from_variant"]
