"""Shared serving-shape bucketing.

Every extra compiled shape on a serving path is a multi-second XLA
compile a live query would otherwise eat, so batch and top-k dimensions
are bucketed to a tiny ladder that warmup can cover. One definition,
used by every engine (recommendation, universal, …) so the compiled-shape
sets cannot drift apart.
"""

from __future__ import annotations


def batch_bucket(n: int) -> int:
    """{1, 8, 64, pow2 beyond}: three compiled programs cover everything
    up to the dispatcher's default max_batch of 64."""
    if n <= 1:
        return 1
    if n <= 8:
        return 8
    if n <= 64:
        return 64
    return 1 << (n - 1).bit_length()


def topk_bucket(k_req: int, n_items: int, floor: int = 128) -> int:
    """Fixed device-side k (pow2 above a floor, capped by the catalog) so
    a query's `num` does not create a compiled program per distinct value;
    results are sliced to `num` on host."""
    if n_items <= floor:
        return n_items
    return min(n_items, max(floor, 1 << (max(k_req, 1) - 1).bit_length()))
