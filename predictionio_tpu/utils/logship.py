"""Remote log shipping: POST server log records to a collector URL.

The analogue of the reference deploy server's `--log-url` option
(core/src/main/scala/io/prediction/workflow/CreateServer.scala:441-452),
generalized to every long-running server here (query server, event
server). Records are buffered and shipped as JSON-lines batches from a
background thread — best-effort: a dead collector never blocks or crashes
the serving path.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import urllib.request

from predictionio_tpu.obs.tracing import current_trace_id


class RemoteLogHandler(logging.Handler):
    """logging.Handler that ships records to `url` as JSON lines.

    Batch shipping: records queue up and a daemon thread POSTs up to
    `batch_size` of them every `flush_interval` seconds. Failures are
    dropped silently after one stderr note (best-effort by design)."""

    def __init__(
        self,
        url: str,
        level: int = logging.INFO,
        batch_size: int = 50,
        flush_interval: float = 2.0,
        max_buffer: int = 10_000,
    ):
        super().__init__(level=level)
        self.url = url
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self._q: queue.Queue = queue.Queue(maxsize=max_buffer)
        self._stop = threading.Event()
        self._warned = False
        self._thread = threading.Thread(
            target=self._loop, name="log-shipper", daemon=True
        )
        self._thread.start()

    def emit(self, record: logging.LogRecord) -> None:
        try:
            entry = {
                "ts": record.created,
                "level": record.levelname,
                "logger": record.name,
                "message": self.format(record),
            }
            # emit() runs on the logging thread, which for server-side
            # records is the request handler thread — the tracing
            # contextvar still holds the request's id, so shipped records
            # correlate with the access log at the collector
            trace_id = current_trace_id()
            if trace_id:
                entry["trace_id"] = trace_id
            self._q.put_nowait(entry)
        except queue.Full:
            pass  # shedding is the correct failure mode for telemetry

    def _drain(self) -> list[dict]:
        out: list[dict] = []
        while len(out) < self.batch_size:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                break
        return out

    def _ship(self, records: list[dict]) -> bool:
        body = "\n".join(json.dumps(r) for r in records).encode()
        req = urllib.request.Request(
            self.url,
            data=body,
            headers={"Content-Type": "application/x-ndjson"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=5):
                pass
            if self._warned:
                # a recovered collector logs its recovery (and re-arms
                # the one-shot warning for the next outage)
                self._warned = False
                logging.getLogger("pio.logship").info(
                    "log shipping to %s recovered", self.url
                )
            return True
        except Exception as e:
            if not self._warned:
                self._warned = True
                # NOT a predictionio_tpu logger: the shipper is typically
                # attached there, and the warning would loop back into the
                # dead-collector queue via propagation
                logging.getLogger("pio.logship").warning(
                    "log shipping to %s failing (%s); further failures "
                    "are silent", self.url, e,
                )
            return False

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(self.flush_interval)
            records = self._drain()
            if records:
                self._ship(records)

    def close(self) -> None:
        self._stop.set()
        while True:  # flush everything pending, batch by batch…
            records = self._drain()
            if not records:
                break
            if not self._ship(records):
                break  # …but a dead collector must not block shutdown
        self._thread.join(timeout=2)
        super().close()


def attach_log_shipper(url: str, logger: logging.Logger | None = None) -> RemoteLogHandler:
    """Install a RemoteLogHandler on `logger` (root by default).

    Also lowers the logger's level to INFO when it would otherwise inherit
    the WARNING root default — --log-url promises INFO-level shipping, and
    without a logging config the records would be dropped at the logger
    before any handler sees them."""
    handler = RemoteLogHandler(url)
    handler.setFormatter(logging.Formatter("%(message)s"))
    target = logger or logging.getLogger()
    if target.getEffectiveLevel() > logging.INFO:
        target.setLevel(logging.INFO)
    target.addHandler(handler)
    return handler
