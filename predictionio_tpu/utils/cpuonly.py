"""Force a CPU-only virtual-device JAX platform in the current process.

Shared by the test conftest, the multi-chip dryrun child, and the
multi-host test children — all of which must run an n-device CPU mesh
even when a sitecustomize has registered a TPU PJRT plugin and set
`jax_platforms` programmatically (so the JAX_PLATFORMS env var alone is
ignored). Must be called BEFORE any JAX backend is initialized.

Non-CPU backend factories are REPLACED with a raising stub, not popped:
Pallas registers MLIR lowerings for the "tpu" platform at import time
and errors if the platform name is no longer known.
"""

from __future__ import annotations

import dataclasses
import os
from typing import MutableMapping


def force_cpu_env(
    env: MutableMapping[str, str],
    n_devices: int,
    override: bool = True,
) -> MutableMapping[str, str]:
    """Set JAX_PLATFORMS/XLA_FLAGS for a CPU n-device platform on an env
    mapping (os.environ or a child-process env dict). With
    override=False an already-present device-count flag is honored."""
    flags = env.get("XLA_FLAGS", "")
    if override or "xla_force_host_platform_device_count" not in flags:
        kept = [
            f
            for f in flags.split()
            if "xla_force_host_platform_device_count" not in f
        ]
        kept.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(kept)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def force_cpu_platform(
    n_devices: int | None = None, override: bool = True
) -> None:
    if n_devices is not None:
        force_cpu_env(os.environ, n_devices, override=override)
    else:
        os.environ["JAX_PLATFORMS"] = "cpu"
    try:  # pragma: no cover - depends on host environment
        import jax

        jax.config.update("jax_platforms", "cpu")

        from jax._src import xla_bridge as xb

        def _blocked(*_a, **_k):
            raise RuntimeError("non-CPU backends are blocked (cpuonly)")

        for name, reg in list(getattr(xb, "_backend_factories", {}).items()):
            if name != "cpu":
                xb._backend_factories[name] = dataclasses.replace(
                    reg, factory=_blocked, fail_quietly=True
                )
    except Exception:
        pass
