"""Shared threaded-HTTP plumbing for the framework's server processes.

Both the Event Server (data/api/server.py) and the deploy query server
(workflow/server.py) are stdlib ThreadingHTTPServer processes with the
same needs: JSON responses, eager body drain (an unread POST body desyncs
HTTP/1.1 keep-alive — the next request parses it as a request line),
routed logging, and a start/stop/port lifecycle."""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

log = logging.getLogger(__name__)


class HttpError(Exception):
    """Raise inside a handler to produce a JSON error response."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class JsonHandler(BaseHTTPRequestHandler):
    """Base handler: drains the body before dispatch, JSON helpers."""

    protocol_version = "HTTP/1.1"
    # status line / headers / body are separate socket writes: with
    # Nagle on, the later writes wait for the peer's delayed ACK — a
    # flat ~40 ms stall per response (measured on the storage RPC path;
    # applies equally to event-server and query-server replies)
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # route through logging, not stderr
        log.debug("%s " + fmt, self.address_string(), *args)

    def handle_one_request(self):
        self._raw_body = b""
        super().handle_one_request()

    def _drain_body(self) -> None:
        length = int(self.headers.get("Content-Length") or 0)
        self._raw_body = self.rfile.read(length) if length else b""

    def _body(self) -> bytes:
        return self._raw_body

    def _json_body(self) -> Any:
        try:
            return json.loads(self._body().decode() or "null")
        except json.JSONDecodeError as e:
            raise HttpError(400, f"invalid JSON: {e}")

    def _respond(
        self, status: int, body: Any, content_type: str = "application/json"
    ) -> None:
        data = (
            body.encode() if isinstance(body, str) else json.dumps(body).encode()
        )
        self.send_response(status)
        self.send_header("Content-Type", f"{content_type}; charset=UTF-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


class ThreadedServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    # socketserver's default listen backlog of 5 drops connections under
    # concurrent load (micro-batched serving expects bursts of clients)
    request_queue_size = 128


class ServerProcess:
    """start/stop/port lifecycle shared by server processes. Subclasses
    implement `_make_server() -> ThreadedServer` and set `_name`."""

    _name = "http-server"

    def __init__(self):
        self._server: Optional[ThreadedServer] = None
        self._thread: Optional[threading.Thread] = None

    def _make_server(self) -> ThreadedServer:
        raise NotImplementedError

    @property
    def port(self) -> int:
        assert self._server is not None, "server not started"
        return self._server.server_address[1]

    def start(self) -> int:
        self._server = self._make_server()
        self._thread = threading.Thread(
            target=self._server.serve_forever, name=self._name, daemon=True
        )
        self._thread.start()
        # remote log shipping (reference CreateServer.scala:441-452
        # --log-url): any server whose config carries log_url ships the
        # framework's log records to the collector
        log_url = getattr(getattr(self, "config", None), "log_url", None)
        if log_url and getattr(self, "_log_shipper", None) is None:
            import logging

            from predictionio_tpu.utils.logship import attach_log_shipper

            self._log_shipper = attach_log_shipper(
                log_url, logging.getLogger("predictionio_tpu")
            )
        return self.port

    def stop(self) -> None:
        shipper = getattr(self, "_log_shipper", None)
        if shipper is not None:
            import logging

            logging.getLogger("predictionio_tpu").removeHandler(shipper)
            shipper.close()
            self._log_shipper = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    def serve_forever(self) -> None:
        self.start()
        assert self._thread is not None
        self._thread.join()
