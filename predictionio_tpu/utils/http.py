"""Shared threaded-HTTP plumbing for the framework's server processes.

Both the Event Server (data/api/server.py) and the deploy query server
(workflow/server.py) are stdlib ThreadingHTTPServer processes with the
same needs: JSON responses, eager body drain (an unread POST body desyncs
HTTP/1.1 keep-alive — the next request parses it as a request line),
routed logging, and a start/stop/port lifecycle."""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import predictionio_tpu.obs.registry as _obs_registry
import predictionio_tpu.obs.spans as _obs_spans
import predictionio_tpu.obs.tracing as _obs_tracing
import predictionio_tpu.resilience.deadline as _deadline

log = logging.getLogger(__name__)


class HttpError(Exception):
    """Raise inside a handler to produce a JSON error response."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class JsonHandler(BaseHTTPRequestHandler):
    """Base handler: drains the body before dispatch, JSON helpers, and
    the observability middleware — every request is timed, tagged with a
    trace id (`X-Request-ID` from the client or generated here), counted
    into the owning server's MetricsRegistry
    (`http_requests_total{server,method,path,status}` +
    `http_request_seconds{server,path}`), and access-logged as one JSON
    record. Servers opt in by setting `metrics` (a MetricsRegistry) and
    `metrics_label` on their ThreadedServer; trace ids propagate
    regardless."""

    protocol_version = "HTTP/1.1"
    # status line / headers / body are separate socket writes: with
    # Nagle on, the later writes wait for the peer's delayed ACK — a
    # flat ~40 ms stall per response (measured on the storage RPC path;
    # applies equally to event-server and query-server replies)
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # route through logging, not stderr
        log.debug("%s " + fmt, self.address_string(), *args)

    def handle_one_request(self):
        self._raw_body = b""
        self._trace_token = None
        self._span_token = None
        self._deadline_token = None
        try:
            super().handle_one_request()
        finally:
            # keep-alive reuses this thread: clear the request's trace id,
            # span context and deadline so the next request (or idle
            # logging) can't inherit them
            if self._deadline_token is not None:
                _deadline.reset(self._deadline_token)
                self._deadline_token = None
            if self._span_token is not None:
                _obs_spans.reset_current_span(self._span_token)
                self._span_token = None
            if self._trace_token is not None:
                _obs_tracing.reset_trace_id(self._trace_token)
                self._trace_token = None

    # client-supplied ids are echoed into RESPONSE headers: restrict to a
    # safe charset/length (a folded header would otherwise smuggle CRLF
    # bytes through http.client's parser into the response — header
    # injection / keep-alive desync)
    _TRACE_ID_RE = re.compile(r"[A-Za-z0-9._:-]{1,128}")

    def parse_request(self):
        ok = super().parse_request()
        if ok:
            self._t0 = time.perf_counter()
            self._start_wall = time.time()
            self._metrics_recorded = False
            tid = self.headers.get("X-Request-ID") or ""
            if not self._TRACE_ID_RE.fullmatch(tid):
                tid = _obs_tracing.new_request_id()
            self._trace_id = tid
            self._trace_token = _obs_tracing.set_trace_id(tid)
            # span context: X-Parent-Span carries the CALLER's span id
            # across the process boundary, so this request's root server
            # span parents under the remote client span (same id charset
            # rules as the trace id — both echo into downstream headers)
            psp = self.headers.get("X-Parent-Span") or ""
            self._parent_span = psp if self._TRACE_ID_RE.fullmatch(psp) else None
            self._span_id = _obs_spans.new_span_id()
            self._span_token = _obs_spans.set_current_span(self._span_id)
            # deadline propagation (ISSUE 4): X-PIO-Deadline carries the
            # caller's REMAINING budget in ms; it becomes this request's
            # ambient deadline so handlers can shed expired work and
            # downstream RPC clients shrink their retry budgets to fit
            dl = _deadline.parse_header(self.headers.get(_deadline.HEADER))
            if dl is not None:
                self._deadline_token = _deadline.set_deadline(dl)
        return ok

    # -- observability middleware ------------------------------------------
    def _route_label(self, path: str) -> str:
        """Collapse per-entity path segments so metric label cardinality
        stays bounded (/events/<id>.json → /events/{id}.json; admin's
        /cmd/app/<name>[/data] → /cmd/app/{name}[/data])."""
        parts = path.split("/")
        if len(parts) >= 3 and parts[1] in ("jobs", "models", "tenants"):
            # lifecycle + tenancy control planes: job/version/tenant ids
            # are unbounded (and /tenants/{id}/queries.json is the
            # serving hot path — one tenant, one label child)
            parts[2] = "{id}"
        elif len(parts) >= 3 and parts[1] in ("events", "engine_instances"):
            for suffix in (".json", ".html"):
                if parts[2].endswith(suffix):
                    parts[2] = "{id}" + suffix
                    break
            else:
                parts[2] = "{id}"
        elif (
            len(parts) >= 4
            and parts[1] == "cmd"
            and parts[2] in ("app", "channel", "accesskey")
        ):
            # per-entity admin routes: the name/id segment is
            # client-chosen — every distinct app would otherwise mint a
            # metric child per delete/show
            parts[3] = "{name}"
        return "/".join(parts)

    def _record_request(self, status: int) -> None:
        if getattr(self, "_metrics_recorded", True):
            return
        self._metrics_recorded = True
        duration = time.perf_counter() - self._t0
        label = getattr(self.server, "metrics_label", "http")
        real_path = self.path.split("?")[0].rstrip("/") or "/"
        route = self._route_label(real_path)
        # unmatched routes share ONE metric label value: an internet-facing
        # port gets scanned with unbounded distinct paths, and each would
        # otherwise mint a fresh counter+histogram child. The access log
        # and the span keep the real path — logs and the bounded trace
        # store have no cardinality constraint, and per-entity debugging
        # needs to see WHICH entity the request touched.
        metric_path = "(unmatched)" if status == 404 else route
        registry = getattr(self.server, "metrics", None)
        if registry is not None:
            registry.counter(
                "http_requests_total",
                "HTTP requests served",
                # label-bound: path through _route_label's table
                # (cardinality-guard test), method/status from HTTP
                ("server", "method", "path", "status"),
            ).inc(
                server=label, method=self.command,
                path=metric_path, status=status,
            )
            registry.histogram(
                "http_request_seconds",
                "request wall time, request line to response written",
                ("server", "path"),  # label-bound: _route_label table
            ).observe(duration, server=label, path=metric_path)
        _obs_tracing.log_access(
            server=label,
            method=self.command,
            path=real_path,
            status=status,
            duration_s=duration,
            trace_id=getattr(self, "_trace_id", None),
        )
        # root server span: parents under the caller's span when the
        # request came with X-Parent-Span (cross-process), else starts
        # the trace. finalize=True runs the tail-sampling decision over
        # every span this request's handling recorded.
        attrs = {
            "server": label,
            "method": self.command,
            "path": real_path,
            "status": status,
        }
        if route != real_path:
            attrs["route"] = route  # the metric label this request fed
        # identity attrs the owning process declared (ISSUE 16): a
        # replica sets {"replica": id} here so its server spans stay
        # attributable after the collector stitches them into a fleet
        # trace alongside other replicas' identically-named spans
        extra = getattr(self.server, "span_attrs", None)
        if extra:
            attrs.update(extra)
        _obs_spans.get_default_recorder().record(
            _obs_spans.Span(
                trace_id=self._trace_id,
                span_id=self._span_id,
                parent_span_id=getattr(self, "_parent_span", None),
                name="server.request",
                start=getattr(self, "_start_wall", time.time()),
                duration=duration,
                attrs=attrs,
                error=status >= 500,
            ),
            finalize=True,
        )

    def _serve_metrics(self) -> None:
        """GET /metrics: this server's registry merged with the
        process-default one (train-stage metrics live there)."""
        text = _obs_registry.render_merged(
            getattr(self.server, "metrics", None),
            _obs_registry.get_default_registry(),
        )
        self._respond(200, text, "text/plain; version=0.0.4")

    def _serve_debug_traces(self) -> None:
        """GET /debug/traces — recent retained traces (tail-sampled);
        `?trace_id=` for one trace's full span list, plus
        `&format=perfetto` for Chrome trace-event JSON of it;
        `?min_duration_ms=` / `?error=1` filter the summary listing so
        operators pull only slow/errored traces without exporting the
        whole store. Every JsonHandler server mounts this, same as
        /metrics."""
        from urllib.parse import parse_qsl, urlsplit

        qs = dict(parse_qsl(urlsplit(self.path).query))
        recorder = _obs_spans.get_default_recorder()
        if qs.get("spans") in ("1", "true", "yes"):
            # raw recent-span dump (pre-sampling) for the fleet trace
            # collector: `?spans=1[&since=<epoch-s>]`
            try:
                since = float(qs.get("since", 0) or 0)
            except ValueError:
                since = 0.0
            self._respond(200, {
                "now": time.time(),
                "spans": [s.to_dict() for s in recorder.recent(since)],
            })
            return
        if qs.get("fleet") in ("1", "true", "yes"):
            self._serve_fleet_traces(qs)
            return
        capture_id = qs.get("capture")
        if capture_id:
            cap = recorder.capture_status(capture_id)
            if cap is None:
                self._respond(
                    404, {"message": f"no capture {capture_id}"}
                )
                return
            self._respond(200, cap)
            return
        trace_id = qs.get("trace_id")
        if qs.get("format") == "perfetto":
            # with trace_id: that one trace; without: every retained one
            export = recorder.perfetto_export(trace_id)
            if trace_id and not export["traceEvents"]:
                self._respond(404, {"message": f"no trace {trace_id}"})
                return
            self._respond(200, export)
            return
        if trace_id:
            spans = recorder.get_trace(trace_id)
            if not spans:
                self._respond(404, {"message": f"no trace {trace_id}"})
                return
            self._respond(200, {
                "trace_id": trace_id,
                "spans": [s.to_dict() for s in spans],
            })
            return
        try:
            limit = int(qs.get("limit", "50"))
        except ValueError:
            limit = 50
        try:
            min_ms = float(qs.get("min_duration_ms", 0) or 0)
        except ValueError:
            min_ms = 0.0
        error_only = qs.get("error") in ("1", "true", "yes")
        if min_ms > 0 or error_only:
            # filter over the FULL store, then apply the limit — the
            # newest N unfiltered rows would hide older slow/errored
            # traces, which are exactly what the filters exist to find
            summaries = [
                s for s in recorder.summaries(limit=0)
                if s["duration_ms"] >= min_ms
                and (not error_only or s["error"])
            ]
            if limit:
                summaries = summaries[:limit]
        else:
            summaries = recorder.summaries(limit=limit)
        self._respond(200, {
            "traces": summaries,
            "sampling": recorder.config(),
        })

    def _serve_fleet_traces(self, qs: dict) -> None:
        """`GET /debug/traces?fleet=1` — the ASSEMBLED cross-process
        traces from this process's fleet trace collector (ISSUE 16):
        summaries by default, `&trace_id=` for one stitched tree,
        `&format=perfetto` for the Chrome trace-event export. 503 on
        processes that don't run a collector (replicas, bare servers)."""
        from predictionio_tpu.obs.monitor import get_monitor

        collector = get_monitor().collector
        if collector is None:
            self._respond(503, {
                "message": "no fleet trace collector runs in this "
                           "process (gateways, dashboards and `pio "
                           "monitor` own one)",
            })
            return
        trace_id = qs.get("trace_id")
        if qs.get("format") == "perfetto":
            export = collector.perfetto_export(trace_id)
            if trace_id and not export["traceEvents"]:
                self._respond(404, {"message": f"no trace {trace_id}"})
                return
            self._respond(200, export)
            return
        if trace_id:
            spans = collector.get_trace(trace_id)
            if not spans:
                self._respond(404, {"message": f"no trace {trace_id}"})
                return
            self._respond(200, {"trace_id": trace_id, "spans": spans})
            return
        try:
            limit = int(qs.get("limit", "50"))
        except ValueError:
            limit = 50
        self._respond(200, {
            "traces": collector.summaries(limit=limit),
            "collector": collector.status(),
        })

    def _serve_debug_tsdb(self) -> None:
        """GET /debug/tsdb — the in-process time-series history (ISSUE
        8): no params lists series; `?name=` returns points, with
        optional `labels=k:v,...`, `window_s=`, and
        `agg=rate|increase|quantile&q=`. Every JsonHandler server
        mounts this next to /metrics."""
        from urllib.parse import parse_qsl, urlsplit

        from predictionio_tpu.obs.monitor import get_monitor

        qs = dict(parse_qsl(urlsplit(self.path).query))
        self._respond(200, get_monitor().tsdb_payload(qs))

    def _serve_alerts(self) -> None:
        """GET /alerts — the SLO engine's alert states (ISSUE 8):
        pending/firing/resolved per declared SLO, with live burn
        rates. Mounted on the query, admin, and dashboard servers."""
        from predictionio_tpu.obs.monitor import get_monitor

        self._respond(200, get_monitor().alerts_payload())

    def _serve_traces_capture(self) -> None:
        """POST /debug/traces/capture {"n": N} — arm the span recorder
        so the dispatcher force-samples the next N batches' traces
        regardless of PIO_TRACE_SAMPLE (ISSUE 8 satellite, the PR-3
        follow-up). Returns a capture id for
        `GET /debug/traces?capture=<id>`. The query server routes this
        — it owns the dispatcher that consumes the arm."""
        body = self._json_body()
        n = 1
        if isinstance(body, dict) and "n" in body:
            try:
                n = int(body["n"])
            except (TypeError, ValueError):
                raise HttpError(400, "'n' must be an integer")
        if not 1 <= n <= 64:
            raise HttpError(400, "'n' must be in [1, 64]")
        capture_id = _obs_spans.get_default_recorder().arm_capture(n)
        self._respond(200, {"capture": capture_id, "batches": n})

    def _serve_debug_profile(self) -> None:
        """GET /debug/profile — the device-profiling report: per-
        executable XLA cost/memory analysis, derived MFU / HBM roofline
        numbers, and padding-waste accounting. Empty-but-valid on
        processes that never loaded jax."""
        from predictionio_tpu.obs import devprof as _devprof

        self._respond(200, _devprof.report())

    def _serve_profile_capture(self) -> None:
        """POST /debug/profile/capture — on-demand jax.profiler trace
        window. Guarded: disabled (403) unless the operator set
        PIO_PROFILE_CAPTURE_DIR on the server process; 409 when jax is
        not loaded here or a capture is already running. Body:
        {"seconds": 2.0} (bounded to (0, 60])."""
        import time as _time

        from predictionio_tpu.obs import devprof as _devprof
        from predictionio_tpu.utils.env import env_path as _env_path

        cap_dir = _env_path("PIO_PROFILE_CAPTURE_DIR")
        if not cap_dir:
            self._respond(403, {
                "message": "profiler capture is disabled: set "
                           "PIO_PROFILE_CAPTURE_DIR on this server to "
                           "enable it"
            })
            return
        body = self._json_body()
        seconds = 2.0
        if isinstance(body, dict) and "seconds" in body:
            try:
                seconds = float(body["seconds"])
            except (TypeError, ValueError):
                raise HttpError(400, "'seconds' must be a number")
        out_dir = _os.path.join(
            cap_dir, _time.strftime("capture-%Y%m%d-%H%M%S")
        )
        try:
            result = _devprof.capture_trace(out_dir, seconds)
        except ValueError as e:
            raise HttpError(400, str(e))
        except RuntimeError as e:
            raise HttpError(409, str(e))
        self._respond(200, result)

    def _serve_debug_faults(self) -> None:
        """GET /debug/faults — the process's active fault specs. Every
        JsonHandler server mounts this next to /metrics (read-only, so
        ungated; mutation goes through the gated POST below)."""
        from predictionio_tpu.resilience import faults as _faults

        self._respond(200, {"faults": _faults.specs()})

    def _serve_debug_faults_set(self) -> None:
        """POST /debug/faults — install/clear fault specs at runtime.
        Guarded like /debug/profile/capture: 403 unless the operator set
        PIO_FAULTS_ADMIN=1 on the server process. Body:
        {"set": "point:mode:prob[:param][,...]", "seed": N} and/or
        {"clear": "point" | true}."""
        from predictionio_tpu.resilience import faults as _faults
        from predictionio_tpu.utils.env import env_flag as _env_flag

        if not _env_flag("PIO_FAULTS_ADMIN"):
            self._respond(403, {
                "message": "fault-injection admin is disabled: set "
                           "PIO_FAULTS_ADMIN=1 on this server to enable it"
            })
            return
        body = self._json_body()
        if not isinstance(body, dict):
            raise HttpError(400, "fault admin body must be a JSON object")
        # validate the whole request BEFORE mutating anything: a
        # malformed `set` must 400 without having executed the `clear`
        spec_text = body.get("set")
        specs = []
        if spec_text:
            seed = body.get("seed")
            try:
                specs = _faults.parse_specs(
                    spec_text, int(seed) if seed is not None else None
                )
            except (_faults.FaultSpecError, TypeError, ValueError) as e:
                raise HttpError(400, str(e))
        clear = body.get("clear")
        if clear is True:
            _faults.clear()
        elif isinstance(clear, str):
            _faults.clear(clear)
        for spec in specs:
            _faults.install(spec)
        self._respond(200, {"faults": _faults.specs()})

    def _serve_telemetry_push(self) -> None:
        """POST /telemetry/push — ingest a pushed telemetry payload from
        an ephemeral process (ISSUE 17). Guarded like /debug/faults: 403
        unless the operator set PIO_PUSH_INGEST=1 on this server, so an
        internet-facing query server can't be fed fabricated series.
        Body is the :mod:`obs.monitor.push` payload (v1: series + spans
        + optional devprof report); lands in the process monitor's TSDB
        tagged ``instance``/``job_id`` and in its trace collector."""
        from predictionio_tpu.obs.monitor import push as _push
        from predictionio_tpu.utils.env import env_flag as _env_flag

        if not _env_flag("PIO_PUSH_INGEST"):
            self._respond(403, {
                "message": "telemetry push ingest is disabled: set "
                           "PIO_PUSH_INGEST=1 on this server to enable it"
            })
            return
        body = self._json_body()
        try:
            result = _push.ingest(
                body, token=self.headers.get(_push.TOKEN_HEADER)
            )
        except _push.PushAuthError as e:
            raise HttpError(403, str(e))
        except _push.PushError as e:
            raise HttpError(400, str(e))
        self._respond(200, result)

    def _drain_body(self) -> None:
        length = int(self.headers.get("Content-Length") or 0)
        self._raw_body = self.rfile.read(length) if length else b""

    def _body(self) -> bytes:
        return self._raw_body

    def _json_body(self) -> Any:
        try:
            return json.loads(self._body().decode() or "null")
        except json.JSONDecodeError as e:
            raise HttpError(400, f"invalid JSON: {e}")

    def _respond(
        self, status: int, body: Any, content_type: str = "application/json",
        headers: Optional[dict] = None,
    ) -> None:
        data = (
            body.encode() if isinstance(body, str) else json.dumps(body).encode()
        )
        self.send_response(status)
        self.send_header("Content-Type", f"{content_type}; charset=UTF-8")
        self.send_header("Content-Length", str(len(data)))
        trace_id = getattr(self, "_trace_id", None)
        if trace_id:
            self.send_header("X-Request-ID", trace_id)
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        # account BEFORE the body write: the moment the client sees the
        # last byte it may issue a follow-up scrape, and the counter for
        # THIS request must already be visible to it (recording after
        # the write loses that race — observed as a missing
        # http_requests_total child on single-vCPU hosts). The final
        # body-write syscall falls outside the measured duration;
        # headers are already on the wire by this point.
        self._record_request(status)
        self.wfile.write(data)


class ThreadedServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    # socketserver's default listen backlog of 5 drops connections under
    # concurrent load (micro-batched serving expects bursts of clients)
    request_queue_size = 128


class ServerProcess:
    """start/stop/port lifecycle shared by server processes. Subclasses
    implement `_make_server() -> ThreadedServer` and set `_name`."""

    _name = "http-server"

    def __init__(self):
        self._server: Optional[ThreadedServer] = None
        self._thread: Optional[threading.Thread] = None
        self._monitor_token: Optional[int] = None

    def _make_server(self) -> ThreadedServer:
        raise NotImplementedError

    @property
    def port(self) -> int:
        assert self._server is not None, "server not started"
        return self._server.server_address[1]

    def start(self) -> int:
        self._server = self._make_server()
        self._thread = threading.Thread(
            target=self._server.serve_forever, name=self._name, daemon=True
        )
        self._thread.start()
        # monitoring plane (ISSUE 8): register this server's registry
        # with the process monitor — the TSDB sampler starts with the
        # first attached server and joins when the last one stops
        registry = getattr(self._server, "metrics", None)
        if registry is not None and self._monitor_token is None:
            from predictionio_tpu.obs.monitor import get_monitor

            self._monitor_token = get_monitor().attach(
                getattr(self._server, "metrics_label", self._name),
                registry,
            )
        # remote log shipping (reference CreateServer.scala:441-452
        # --log-url): any server whose config carries log_url ships the
        # framework's log records to the collector
        log_url = getattr(getattr(self, "config", None), "log_url", None)
        if log_url and getattr(self, "_log_shipper", None) is None:
            import logging

            from predictionio_tpu.utils.logship import attach_log_shipper

            self._log_shipper = attach_log_shipper(
                log_url, logging.getLogger("predictionio_tpu")
            )
        return self.port

    def stop(self) -> None:
        if self._monitor_token is not None:
            from predictionio_tpu.obs.monitor import get_monitor

            get_monitor().detach(self._monitor_token)
            self._monitor_token = None
        shipper = getattr(self, "_log_shipper", None)
        if shipper is not None:
            import logging

            logging.getLogger("predictionio_tpu").removeHandler(shipper)
            shipper.close()
            self._log_shipper = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    def serve_forever(self) -> None:
        self.start()
        assert self._thread is not None
        self._thread.join()
