"""Shared host-side utilities."""
