"""Environment-variable parsing helpers shared across subsystems."""

import logging
import os

log = logging.getLogger(__name__)


def env_float(name: str, default: float) -> float:
    """Float env knob: missing/empty → default; malformed → default
    with a warning (a typo'd knob must not silently change behavior)."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return float(default)
    try:
        return float(raw)
    except ValueError:
        log.warning("ignoring malformed %s=%r", name, raw)
        return float(default)
