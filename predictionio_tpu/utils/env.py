"""Environment-knob registry + typed parsers (ISSUE 12).

Every ``PIO_*`` environment variable the framework reads is declared
here ONCE — name, type, default, one-line doc — and read ONLY through
the typed parsers below. The `pio lint` env-knob checker
(analysis/check_env.py) fails any raw ``os.environ`` read of a
``PIO_*`` key elsewhere in the package, and any parser call against an
undeclared name raises at call time, so the registry can never go
stale in either direction. ``pio lint --knobs`` renders this registry
as the README "Configuration knobs" table (CI diffs it for freshness).

Parsers accept an optional ``env`` mapping so call sites that operate
on captured child/config environments (rollout config, fault specs,
fleet coords) parse through the same single grammar: missing/empty →
default; malformed → default with a warning (a typo'd knob must not
silently change behavior — PR-6 round 6 discipline, now universal).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Any, Mapping, Optional

log = logging.getLogger(__name__)

_TRUTHY = ("1", "true", "yes", "on")
_FALSY = ("0", "false", "no", "off")


@dataclass(frozen=True)
class Knob:
    """One declared environment knob."""

    name: str
    type: str  # str|path|int|float|bool|flag|enum|json|spec|prefix
    default: Any
    doc: str
    prefix: bool = False  # name is a family prefix (dynamic suffixes)


KNOBS: dict[str, Knob] = {}


def _k(name: str, type_: str, default: Any, doc: str) -> None:
    KNOBS[name] = Knob(name, type_, default, doc, prefix=type_ == "prefix")


# -- storage / data plane ----------------------------------------------------
_k("PIO_FS_BASEDIR", "path", "~/.pio_store",
   "Base directory for sqlite/localfs/docfs storage and pickled models.")
_k("PIO_STORAGE_SOURCES_", "prefix", None,
   "Storage source family: PIO_STORAGE_SOURCES_<NAME>_TYPE plus "
   "per-source keys (PATH, HOSTS, PORTS, ...) — reference pio-env.sh.")
_k("PIO_STORAGE_REPOSITORIES_", "prefix", None,
   "Repository bindings: PIO_STORAGE_REPOSITORIES_<REPO>_SOURCE for "
   "METADATA / EVENTDATA / MODELDATA.")
_k("PIO_STORAGE_RETRY_ATTEMPTS", "int", 3,
   "Storage RPC retry attempts (per-source RETRY_ATTEMPTS overrides).")
_k("PIO_STORAGE_RETRY_BASE_DELAY", "float", 0.05,
   "Base delay (s) of the storage RPC exponential backoff.")
_k("PIO_BREAKER_THRESHOLD", "int", 5,
   "Consecutive storage failures before the circuit breaker opens.")
_k("PIO_BREAKER_COOLDOWN", "float", 10.0,
   "Seconds an open storage breaker waits before its recovery probe.")
_k("PIO_WAL_DIR", "path", "~/.predictionio_tpu/event-wal",
   "Event-server WAL spill directory for storage-outage ingestion.")
_k("PIO_TEST_POSTGRES_DSN", "str", "",
   "DSN enabling the live-postgres storage contract tests.")

# -- serving / rollout -------------------------------------------------------
_k("PIO_ROLLOUT_FRACTION", "float", 0.1,
   "Sticky fraction of traffic routed to a canary candidate.")
_k("PIO_ROLLOUT_WINDOW_S", "float", 30.0,
   "Sliding stats window (s) the rollout verdict compares over.")
_k("PIO_ROLLOUT_INTERVAL_S", "float", 1.0,
   "Seconds between rollout verdict ticks.")
_k("PIO_ROLLOUT_MIN_REQUESTS", "int", 20,
   "Candidate samples required before the verdict engages.")
_k("PIO_ROLLOUT_MAX_ERROR_DELTA", "float", 0.05,
   "Candidate-minus-live error-rate delta that forces rollback.")
_k("PIO_ROLLOUT_MAX_P99_RATIO", "float", 3.0,
   "Candidate/live p99 latency ratio that forces rollback.")
_k("PIO_ROLLOUT_BAKE_S", "float", 60.0,
   "Healthy bake time (s) before a canary auto-promotes.")
_k("PIO_ROLLOUT_SHADOW", "bool", False,
   "Shadow mode: mirror live traffic to the candidate and compare.")
_k("PIO_ROLLOUT_MIN_AGREEMENT", "float", 0.9,
   "Minimum shadow result-agreement fraction (rollback below).")
_k("PIO_ROLLOUT_PROXY", "flag", "",
   "Set 1 to enable the admin server's /rollout proxy endpoints (the "
   "target query-server URL rides each request body).")
_k("PIO_SERVE_HBM_BYTES", "float", None,
   "Per-device HBM budget (bytes) gating sharded serving residency.")

# -- tenancy -----------------------------------------------------------------
_k("PIO_TENANT_CACHE_SIZE", "int", 4,
   "Resident model-cache entries per query server (LRU beyond).")
_k("PIO_TENANT_CACHE_HBM_BYTES", "float", 0,
   "Model-cache budget in measured device bytes (0 = count-based).")
_k("PIO_TENANT_REFRESH_S", "float", 5.0,
   "TTL (s) of the admission path's cached tenant records.")
_k("PIO_TENANT_SYNC_S", "float", 10.0,
   "Period (s) of the mux background sync (refresh/rollouts/prefetch).")
_k("PIO_TENANT_METRIC_MAX", "int", 50,
   "Distinct tenant label values before metrics collapse to (other).")

# -- gateway / replicated serving (ISSUE 15) ---------------------------------
_k("PIO_GATEWAY_SYNC_S", "float", 0.5,
   "Seconds between gateway discovery/health sync passes.")
_k("PIO_GATEWAY_STALE_S", "float", 3.0,
   "Replica heartbeat age (s) past which the gateway stops routing "
   "to it.")
_k("PIO_GATEWAY_HEDGE", "bool", True,
   "Hedged queries: speculate to the next replica at the p95 mark.")
_k("PIO_GATEWAY_HEDGE_MIN_MS", "float", 25.0,
   "Floor (ms) on the hedge delay while a replica's latency window "
   "is cold.")
_k("PIO_GATEWAY_LOAD_FACTOR", "float", 1.5,
   "Bounded-load consistent hashing: skip replicas over factor x the "
   "mean in-flight load.")
_k("PIO_GATEWAY_VNODES", "int", 64,
   "Virtual nodes per replica on the consistent-hash ring.")
_k("PIO_REPLICA_HEARTBEAT_S", "float", 1.0,
   "Seconds between a replica's registry heartbeats.")

# -- online learning ---------------------------------------------------------
_k("PIO_ONLINE_TICK_S", "float", 0.5,
   "Seconds between online fold-in consumer ticks.")
_k("PIO_ONLINE_DRIFT_THRESHOLD", "float", 1.0,
   "Score-drift score that pauses fold-in and raises the alert.")
_k("PIO_ONLINE_DRIFT_COOLDOWN_S", "float", 0.0,
   "Cool-down (s) after a completed retrain before a drift-paused "
   "consumer re-probes drift once and auto-resumes if clean; 0 keeps "
   "the immediate-resume-on-retrain behaviour.")

# -- event-store replication -------------------------------------------------
_k("PIO_REPL_FOLLOWERS", "str", "",
   "Comma-separated host:port follower storage daemons the primary's "
   "SegmentShipper streams segments and the WAL tail to. Empty "
   "disables replication.")
_k("PIO_REPL_MIN_ACKS", "int", 0,
   "Synchronous-replication floor: insert_batch acks only after this "
   "many followers applied the WAL frame (0 = async shipping only).")
_k("PIO_REPL_SHIP_INTERVAL_S", "float", 0.25,
   "Seconds between background SegmentShipper passes (segment sync + "
   "WAL-tail catch-up + tombstone sync).")
_k("PIO_REPL_WAL_BATCH", "int", 512,
   "Max live-tail rows per replication WAL frame on catch-up passes.")
_k("PIO_REPL_MAX_LAG_REVISIONS", "int", 1000,
   "Replication-lag budget (revisions) used by the replication_lag "
   "SLO preset.")
_k("PIO_REPL_EPOCH", "int", 1,
   "Replication epoch a primary storage daemon stamps into shipped "
   "frames at boot. Normally 1 for the original primary; a promoted "
   "follower's epoch comes from the election generation instead.")

# -- fleet -------------------------------------------------------------------
_k("PIO_FLEET_COORDINATOR", "str", "",
   "host:port of process 0 for jax.distributed multi-host init.")
_k("PIO_FLEET_NUM_PROCESSES", "int", 1,
   "Total process count of the fleet's jax.distributed job.")
_k("PIO_FLEET_PROCESS_ID", "int", 0,
   "This process's index within the jax.distributed job.")

# -- observability: tracing / metrics / profiling ----------------------------
_k("PIO_TRACE_SAMPLE", "float", 0.1,
   "Tail-sampling keep probability for non-error, non-slow traces.")
_k("PIO_TRACE_MAX", "int", 256,
   "Retained-trace cap of the in-process span recorder.")
_k("PIO_TRACE_SLOW_MS", "float", 250.0,
   "Root-span duration (ms) above which a trace is always kept.")
_k("PIO_DEVPROF", "flag", "1",
   "Device profiling layer; 0 disables every instrument() wrapper.")
_k("PIO_DEVPROF_MEMORY", "flag", "",
   "Force memory_analysis on (1) / off (0) for all instrumented jits.")
_k("PIO_PEAK_FLOPS", "float", None,
   "Peak device FLOP/s override pinning every dtype column (MFU).")
_k("PIO_PEAK_FLOPS_INT8", "float", None,
   "Peak int8 FLOP/s override for dtype-aware MFU.")
_k("PIO_PEAK_FLOPS_F32", "float", None,
   "Peak f32 FLOP/s override for dtype-aware MFU.")
_k("PIO_PEAK_HBM_BPS", "float", None,
   "Peak HBM bandwidth (bytes/s) override for %-of-roof.")
_k("PIO_PROFILE_CAPTURE_DIR", "path", "",
   "Directory enabling POST /debug/profile/capture jax.profiler dumps.")

# -- fleet observability (ISSUE 16) -----------------------------------------
_k("PIO_TRACE_COLLECT", "flag", "1",
   "Fleet trace collector; 0 disables /debug/traces polling even when "
   "scrape targets exist.")
_k("PIO_TRACE_COLLECT_INTERVAL_S", "float", 2.0,
   "Seconds between trace-collector /debug/traces polls.")
_k("PIO_TRACE_COLLECT_HOLD_S", "float", 15.0,
   "Seconds an orphan span fragment (no root seen yet) is held for "
   "late stitching before it expires.")
_k("PIO_TRACE_COLLECT_MAX", "int", 256,
   "Assembled cross-process traces retained by the collector.")
_k("PIO_TRACE_EXEMPLARS", "int", 4,
   "Slowest (trace-id, value) exemplars retained per histogram family "
   "(0 disables exemplar capture).")
_k("PIO_RECORDING_RULES", "json", "",
   "Recording rules: JSON array of rule objects, or @/path/to/rules "
   "(auto-derived per-SLO rules are added on top).")
_k("PIO_TENANT_SLO_PRESETS", "flag", "",
   "Set 1 to auto-derive per-tenant availability/latency SLO presets "
   "from tenant records at mux attach.")
_k("PIO_WORKER_METRICS_URL", "str", "",
   "Metrics URL a fleet worker advertises on its registry record so "
   "`pio fleet status` can scrape per-worker device gauges.")

# -- push telemetry (ISSUE 17) ----------------------------------------------
_k("PIO_PUSH_URL", "str", "",
   "Base URL of a push-telemetry ingest (POST /telemetry/push); set in "
   "ephemeral processes (train workers, fleet workers) to ship spooled "
   "metrics/spans/devprof. Empty disables shipping.")
_k("PIO_PUSH_SPOOL", "path", "",
   "Local fsync'd spool directory for the telemetry shipper; the train "
   "scheduler defaults each child to <log_dir>/<job>.spool so orphaned "
   "spools of killed workers are shipped by the supervisor.")
_k("PIO_PUSH_INGEST", "flag", "",
   "Set 1 to enable the guarded POST /telemetry/push ingest endpoint "
   "on this server (dashboard/monitor).")
_k("PIO_PUSH_INTERVAL_S", "float", 10.0,
   "Seconds between telemetry-shipper spool+ship passes.")
_k("PIO_PUSH_DEADLINE_S", "float", 5.0,
   "Wall-clock budget (s) one telemetry ship pass may spend retrying.")
_k("PIO_PUSH_SPOOL_MAX_BYTES", "int", 8 * 1024 * 1024,
   "Telemetry spool directory size bound; oldest spool files drop "
   "first.")
_k("PIO_SCRAPE_BACKOFF_MAX_S", "float", 60.0,
   "Cap (s) on the fleet scraper's exponential backoff for down "
   "targets (up{instance}=0 still records every tick).")
_k("PIO_PUSH_TOKEN", "str", "",
   "Shared secret for per-instance push-ingest auth: shippers send "
   "X-PIO-Push-Token = HMAC-SHA256(secret, instance) and the ingest "
   "rejects payloads whose token does not match their instance label. "
   "Empty disables auth.")
_k("PIO_PUSH_SPAN_RATE", "float", 50.0,
   "Per-instance pushed-span admission budget (spans/s token bucket) "
   "at the telemetry ingest; overflow is dropped and counted in "
   "telemetry_push_dropped_total{kind=span}.")
_k("PIO_PUSH_SPAN_BURST", "float", 200.0,
   "Burst capacity (spans) of the per-instance pushed-span bucket.")

# -- monitoring plane --------------------------------------------------------
_k("PIO_TSDB", "flag", "1",
   "In-process monitoring plane; 0 disables sampler/TSDB/SLO engine.")
_k("PIO_TSDB_POINTS", "int", 720,
   "Ring-buffer points retained per TSDB series.")
_k("PIO_TSDB_MAX_SERIES", "int", 4096,
   "TSDB series-cardinality cap (adds beyond are dropped+counted).")
_k("PIO_TSDB_INTERVAL_S", "float", 5.0,
   "Seconds between metrics-sampler snapshots into the TSDB.")
_k("PIO_SLO_INTERVAL_S", "float", 15.0,
   "Seconds between SLO burn-rate evaluation passes.")
_k("PIO_SLOS", "json", "",
   "SLO specs: JSON array of spec objects, or @/path/to/slos.json.")
_k("PIO_MONITOR_TARGETS", "str", "",
   "Comma-separated name=url /metrics scrape targets for the fleet "
   "scraper (pio monitor, dashboard).")
_k("PIO_SCRAPE_INTERVAL_S", "float", 10.0,
   "Seconds between fleet-scraper /metrics polls.")
_k("PIO_TSDB_SNAPSHOT", "path", "",
   "Path persisting the TSDB rings across restarts (empty = off).")
_k("PIO_TSDB_SNAPSHOT_INTERVAL_S", "float", 60.0,
   "Seconds between TSDB snapshot writes.")
_k("PIO_TSDB_DIR", "path", "",
   "Directory of the durable on-disk TSDB tier (fsync'd WAL + sealed "
   "columnar blocks + 5m/1h downsampled tiers). Empty keeps history "
   "memory-only; set, it supersedes PIO_TSDB_SNAPSHOT.")
_k("PIO_TSDB_FLUSH_S", "float", 2.0,
   "Seconds between durable-TSDB WAL flush+fsync passes.")
_k("PIO_TSDB_SEAL_POINTS", "int", 50000,
   "Points in the active WAL segment that trigger sealing it into an "
   "immutable columnar block.")
_k("PIO_TSDB_SEAL_AGE_S", "float", 300.0,
   "Age (s) of a non-empty active WAL segment that triggers sealing.")
_k("PIO_TSDB_COMPACT_S", "float", 30.0,
   "Seconds between durable-TSDB compactor passes (downsampling + "
   "per-tier retention).")
_k("PIO_TSDB_CKPT_POINTS", "int", 50000,
   "Flushed WAL points between durable-TSDB replay-checkpoint writes; "
   "attach replays only WAL bytes past the checkpoint (0 disables "
   "checkpointing).")
_k("PIO_TSDB_RETENTION_RAW", "float", 6 * 3600.0,
   "Retention (s) of raw-resolution durable blocks.")
_k("PIO_TSDB_RETENTION_5M", "float", 3 * 86400.0,
   "Retention (s) of the 5-minute downsampled tier.")
_k("PIO_TSDB_RETENTION_1H", "float", 14 * 86400.0,
   "Retention (s) of the 1-hour downsampled tier.")
_k("PIO_ALERT_WEBHOOK", "str", "",
   "URL POSTed one JSON alert per SLO/external alert transition.")
_k("PIO_ALERT_EXEC", "str", "",
   "Command run per alert transition (JSON on stdin + $PIO_ALERT_JSON).")
_k("PIO_ALERT_JSON", "str", "",
   "Set BY the exec alert sink for its child: the alert payload.")

# -- kernels / numerics ------------------------------------------------------
_k("PIO_DENSE_ALS", "flag", "",
   "Dense ALS solver: 1 forces on, 0 forces off, empty = auto.")
_k("PIO_DENSE_ALS_BYTES", "int", 2 * 1024**3,
   "Densified-matrix byte budget the dense-ALS auto mode respects.")
_k("PIO_PALLAS_DENSE", "enum", "",
   "Dense-pass Pallas kernel mode: tpu | interpret | 0 (XLA).")
_k("PIO_PALLAS_WINDOWED", "enum", "",
   "Windowed-pass Pallas kernel mode: tpu | interpret | 0 (XLA).")
_k("PIO_PALLAS_RECOMMEND", "enum", "",
   "Fused recommend+top-k kernel mode: tpu | interpret | empty (XLA).")

# -- resilience / fault injection -------------------------------------------
_k("PIO_FAULTS", "spec", "",
   "Deterministic fault specs: point:mode:prob[:param][,...].")
_k("PIO_FAULTS_SEED", "int", None,
   "Seed pinning every fault point's RNG across processes.")
_k("PIO_FAULTS_ADMIN", "flag", "",
   "Set 1 to enable the guarded POST /debug/faults admin endpoint.")

# -- analysis / sanitizer (ISSUE 12) ----------------------------------------
_k("PIO_TSAN", "flag", "",
   "Set 1 to patch threading locks with the lock-order sanitizer.")
_k("PIO_TSAN_REPORT", "path", "",
   "Path the sanitizer writes its JSON findings report to at exit.")

# -- fleet evaluation & auto-tuning (ISSUE 20) -------------------------------
_k("PIO_EVAL_POLL_S", "float", 0.5,
   "Eval-driver poll cadence (s): partial-result folds + re-dispatch.")
_k("PIO_EVAL_SHARD_TIMEOUT_S", "float", 600.0,
   "Wall-clock timeout (s) for one fleet eval shard job.")
_k("PIO_EVAL_MAX_ATTEMPTS", "int", 3,
   "Queue retry budget per eval shard job (infra failures).")
_k("PIO_EVAL_REDISPATCH", "int", 2,
   "Extra driver re-submissions per exhausted eval shard before the "
   "run fails (straggler/poison insurance on top of queue retries).")
_k("PIO_EVAL_RETENTION", "int", 20,
   "Terminal EvalRun records (with results) the eval GC keeps.")
_k("PIO_TUNE_PRIOR", "flag", "1",
   "Set 0 to disable the canary offline prior from eval records.")
_k("PIO_TUNE_STRICT_BAKE", "float", 2.0,
   "Bake-window multiplier when the candidate's linked offline eval "
   "score is worse than live's (<=1 disables).")
_k("PIO_CAS_SETTLE_S", "str", "",
   "Operator-pinned CAS claim settle window (s); empty = adapt from "
   "measured storage write-visibility skew at fleet-member start.")
_k("PIO_CAS_SETTLE_MIN_S", "float", 0.02,
   "Floor (s) of the adaptive CAS claim settle window.")
_k("PIO_CAS_SETTLE_MAX_S", "float", 2.0,
   "Ceiling (s) of the adaptive CAS claim settle window.")

# -- bench harness -----------------------------------------------------------
_k("PIO_BENCH_SCALE", "enum", "",
   "Set small for the CI-sized bench shapes (100K-scale).")
_k("PIO_BENCH_HBM_PEAK", "float", 819e9,
   "HBM roof (bytes/s) bench.py reports bandwidth fractions against.")
_k("PIO_BENCH_PEAK_FLOPS", "float", 197e12,
   "FLOP/s roof bench.py reports MFU against.")


def knob_registry() -> list[Knob]:
    """Declared knobs, sorted by name (the `pio lint --knobs` view)."""
    return [KNOBS[n] for n in sorted(KNOBS)]


def _require(name: str) -> Knob:
    knob = KNOBS.get(name)
    if knob is None:
        for k in KNOBS.values():
            if k.prefix and name.startswith(k.name):
                return k
        raise ValueError(
            f"env knob {name!r} is not declared in the registry "
            "(predictionio_tpu/utils/env.py) — declare it with a type, "
            "default, and doc line before reading it"
        )
    return knob


def _get(name: str, env: Optional[Mapping[str, str]]) -> Optional[str]:
    _require(name)
    mapping = os.environ if env is None else env
    raw = mapping.get(name)
    if raw is None or raw == "":
        return None
    return raw


def env_raw(name: str, env: Optional[Mapping[str, str]] = None
            ) -> Optional[str]:
    """Raw registered read: the value as set, or None when missing/empty.
    For save/restore sites and grammars with their own parser (faults,
    SLO specs) — everything else should use a typed parser."""
    return _get(name, env)


def env_str(name: str, default: Optional[str] = None,
            env: Optional[Mapping[str, str]] = None) -> str:
    raw = _get(name, env)
    if raw is not None:
        return raw
    if default is not None:
        return default
    knob_default = _require(name).default
    return "" if knob_default is None else str(knob_default)


def env_path(name: str, default: Optional[str] = None,
             env: Optional[Mapping[str, str]] = None) -> str:
    """Like env_str but expands ~ in both the value and the default."""
    return os.path.expanduser(env_str(name, default, env))


def env_float(name: str, default: Optional[float] = None,
              env: Optional[Mapping[str, str]] = None) -> float:
    if default is None:
        d = _require(name).default
        default = 0.0 if d is None else float(d)
    raw = _get(name, env)
    if raw is None:
        return float(default)
    try:
        return float(raw)
    except ValueError:
        log.warning("ignoring malformed %s=%r", name, raw)
        return float(default)


def env_opt_float(name: str, env: Optional[Mapping[str, str]] = None
                  ) -> Optional[float]:
    """Float or None when unset/malformed (peak-override semantics)."""
    raw = _get(name, env)
    if raw is None:
        return None
    try:
        return float(raw)
    except ValueError:
        log.warning("ignoring malformed %s=%r", name, raw)
        return None


def env_int(name: str, default: Optional[int] = None,
            env: Optional[Mapping[str, str]] = None) -> int:
    if default is None:
        d = _require(name).default
        default = 0 if d is None else int(d)
    raw = _get(name, env)
    if raw is None:
        return int(default)
    try:
        return int(float(raw))
    except (ValueError, OverflowError):  # OverflowError: "inf"
        log.warning("ignoring malformed %s=%r", name, raw)
        return int(default)


def env_bool(name: str, default: Optional[bool] = None,
             env: Optional[Mapping[str, str]] = None) -> bool:
    if default is None:
        default = bool(_require(name).default)
    raw = _get(name, env)
    if raw is None:
        return bool(default)
    lowered = raw.strip().lower()
    if lowered in _TRUTHY:
        return True
    if lowered in _FALSY:
        return False
    log.warning("ignoring malformed %s=%r", name, raw)
    return bool(default)


def env_flag(name: str, env: Optional[Mapping[str, str]] = None) -> bool:
    """Presence-style gate: set to anything but ''/0/false/no/off."""
    raw = _get(name, env)
    if raw is None:
        return False
    return raw.strip().lower() not in _FALSY


def knobs_markdown() -> str:
    """The registry as a markdown table — `pio lint --knobs` output and
    the README "Configuration knobs" section (CI keeps them in sync)."""
    lines = [
        "| Knob | Type | Default | Description |",
        "| --- | --- | --- | --- |",
    ]
    for knob in knob_registry():
        if knob.prefix:
            name = f"`{knob.name}*`"
            default = ""
        else:
            name = f"`{knob.name}`"
            default = "" if knob.default in (None, "") else f"`{knob.default}`"
        doc = " ".join(knob.doc.split())
        lines.append(f"| {name} | {knob.type} | {default} | {doc} |")
    return "\n".join(lines) + "\n"
