"""First-class evaluation records (ISSUE 20): EvalRun + EvalResult on the
LifecycleRecordStore event-fold layer.

This replaces `best.json` as the source of truth: runs and per-point
results are durable, compactable, GC'd, and carry a lineage pointer
from the winning params to the ModelVersion later trained from them.

Exactly-once across a crashy fleet comes from the record SHAPE, not
from coordination:

- an EvalResult's entity id is deterministic — ``{run_id}#p{index}`` —
  so a re-run shard (crash-requeue, fenced steal, straggler
  re-dispatch) writes the SAME record, never a duplicate;
- each shard writes its fold's partial under its own field
  (``fold_3``), and the store's field-level LWW fold merges folds from
  different workers while making same-fold rewrites idempotent.

The driver declares a point converged when every expected fold field is
present; duplicates are structurally impossible.
"""

from __future__ import annotations

import logging
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.deploy.registry import LifecycleRecordStore

log = logging.getLogger(__name__)

EVAL_RUN_ENTITY = "pio_eval_run"
EVAL_RESULT_ENTITY = "pio_eval_result"

RUN_TERMINAL = ("completed", "failed")


@dataclass
class EvalRun:
    """One declarative evaluation of a param space (the E2 layer's unit
    of record)."""

    id: str
    engine_id: str
    status: str = "running"  # running | completed | failed
    tenant: Optional[str] = None
    spec: dict = field(default_factory=dict)
    num_points: int = 0
    num_groups: int = 0
    num_folds: int = 1  # shard granularity (1 = all folds in one shard)
    metric_header: str = ""
    higher_is_better: bool = True
    created_at: float = 0.0
    finished_at: Optional[float] = None
    winner_index: Optional[int] = None
    winner_score: Optional[float] = None
    winner_params: Optional[dict] = None
    winner_model_version: Optional[str] = None
    last_error: Optional[str] = None
    shards: dict = field(default_factory=dict)  # job_id → {group, fold}
    links: dict = field(default_factory=dict)  # version_id → {job_id, at}

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "engine_id": self.engine_id,
            "status": self.status,
            "tenant": self.tenant,
            "spec": self.spec,
            "num_points": self.num_points,
            "num_groups": self.num_groups,
            "num_folds": self.num_folds,
            "metric_header": self.metric_header,
            "higher_is_better": self.higher_is_better,
            "created_at": self.created_at,
            "finished_at": self.finished_at,
            "winner_index": self.winner_index,
            "winner_score": self.winner_score,
            "winner_params": self.winner_params,
            "winner_model_version": self.winner_model_version,
            "last_error": self.last_error,
            "shards": self.shards,
            "links": self.links,
        }

    @staticmethod
    def from_fields(fields: dict) -> "EvalRun":
        run = EvalRun(id=fields.get("id", ""), engine_id=fields.get("engine_id", ""))
        for k in (
            "status", "tenant", "spec", "num_points", "num_groups",
            "num_folds", "metric_header", "higher_is_better", "created_at",
            "finished_at", "winner_index", "winner_score", "winner_params",
            "winner_model_version", "last_error", "shards",
        ):
            if fields.get(k) is not None:
                setattr(run, k, fields[k])
        # lineage links live as link_{version_id} fields so concurrent
        # stampers never clobber each other (field-level LWW)
        run.links = {
            k[len("link_"):]: v for k, v in fields.items()
            if k.startswith("link_") and isinstance(v, dict)
        }
        return run


class EvalRecordStore:
    """CRUD + fold/compaction/GC for the EvalRun/EvalResult family."""

    def __init__(self, storage: Storage):
        self.storage = storage
        self._store = LifecycleRecordStore(storage)

    # -- runs --------------------------------------------------------------

    def create_run(
        self,
        engine_id: str,
        spec: dict,
        num_points: int,
        num_groups: int,
        num_folds: int,
        metric_header: str,
        higher_is_better: bool = True,
        tenant: Optional[str] = None,
    ) -> EvalRun:
        run = EvalRun(
            id=f"eval-{uuid.uuid4().hex[:12]}",
            engine_id=engine_id,
            tenant=tenant,
            spec=spec,
            num_points=num_points,
            num_groups=num_groups,
            num_folds=max(1, num_folds),
            metric_header=metric_header,
            higher_is_better=higher_is_better,
            created_at=time.time(),
        )
        props = {k: v for k, v in run.to_dict().items()
                 if k != "links" and v is not None}
        self._store.append(EVAL_RUN_ENTITY, run.id, props)
        return run

    def update_run(self, run_id: str, **fields: Any) -> None:
        self._store.append(EVAL_RUN_ENTITY, run_id, fields)

    def get_run(self, run_id: str) -> Optional[EvalRun]:
        fields = self._store.fold(EVAL_RUN_ENTITY, run_id).get(run_id)
        return EvalRun.from_fields(fields) if fields else None

    def list_runs(
        self,
        engine_id: Optional[str] = None,
        status: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> list[EvalRun]:
        runs = [
            EvalRun.from_fields(f)
            for f in self._store.fold(EVAL_RUN_ENTITY).values()
            if f.get("id")
        ]
        if engine_id is not None:
            runs = [r for r in runs if r.engine_id == engine_id]
        if status is not None:
            runs = [r for r in runs if r.status == status]
        if tenant is not None:
            runs = [r for r in runs if r.tenant == tenant]
        runs.sort(key=lambda r: r.created_at, reverse=True)
        return runs

    # -- per-point results -------------------------------------------------

    @staticmethod
    def result_id(run_id: str, point_index: int) -> str:
        return f"{run_id}#p{point_index}"

    @staticmethod
    def fold_key(fold: Optional[int]) -> str:
        return "fold_all" if fold is None else f"fold_{int(fold)}"

    def record_partial(
        self,
        run_id: str,
        point_index: int,
        fold: Optional[int],
        payload: dict,
        params: Optional[dict] = None,
    ) -> None:
        """One shard's per-point contribution. Idempotent: a requeued
        shard rewrites the same entity's same fold field."""
        props: dict[str, Any] = {
            "run_id": run_id,
            "point_index": int(point_index),
            self.fold_key(fold): payload,
        }
        if params is not None:
            props["params"] = params
        self._store.append(
            EVAL_RESULT_ENTITY, self.result_id(run_id, point_index), props
        )

    def results(self, run_id: str) -> dict[int, dict]:
        """point_index → folded result record for one run."""
        out: dict[int, dict] = {}
        prefix = f"{run_id}#p"
        for eid, fields in self._store.fold(EVAL_RESULT_ENTITY).items():
            if eid.startswith(prefix) and fields.get("run_id") == run_id:
                out[int(fields.get("point_index", eid[len(prefix):]))] = fields
        return out

    def point_partials(self, record: dict) -> dict[str, dict]:
        """fold_key → partial payload from a folded result record."""
        return {
            k: v for k, v in record.items()
            if (k == "fold_all" or k.startswith("fold_")) and isinstance(v, dict)
        }

    # -- lineage -----------------------------------------------------------

    def link_model_version(
        self, run_id: str, version_id: str, job_id: Optional[str] = None,
    ) -> None:
        """Lineage pointer: the winning params of `run_id` were trained
        into ModelVersion `version_id` (stamped by the scheduler when a
        preset-carrying retrain completes). Field-per-version keeps
        concurrent stamps merge-safe; winner_model_version tracks the
        newest."""
        self._store.append(EVAL_RUN_ENTITY, run_id, {
            f"link_{version_id}": {"job_id": job_id, "at": time.time()},
            "winner_model_version": version_id,
        })

    # -- hygiene: compaction + GC (same discipline as ModelRegistry) -------

    def compact(self, min_events: int = 8, min_age_s: float = 60.0) -> int:
        removed = self._store.compact_all(
            EVAL_RUN_ENTITY, min_events=min_events, min_age_s=min_age_s
        )
        removed += self._store.compact_all(
            EVAL_RESULT_ENTITY, min_events=min_events, min_age_s=min_age_s
        )
        return removed

    def purge_run(self, run_id: str) -> int:
        removed = self._store.purge(EVAL_RUN_ENTITY, run_id)
        for eid in list(self._store.fold(EVAL_RESULT_ENTITY)):
            if eid.startswith(f"{run_id}#p"):
                removed += self._store.purge(EVAL_RESULT_ENTITY, eid)
        return removed

    def gc(self, keep: int = 20) -> int:
        """Drop the oldest terminal runs (and their results) beyond
        `keep`; running evaluations are never collected."""
        terminal = [r for r in self.list_runs() if r.status in RUN_TERMINAL]
        removed = 0
        for run in terminal[keep:]:
            removed += self.purge_run(run.id)
        if removed:
            log.info("eval GC: purged %d events beyond %d kept runs",
                     removed, keep)
        return removed
