"""Eval-shard subprocess entry (`python -m predictionio_tpu.evalfleet.worker`).

The scheduler spawns this module for `kind="eval"` jobs exactly as it
spawns deploy/worker for train jobs: same spec file (storage wiring +
variant + result path), same exit-code retry contract. The variant
carries an `evalShard` payload (written by evalfleet/driver.py): which
points of which run, which fold, which metrics.

In here the shard is plain: build the engine, materialize one
EngineParams per point, run the grid through `Engine.batch_eval` (the
grid-compatible group trains as ONE device program per fold via
train_grid), reduce each point's (Q,P,A) tuples to combinable metric
partials, and write them to the durable EvalResult records.

Crash-safety is free: result entity ids are deterministic and fold
fields idempotent (evalfleet/records.py), so a kill -9 here just means
the re-claimed shard rewrites the same fields.

Exit codes (the scheduler's retry contract):
- 0                  — partials recorded
- EXIT_TRAIN_FAILED  — the eval itself raised (deterministic fail-fast)
- anything else      — infra trouble; the scheduler re-queues with backoff
"""

from __future__ import annotations

import json
import logging
import sys
import traceback


def main(argv: list[str]) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    if len(argv) != 2:
        print("usage: python -m predictionio_tpu.evalfleet.worker <spec.json>",
              file=sys.stderr)
        return 2
    from predictionio_tpu.controller.engine import resolve_engine
    from predictionio_tpu.controller.params import load_symbol
    from predictionio_tpu.core.base import RuntimeContext, WorkflowParams
    from predictionio_tpu.data.storage.base import StorageError
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.deploy.scheduler import (
        EXIT_INFRA_FAILED,
        EXIT_TRAIN_FAILED,
        storage_config_from_json,
    )
    from predictionio_tpu.evalfleet.records import EvalRecordStore
    from predictionio_tpu.evalfleet.specs import metric_partial, resolve_metric

    with open(argv[1]) as f:
        spec = json.load(f)
    try:
        storage = Storage(storage_config_from_json(spec["storage"]))
    except Exception:
        traceback.print_exc()
        return EXIT_INFRA_FAILED

    variant = spec["variant"]
    shard = variant.get("evalShard")
    if not shard:
        print("spec variant carries no evalShard payload", file=sys.stderr)
        return EXIT_TRAIN_FAILED

    try:
        engine = resolve_engine(load_symbol(variant["engineFactory"]))
        base = {k: v for k, v in variant.items() if k != "evalShard"}
        eps = []
        for frag in shard["points"]:
            eps.append(engine.params_from_variant_json({**base, **frag}))
        fold = shard.get("fold")
        ctx = RuntimeContext(storage=storage, mesh=None, mode="eval",
                             workflow_params=WorkflowParams())
        eval_data = engine.batch_eval(
            ctx, eps,
            fold_indices=None if fold is None else [int(fold)],
        )
        primary = resolve_metric(shard["metric"])
        others = [resolve_metric(m) for m in shard.get("other_metrics", [])]
    except StorageError:
        traceback.print_exc()
        return EXIT_INFRA_FAILED
    except Exception:
        traceback.print_exc()
        return EXIT_TRAIN_FAILED

    run_id = shard["run_id"]
    try:
        records = EvalRecordStore(storage)
        for idx, (point_index, (_ep, data)) in enumerate(
            zip(shard["point_indices"], eval_data)
        ):
            payload = {
                "primary": metric_partial(primary, ctx, data),
                "others": [
                    {"header": m.header(), **metric_partial(m, ctx, data)}
                    for m in others
                ],
                "job_id": spec.get("job_id"),
            }
            records.record_partial(
                run_id, point_index, fold, payload,
                params=shard["points"][idx],
            )
    except StorageError:
        traceback.print_exc()
        return EXIT_INFRA_FAILED
    except Exception:
        traceback.print_exc()
        return EXIT_TRAIN_FAILED

    with open(spec["result_path"], "w") as f:
        json.dump({"run_id": run_id, "points": len(eps),
                   "fold": fold}, f)
    print(f"eval shard done: run {run_id}, {len(eps)} point(s), "
          f"fold {'all' if fold is None else fold}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
