"""Fleet evaluation driver (ISSUE 20): split a declarative param space
into per-fold × per-group shard jobs on the persistent JobQueue, fold
the shards' durable partial results into a live status view, re-dispatch
stragglers, and finalize the winner.

The driver owns NO execution: shards are `kind="eval"` jobs that fleet
workers CAS-claim exactly like train jobs (heartbeats, crash-requeue,
fenced steal — deploy/scheduler.py). The driver is a pure fold over
durable records, so it can die and restart anywhere: `status(run_id)`
recomputes everything from the EvalResult records + job states.

Thread contract: `start(run_id)` spawns ONE named poll thread
("eval-driver"); `stop()` joins it — the same join discipline CI
enforces for every monitor thread.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.deploy.scheduler import JobQueue
from predictionio_tpu.evalfleet.records import (
    EvalRecordStore,
    EvalRun,
    RUN_TERMINAL,
)
from predictionio_tpu.evalfleet.specs import (
    EvalSpec,
    combine_partials,
    expand_points,
    group_points,
    metric_finalize,
    point_fragment,
    resolve_metric,
)
from predictionio_tpu.utils.env import env_float, env_int

log = logging.getLogger(__name__)

EVAL_DRIVER_THREAD = "eval-driver"


@dataclass
class EvalDriverConfig:
    poll_interval_s: float = field(
        default_factory=lambda: env_float("PIO_EVAL_POLL_S"))
    shard_timeout_s: float = field(
        default_factory=lambda: env_float("PIO_EVAL_SHARD_TIMEOUT_S"))
    max_attempts: int = field(
        default_factory=lambda: env_int("PIO_EVAL_MAX_ATTEMPTS"))
    # extra re-submissions per exhausted shard before the run fails —
    # straggler/poison insurance ON TOP of the queue's own retry budget
    redispatch_limit: int = field(
        default_factory=lambda: env_int("PIO_EVAL_REDISPATCH"))


class EvalDriver:
    """Fan out an EvalSpec, watch it converge, pick the winner."""

    def __init__(self, storage: Storage,
                 config: Optional[EvalDriverConfig] = None):
        self.storage = storage
        self.config = config or EvalDriverConfig()
        self.queue = JobQueue(storage)
        self.records = EvalRecordStore(storage)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- fan-out -----------------------------------------------------------

    def submit(self, spec: EvalSpec, tenant: Optional[str] = None) -> EvalRun:
        """Expand the space, create the EvalRun record, and enqueue one
        shard job per (grid-compatible group × fold)."""
        points = expand_points(spec)
        groups = group_points(points)
        folds = list(range(spec.folds)) if spec.folds > 0 else [None]
        metric = resolve_metric(spec.metric)
        run = self.records.create_run(
            engine_id=str(spec.variant.get("id", "")),
            spec=spec.to_dict(),
            num_points=len(points),
            num_groups=len(groups),
            num_folds=len(folds),
            metric_header=metric.header(),
            higher_is_better=metric.higher_is_better,
            tenant=tenant,
        )
        shards: dict[str, dict] = {}
        for gi, group in enumerate(groups):
            for fold in folds:
                job = self._submit_shard(run, spec, points, group, fold)
                shards[job.id] = {"group": gi, "fold": fold,
                                  "point_indices": list(group)}
        self.records.update_run(run.id, shards=shards)
        run.shards = shards
        log.info(
            "eval run %s: %d points in %d groups x %d folds -> %d shards",
            run.id, len(points), len(groups), len(folds), len(shards),
        )
        return run

    def _submit_shard(self, run: EvalRun, spec: EvalSpec,
                      points: list, group: list, fold: Optional[int]):
        shard = {
            "run_id": run.id,
            "point_indices": list(group),
            "points": [point_fragment(points[i]) for i in group],
            "fold": fold,
            "folds": spec.folds,
            "metric": spec.metric,
            "other_metrics": list(spec.other_metrics),
        }
        variant = {
            "id": spec.variant.get("id", ""),
            "engineFactory": spec.variant["engineFactory"],
            "evalShard": shard,
        }
        return self.queue.submit(
            variant,
            engine_id=str(spec.variant.get("id", "")),
            timeout_s=self.config.shard_timeout_s,
            max_attempts=self.config.max_attempts,
            kind="eval",
            tenant=run.tenant,
        )

    # -- the live fold -----------------------------------------------------

    def scores(self, run: EvalRun) -> list[dict]:
        """Per-point combined view from the durable records: folds seen,
        combined primary score (partial until all folds land)."""
        metric = resolve_metric((run.spec or {}).get("metric", run.metric_header))
        results = self.records.results(run.id)
        expected = (
            [f"fold_{i}" for i in range(run.num_folds)]
            if run.num_folds > 1 or (run.spec or {}).get("folds", 0) > 0
            else ["fold_all"]
        )
        out = []
        for pi in range(run.num_points):
            rec = results.get(pi, {})
            partials = self.records.point_partials(rec)
            primary = [p.get("primary", {}) for p in partials.values()]
            total, count = combine_partials(primary)
            out.append({
                "point_index": pi,
                "params": rec.get("params"),
                "folds_done": sorted(partials),
                "complete": all(k in partials for k in expected),
                "score": metric_finalize(metric, total, count)
                if primary else None,
            })
        return out

    def status(self, run_id: str) -> dict:
        """The `pio eval status` payload: run record + per-point coverage
        + shard job states, recomputed from durable state every call."""
        run = self.records.get_run(run_id)
        if run is None:
            raise KeyError(f"no such eval run: {run_id}")
        scores = self.scores(run)
        jobs = {j.id: j for j in self.queue.list()}
        shard_view = []
        for job_id, meta in sorted(run.shards.items()):
            j = jobs.get(job_id)
            shard_view.append({
                "job_id": job_id,
                "group": meta.get("group"),
                "fold": meta.get("fold"),
                "status": j.status if j is not None else "unknown",
                "worker_id": getattr(j, "worker_id", None),
                "attempt": getattr(j, "attempt", None),
            })
        done = sum(1 for s in scores if s["complete"])
        return {
            "run": run.to_dict(),
            "points_done": done,
            "points_total": run.num_points,
            "shards": shard_view,
            "points": scores,
        }

    # -- convergence -------------------------------------------------------

    def poll_once(self, run_id: str) -> EvalRun:
        """One driver tick: re-dispatch exhausted shards whose points are
        still incomplete, finalize when every point converged, fail when
        the retry budget is spent."""
        run = self.records.get_run(run_id)
        if run is None:
            raise KeyError(f"no such eval run: {run_id}")
        if run.status in RUN_TERMINAL:
            return run
        scores = self.scores(run)
        if all(s["complete"] for s in scores):
            return self._finalize(run, scores)

        jobs = {j.id: j for j in self.queue.list()}
        incomplete = {
            pi for s in scores if not s["complete"]
            for pi in [s["point_index"]]
        }
        redispatches = dict(run.shards)
        changed = False
        exhausted = 0
        for job_id, meta in list(run.shards.items()):
            if not (set(meta.get("point_indices", [])) & incomplete):
                continue  # this shard's points already landed
            j = jobs.get(job_id)
            if j is None or j.status != "failed":
                continue  # pending/running/completed: let the fleet work
            n = int(meta.get("redispatched", 0))
            if n >= self.config.redispatch_limit:
                exhausted += 1
                continue
            # straggler/poison re-dispatch: same shard payload, fresh job
            nxt = self.queue.submit(
                j.variant,
                engine_id=j.engine_id,
                timeout_s=j.timeout_s,
                max_attempts=self.config.max_attempts,
                kind="eval",
                tenant=run.tenant,
            )
            log.warning("eval run %s: re-dispatched failed shard %s as %s",
                        run.id, job_id, nxt.id)
            meta = dict(meta, redispatched=n + 1)
            redispatches[job_id] = meta
            # the replacement INHERITS the lineage's spent budget — a
            # poison shard can't buy itself a fresh limit per re-dispatch
            redispatches[nxt.id] = {
                "group": meta.get("group"), "fold": meta.get("fold"),
                "point_indices": list(meta.get("point_indices", [])),
                "redispatched": n + 1,
            }
            changed = True
        if changed:
            self.records.update_run(run.id, shards=redispatches)
            run.shards = redispatches
        elif exhausted:
            self.records.update_run(
                run.id, status="failed", finished_at=time.time(),
                last_error=f"{exhausted} shard(s) exhausted their retry "
                           f"budget with incomplete points",
            )
            return self.records.get_run(run.id) or run
        return run

    def _finalize(self, run: EvalRun, scores: list[dict]) -> EvalRun:
        metric = resolve_metric((run.spec or {}).get("metric", run.metric_header))
        winner = None
        for s in scores:
            if s["score"] is None:
                continue
            if winner is None or metric.compare(s["score"], winner["score"]) > 0:
                winner = s
        fields: dict[str, Any] = {
            "status": "completed", "finished_at": time.time(),
        }
        if winner is not None:
            fields.update(
                winner_index=winner["point_index"],
                winner_score=winner["score"],
                winner_params=winner["params"],
            )
        self.records.update_run(run.id, **fields)
        out = self.records.get_run(run.id) or run
        log.info(
            "eval run %s completed: winner point %s (%s=%s)",
            run.id, out.winner_index, out.metric_header, out.winner_score,
        )
        return out

    def wait(self, run_id: str, timeout_s: Optional[float] = None) -> EvalRun:
        """Poll until the run is terminal (or timeout); returns the run."""
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        while True:
            run = self.poll_once(run_id)
            if run.status in RUN_TERMINAL:
                return run
            if deadline is not None and time.monotonic() >= deadline:
                return run
            if self._stop.wait(self.config.poll_interval_s):
                return run

    # -- background poll thread (CI join contract) -------------------------

    def start(self, run_id: str) -> None:
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("eval driver already running")
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    run = self.poll_once(run_id)
                    if run.status in RUN_TERMINAL:
                        return
                except Exception:
                    log.warning("eval driver poll failed", exc_info=True)
                if self._stop.wait(self.config.poll_interval_s):
                    return

        self._thread = threading.Thread(
            target=loop, name=EVAL_DRIVER_THREAD, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)
