"""Declarative evaluation specs (ISSUE 20): param-space DSL + metric specs.

The reference expressed grid search as Scala code (EngineParamsGenerator
subclasses, e2's `Evaluation` DSL). Here the space is DATA — a JSON spec
that survives the trip through the persistent JobQueue to fleet workers:

    {
      "variant": { ... engine.json ... },
      "axes": [
        {"path": "algorithms.0.params.lambda_", "values": [0.1, 1.0]},
        {"path": "algorithms.0.params.alpha",
         "range": {"from": 0.01, "to": 10.0, "steps": 4, "scale": "log"}}
      ],
      "metric": {"name": "map@5"},
      "otherMetrics": [{"name": "precision@5"}],
      "folds": 2
    }

Axes are dot-paths into the variant dict (list indices as integer
segments); the cross product of all axes is the point list, expanded in
deterministic axis-major order. `group_points` buckets points by
grid-kernel compatibility — the same shared_key discipline as
`Engine._grid_batchable` — so every group trains as ONE device program
per fold through the existing `train_grid` path.

Metrics resolve from a name registry (map@k / precision@k / ndcg@k /
rmse) or an import-path escape hatch ({"class": "pkg.mod.Metric"}).
`metric_partial` / `metric_finalize` turn any metric into a combinable
(sum, count) pair so per-fold shards on different workers reduce to
EXACTLY the sequential MetricEvaluator's score (AverageMetric's
np.mean over all folds == total_sum / total_count).

Import-leak contract: this module (and the whole evalfleet package)
never imports jax — the driver and records layers run on coordinator
hosts; only shard subprocesses pay for device runtimes.
"""

from __future__ import annotations

import copy
import itertools
import json
import math
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

from predictionio_tpu.controller.metrics import (
    AverageMetric,
    EvalData,
    Metric,
    OptionAverageMetric,
    QPAMetric,
)

# variant keys that parameterize DASE stages — the only keys axes may
# target and the shape of the winner fragment fed back into retrains
STAGE_KEYS = ("datasource", "preparator", "algorithms", "serving")


# ---------------------------------------------------------------------------
# ranking / regression metrics (reusing the controller Metric family)
# ---------------------------------------------------------------------------


def _get(obj: Any, name: str, default: Any = None) -> Any:
    if isinstance(obj, dict):
        return obj.get(name, default)
    return getattr(obj, name, default)


class RankingMetric(AverageMetric):
    """Base for top-k ranking metrics: the Prediction carries a ranked
    item list under `pred_attr` (plain ids, (id, score) pairs, or dicts
    with an "item" key), the Actual carries the relevant set under
    `actual_attr`."""

    def __init__(self, k: int = 10, pred_attr: str = "items",
                 actual_attr: str = "items"):
        self.k = int(k)
        if self.k <= 0:
            raise ValueError(f"k must be positive, got {k}")
        self.pred_attr = pred_attr
        self.actual_attr = actual_attr

    def header(self) -> str:
        return f"{type(self).__name__}@{self.k}"

    def _ranked(self, p: Any) -> list:
        items = _get(p, self.pred_attr) or ()
        out = []
        for it in items:
            if isinstance(it, (tuple, list)) and it:
                out.append(it[0])
            elif isinstance(it, dict) and "item" in it:
                out.append(it["item"])
            else:
                out.append(it)
        return out[: self.k]

    def _relevant(self, a: Any) -> set:
        return set(self._ranked_raw(_get(a, self.actual_attr) or ()))

    @staticmethod
    def _ranked_raw(items: Any) -> list:
        out = []
        for it in items:
            if isinstance(it, (tuple, list)) and it:
                out.append(it[0])
            elif isinstance(it, dict) and "item" in it:
                out.append(it["item"])
            else:
                out.append(it)
        return out


class PrecisionAtK(RankingMetric):
    """|top-k ∩ relevant| / min(k, |retrieved|); NaN when nothing was
    retrieved (NaN loses best-params selection, see Metric.compare)."""

    def calculate_one(self, q, p, a) -> float:
        ranked = self._ranked(p)
        if not ranked:
            return float("nan")
        rel = self._relevant(a)
        return sum(1 for i in ranked if i in rel) / float(len(ranked))


class MAPAtK(RankingMetric):
    """Mean average precision truncated at k (reference e2
    MeanAveragePrecisionAtK)."""

    def calculate_one(self, q, p, a) -> float:
        ranked = self._ranked(p)
        rel = self._relevant(a)
        if not rel:
            return float("nan")
        hits, ap = 0, 0.0
        for pos, item in enumerate(ranked):
            if item in rel:
                hits += 1
                ap += hits / float(pos + 1)
        return ap / float(min(len(rel), self.k))


class NDCGAtK(RankingMetric):
    """Binary-relevance normalized discounted cumulative gain at k."""

    def calculate_one(self, q, p, a) -> float:
        ranked = self._ranked(p)
        rel = self._relevant(a)
        if not rel:
            return float("nan")
        dcg = sum(
            1.0 / math.log2(pos + 2)
            for pos, item in enumerate(ranked) if item in rel
        )
        idcg = sum(
            1.0 / math.log2(pos + 2) for pos in range(min(len(rel), self.k))
        )
        return dcg / idcg if idcg > 0 else float("nan")


class HeldOutRMSE(QPAMetric):
    """Root mean squared error over held-out (prediction, actual) value
    pairs; lower is better. Carries its own combinable partial (sum of
    squared errors) so cross-shard reduction stays exact — a mean of
    per-fold RMSEs would NOT equal the pooled RMSE."""

    higher_is_better = False

    def __init__(self, pred_attr: str = "rating",
                 actual_attr: str = "rating"):
        self.pred_attr = pred_attr
        self.actual_attr = actual_attr

    def header(self) -> str:
        return "HeldOutRMSE"

    def calculate_one(self, q, p, a) -> float:
        pv, av = _get(p, self.pred_attr), _get(a, self.actual_attr)
        if pv is None or av is None:
            return float("nan")
        return (float(pv) - float(av)) ** 2

    def calculate(self, ctx, data: EvalData) -> float:
        part = self.partial(ctx, data)
        return self.finalize(part["sum"], part["count"])

    def partial(self, ctx, data: EvalData) -> dict:
        sqe = [
            s for _, qpa in data for q, p, a in qpa
            if not math.isnan(s := self.calculate_one(q, p, a))
        ]
        return {"sum": float(sum(sqe)), "count": len(sqe)}

    def finalize(self, total: float, count: int) -> float:
        return math.sqrt(total / count) if count else float("nan")


METRIC_REGISTRY: dict[str, type] = {
    "precision": PrecisionAtK,
    "map": MAPAtK,
    "ndcg": NDCGAtK,
    "rmse": HeldOutRMSE,
}


def resolve_metric(spec: Any) -> Metric:
    """Metric spec → Metric instance.

    Accepts "map@5", {"name": "map@5"}, {"name": "map", "k": 5,
    "pred_attr": ...}, or the escape hatch {"class": "pkg.mod.Cls",
    "params": {...}} for project-defined metrics."""
    if isinstance(spec, Metric):
        return spec
    if isinstance(spec, str):
        spec = {"name": spec}
    if not isinstance(spec, dict):
        raise ValueError(f"metric spec must be a name or dict, got {spec!r}")
    if "class" in spec:
        from predictionio_tpu.controller.params import load_symbol

        cls = load_symbol(spec["class"])
        return cls(**spec.get("params", {}))
    name = spec.get("name", "")
    kwargs = {k: v for k, v in spec.items() if k != "name"}
    if "@" in name:
        name, _, k = name.partition("@")
        kwargs.setdefault("k", int(k))
    cls = METRIC_REGISTRY.get(name.lower())
    if cls is None:
        raise ValueError(
            f"unknown metric {name!r} (known: {sorted(METRIC_REGISTRY)}; "
            f"or pass {{'class': 'pkg.mod.Metric'}})"
        )
    if cls is HeldOutRMSE:
        kwargs.pop("k", None)
    return cls(**kwargs)


# ---------------------------------------------------------------------------
# combinable partials — the cross-shard reduction contract
# ---------------------------------------------------------------------------


def metric_partial(metric: Metric, ctx, data: EvalData) -> dict:
    """One shard's contribution as a combinable {"sum", "count"} pair.

    Exact for the averaging family (per-tuple score sums) and for any
    metric exposing its own `partial`; other metrics degrade to a
    per-fold score with count 1 (the combined value is then a mean of
    fold scores — documented approximation)."""
    part = getattr(metric, "partial", None)
    if callable(part):
        out = part(ctx, data)
        return {"sum": float(out["sum"]), "count": int(out["count"])}
    if isinstance(metric, OptionAverageMetric):
        scores = [
            s for _, qpa in data for q, p, a in qpa
            if (s := metric.calculate_one(q, p, a)) is not None
        ]
    elif isinstance(metric, AverageMetric):
        scores = [
            metric.calculate_one(q, p, a) for _, qpa in data for q, p, a in qpa
        ]
    else:
        score = metric.calculate(ctx, data)
        return {"sum": float(score), "count": 1}
    scores = [s for s in scores if not (isinstance(s, float) and math.isnan(s))]
    return {"sum": float(sum(scores)), "count": len(scores)}


def metric_finalize(metric: Metric, total: float, count: int) -> float:
    """Combined (sum, count) → final score."""
    fin = getattr(metric, "finalize", None)
    if callable(fin):
        return float(fin(total, count))
    return float(total) / count if count else float("nan")


def combine_partials(parts: Sequence[dict]) -> tuple[float, int]:
    total = sum(float(p.get("sum", 0.0)) for p in parts)
    count = sum(int(p.get("count", 0)) for p in parts)
    return total, count


# ---------------------------------------------------------------------------
# param-space DSL
# ---------------------------------------------------------------------------


@dataclass
class ParamAxis:
    """One searched field: a dot-path into the variant + explicit values
    (ranges are expanded at parse time so the spec round-trips as data)."""

    path: str
    values: list

    def to_dict(self) -> dict:
        return {"path": self.path, "values": list(self.values)}

    @staticmethod
    def from_dict(obj: dict) -> "ParamAxis":
        path = obj.get("path", "")
        if not path or path.split(".", 1)[0] not in STAGE_KEYS:
            raise ValueError(
                f"axis path must target a stage key {STAGE_KEYS}, got {path!r}"
            )
        if "values" in obj:
            values = list(obj["values"])
        elif "range" in obj:
            values = _expand_range(obj["range"])
        else:
            raise ValueError(f"axis {path!r} needs 'values' or 'range'")
        if not values:
            raise ValueError(f"axis {path!r} expands to no values")
        return ParamAxis(path=path, values=values)


def _expand_range(r: dict) -> list:
    lo, hi = float(r["from"]), float(r["to"])
    steps = int(r.get("steps", 2))
    if steps < 1:
        raise ValueError(f"range steps must be >= 1, got {steps}")
    if steps == 1:
        return [lo]
    if r.get("scale", "linear") == "log":
        if lo <= 0 or hi <= 0:
            raise ValueError("log-scale range needs positive endpoints")
        ratio = (hi / lo) ** (1.0 / (steps - 1))
        return [lo * ratio ** i for i in range(steps)]
    step = (hi - lo) / (steps - 1)
    return [lo + step * i for i in range(steps)]


@dataclass
class EvalSpec:
    """The full declarative evaluation: base variant + axes + metrics.

    `folds > 0` shards the run per fold as well as per group — the
    datasource's read_eval must then yield exactly that many eval sets;
    0 means each shard evaluates all folds in one go."""

    variant: dict
    axes: list = field(default_factory=list)
    metric: Any = field(default_factory=lambda: {"name": "map@10"})
    other_metrics: list = field(default_factory=list)
    folds: int = 0

    def __post_init__(self):
        if not isinstance(self.variant, dict) or "engineFactory" not in self.variant:
            raise ValueError("spec variant must be an engine.json dict "
                             "with an engineFactory")
        if self.folds < 0:
            raise ValueError(f"folds must be >= 0, got {self.folds}")

    def to_dict(self) -> dict:
        return {
            "variant": self.variant,
            "axes": [a.to_dict() for a in self.axes],
            "metric": self.metric,
            "otherMetrics": list(self.other_metrics),
            "folds": self.folds,
        }

    @staticmethod
    def from_dict(obj: dict) -> "EvalSpec":
        return EvalSpec(
            variant=obj.get("variant") or {},
            axes=[ParamAxis.from_dict(a) for a in obj.get("axes", [])],
            metric=obj.get("metric") or {"name": "map@10"},
            other_metrics=list(obj.get("otherMetrics", [])),
            folds=int(obj.get("folds", 0)),
        )

    @staticmethod
    def load(path: str) -> "EvalSpec":
        with open(path) as f:
            return EvalSpec.from_dict(json.load(f))


def _set_path(variant: dict, path: str, value: Any) -> None:
    """Write `value` at a dot-path; integer segments index lists, missing
    dict segments are created (e.g. an algorithm entry without params)."""
    node: Any = variant
    segs = path.split(".")
    for i, seg in enumerate(segs):
        last = i == len(segs) - 1
        if isinstance(node, list):
            idx = int(seg)
            if idx >= len(node):
                raise ValueError(
                    f"axis path {path!r}: index {idx} out of range "
                    f"({len(node)} entries)"
                )
            if last:
                node[idx] = value
            else:
                node = node[idx]
        elif isinstance(node, dict):
            if last:
                node[seg] = value
            else:
                if seg not in node or node[seg] is None:
                    node[seg] = {}
                node = node[seg]
        else:
            raise ValueError(
                f"axis path {path!r}: segment {seg!r} lands on a scalar"
            )


def expand_points(spec: EvalSpec) -> list[dict]:
    """Cross product of all axes applied to deep copies of the base
    variant; deterministic axis-major order (point 0 = first value of
    every axis). No axes → the single base point."""
    if not spec.axes:
        return [copy.deepcopy(spec.variant)]
    points = []
    for combo in itertools.product(*(a.values for a in spec.axes)):
        v = copy.deepcopy(spec.variant)
        for axis, value in zip(spec.axes, combo):
            _set_path(v, axis.path, value)
        points.append(v)
    return points


def point_fragment(point_variant: dict) -> dict:
    """The stage-params fragment of a point — what EvalResult records
    store and what the tuning loop overlays onto retrain variants (same
    shape as MetricEvaluatorResult._params_dict)."""
    return {k: copy.deepcopy(point_variant[k])
            for k in STAGE_KEYS if k in point_variant}


def _group_key(point: dict) -> str:
    """Grid-kernel compatibility key, mirroring Engine._grid_batchable:
    points sharing a single same-named algorithm and identical
    datasource/preparator/serving configs can train as one device
    program per fold via train_grid. (train_grid availability is checked
    at shard runtime — Engine.batch_eval degrades to the serial path.)"""
    algos = point.get("algorithms") or []
    if len(algos) != 1:
        return "solo:" + json.dumps(point, sort_keys=True, default=str)
    shared = {k: point.get(k) for k in ("datasource", "preparator", "serving")}
    shared["algo_name"] = algos[0].get("name", "")
    return "grid:" + json.dumps(shared, sort_keys=True, default=str)


def group_points(points: Sequence[dict]) -> list[list[int]]:
    """Point indices bucketed by grid compatibility, order-preserving."""
    groups: dict[str, list[int]] = {}
    for i, p in enumerate(points):
        groups.setdefault(_group_key(p), []).append(i)
    return list(groups.values())
