"""The tuning→retrain loop (ISSUE 20): park an eval run's winner as a
retrain preset, overlay it onto the next periodic retrain, and lend the
winner's offline metrics to the canary verdict as an optional prior.

Presets are LifecycleRecordStore records (entity "pio_retrain_preset"),
keyed by engine id — or `engine_id@tenant` for tenant-scoped presets
(`pio tune --tenant <id>`), which win over the global one when the
retrain job carries that tenant. The scheduler consults
`apply_preset` inside `_schedule_next_period`, so the NEXT scheduled
retrain of the engine trains the winning params; the merged variant
carries an `evalRun` marker so the completing train job can stamp the
lineage pointer (EvalRun.winner_model_version) back onto the run.

The offline prior: when both the canary candidate and the live version
have lineage-linked eval runs on the same metric, and the candidate's
offline score is WORSE than live's, the rollout bake window stretches
by PIO_TUNE_STRICT_BAKE — offline evidence doesn't veto the canary, it
just buys the online verdict more time. Missing data → multiplier 1.0.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.deploy.registry import LifecycleRecordStore
from predictionio_tpu.evalfleet.records import EvalRecordStore, EvalRun
from predictionio_tpu.evalfleet.specs import EvalSpec, STAGE_KEYS
from predictionio_tpu.utils.env import env_bool, env_float

log = logging.getLogger(__name__)

PRESET_ENTITY = "pio_retrain_preset"


@dataclass
class RetrainPreset:
    """A parked winner: stage-params fragment + provenance."""

    engine_id: str
    params: dict
    tenant: Optional[str] = None
    run_id: str = ""
    metric_header: str = ""
    score: Optional[float] = None
    created_at: float = 0.0

    @property
    def key(self) -> str:
        return preset_key(self.engine_id, self.tenant)

    def to_dict(self) -> dict:
        return {
            "engine_id": self.engine_id,
            "params": self.params,
            "tenant": self.tenant,
            "run_id": self.run_id,
            "metric_header": self.metric_header,
            "score": self.score,
            "created_at": self.created_at,
        }

    @staticmethod
    def from_fields(fields: dict) -> "RetrainPreset":
        return RetrainPreset(
            engine_id=fields.get("engine_id", ""),
            params=fields.get("params") or {},
            tenant=fields.get("tenant"),
            run_id=fields.get("run_id", ""),
            metric_header=fields.get("metric_header", ""),
            score=fields.get("score"),
            created_at=fields.get("created_at", 0.0),
        )


def preset_key(engine_id: str, tenant: Optional[str] = None) -> str:
    return f"{engine_id}@{tenant}" if tenant else engine_id


class PresetStore:
    """CRUD for retrain presets on the shared record layer."""

    def __init__(self, storage: Storage):
        self._store = LifecycleRecordStore(storage)

    def park(self, preset: RetrainPreset) -> None:
        preset.created_at = preset.created_at or time.time()
        self._store.append(PRESET_ENTITY, preset.key, preset.to_dict())

    def get(self, engine_id: str,
            tenant: Optional[str] = None) -> Optional[RetrainPreset]:
        """Tenant-scoped preset first, global fallback."""
        for key in filter(None, (
            preset_key(engine_id, tenant) if tenant else None,
            preset_key(engine_id),
        )):
            fields = self._store.fold(PRESET_ENTITY, key).get(key)
            if fields:
                return RetrainPreset.from_fields(fields)
        return None

    def list(self) -> list[RetrainPreset]:
        out = [RetrainPreset.from_fields(f)
               for f in self._store.fold(PRESET_ENTITY).values() if f]
        out.sort(key=lambda p: p.created_at, reverse=True)
        return out

    def clear(self, engine_id: str, tenant: Optional[str] = None) -> int:
        return self._store.purge(PRESET_ENTITY, preset_key(engine_id, tenant))


def apply_preset(storage: Storage, variant: dict, engine_id: str,
                 tenant: Optional[str] = None) -> dict:
    """Overlay the parked winner's stage params onto a retrain variant.

    Called by TrainScheduler._schedule_next_period for every periodic
    train resubmission; identity when no preset is parked. The merged
    variant keeps id/engineFactory/mesh and gains an `evalRun` marker
    for the completion-time lineage stamp."""
    preset = PresetStore(storage).get(engine_id, tenant)
    if preset is None:
        return variant
    merged = dict(variant)
    for key in STAGE_KEYS:
        if key in preset.params:
            merged[key] = preset.params[key]
    if preset.run_id:
        merged["evalRun"] = preset.run_id
    log.info(
        "retrain preset applied: engine %s%s trains eval winner from %s "
        "(%s=%s)", engine_id, f" tenant {tenant}" if tenant else "",
        preset.run_id, preset.metric_header, preset.score,
    )
    return merged


def park_winner(storage: Storage, run: EvalRun,
                tenant: Optional[str] = None) -> RetrainPreset:
    """EvalRun winner → retrain preset (the `pio tune` parking step)."""
    if run.status != "completed" or run.winner_params is None:
        raise ValueError(
            f"eval run {run.id} has no winner to park "
            f"(status={run.status})"
        )
    preset = RetrainPreset(
        engine_id=run.engine_id,
        params=run.winner_params,
        tenant=tenant if tenant is not None else run.tenant,
        run_id=run.id,
        metric_header=run.metric_header,
        score=run.winner_score,
    )
    PresetStore(storage).park(preset)
    return preset


def tune(
    storage: Storage,
    spec: EvalSpec,
    tenant: Optional[str] = None,
    timeout_s: Optional[float] = None,
    driver: Any = None,
) -> tuple[EvalRun, Optional[RetrainPreset]]:
    """The full loop: run the space on the fleet, wait, park the winner.

    Returns (run, preset); preset is None when the run did not complete
    with a winner (the run record carries the diagnosis)."""
    from predictionio_tpu.evalfleet.driver import EvalDriver

    drv = driver or EvalDriver(storage)
    run = drv.submit(spec, tenant=tenant)
    run = drv.wait(run.id, timeout_s=timeout_s)
    if run.status != "completed" or run.winner_params is None:
        log.warning("tune: eval run %s ended %s without a usable winner",
                    run.id, run.status)
        return run, None
    return run, park_winner(storage, run, tenant=tenant)


# ---------------------------------------------------------------------------
# canary offline prior
# ---------------------------------------------------------------------------


def _linked_score(runs: list[EvalRun],
                  version_id: str) -> Optional[tuple[EvalRun, float]]:
    """Newest completed run whose lineage links `version_id` and whose
    winner score is defined."""
    for run in runs:
        if version_id in run.links and run.winner_score is not None:
            return run, float(run.winner_score)
    return None


def offline_prior_multiplier(
    storage: Storage,
    engine_id: str,
    candidate_version_id: str,
    live_version_id: Optional[str],
) -> tuple[float, Optional[str]]:
    """(bake multiplier, reason) for the canary verdict.

    Strict (PIO_TUNE_STRICT_BAKE) only when both versions carry lineage-
    linked eval scores on the SAME metric header and the candidate's is
    worse; 1.0 whenever the evidence is missing or incomparable — the
    prior must never be able to wedge a rollout."""
    if not env_bool("PIO_TUNE_PRIOR"):
        return 1.0, None
    factor = env_float("PIO_TUNE_STRICT_BAKE")
    if factor <= 1.0 or not live_version_id:
        return 1.0, None
    runs = EvalRecordStore(storage).list_runs(
        engine_id=engine_id, status="completed"
    )
    cand = _linked_score(runs, candidate_version_id)
    live = _linked_score(runs, live_version_id)
    if cand is None or live is None:
        return 1.0, None
    cand_run, cand_score = cand
    live_run, live_score = live
    if cand_run.metric_header != live_run.metric_header:
        return 1.0, None
    from predictionio_tpu.evalfleet.specs import resolve_metric

    try:
        metric = resolve_metric((cand_run.spec or {}).get("metric"))
    except Exception:
        return 1.0, None
    if metric.compare(cand_score, live_score) < 0:
        return factor, (
            f"offline prior: candidate {cand_run.metric_header}="
            f"{cand_score:.6g} worse than live {live_score:.6g} "
            f"(runs {cand_run.id}/{live_run.id}) -> bake x{factor:g}"
        )
    return 1.0, None
