"""Fleet-scale evaluation & auto-tuning (ISSUE 20) — the paper's E2
layer as a fleet workload, not a script.

- specs:   declarative param-space DSL + metric specs + combinable partials
- records: durable EvalRun/EvalResult family (exactly-once by record shape)
- driver:  shard fan-out on the JobQueue, live status, straggler re-dispatch
- worker:  the eval-shard subprocess entry (spawned by the scheduler)
- tuning:  winner → retrain preset → periodic retrain; canary offline prior

Import-leak contract: importing this package must not import jax — the
driver/records layers run on coordinator hosts (CI enforces this).
"""

from predictionio_tpu.evalfleet.driver import EvalDriver, EvalDriverConfig
from predictionio_tpu.evalfleet.records import EvalRecordStore, EvalRun
from predictionio_tpu.evalfleet.specs import (
    EvalSpec,
    HeldOutRMSE,
    MAPAtK,
    NDCGAtK,
    ParamAxis,
    PrecisionAtK,
    expand_points,
    group_points,
    resolve_metric,
)
from predictionio_tpu.evalfleet.tuning import (
    PresetStore,
    RetrainPreset,
    apply_preset,
    offline_prior_multiplier,
    park_winner,
    tune,
)

__all__ = [
    "EvalDriver",
    "EvalDriverConfig",
    "EvalRecordStore",
    "EvalRun",
    "EvalSpec",
    "HeldOutRMSE",
    "MAPAtK",
    "NDCGAtK",
    "ParamAxis",
    "PrecisionAtK",
    "PresetStore",
    "RetrainPreset",
    "apply_preset",
    "expand_points",
    "group_points",
    "offline_prior_multiplier",
    "park_winner",
    "resolve_metric",
    "tune",
]
