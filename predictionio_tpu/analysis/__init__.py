"""In-tree invariant analyzer + thread sanitizer (ISSUE 12).

`pio lint` runs the AST checkers in this package over the framework's
own source; `PIO_TSAN=1` arms the dynamic lock-order sanitizer
(`analysis/tsan.py`) and the pytest thread-leak tripwire
(`analysis/pytest_plugin.py`). Eleven PRs of review rounds fixed the
same defect classes by hand — state mutated outside the runtime-swap
lock, background threads never joined, raw PIO_* env reads with
divergent parsing, jit boundaries missing devprof, unbounded metric
labels; these checkers make each of them a CI failure instead of a
review comment.

Import weight: this package's __init__ is imported by the devprof and
storage RPC hot paths (for the `tsan.note_blocking` hooks), so it must
stay empty-cheap: no jax, no numpy, and no eager import of the AST
checker machinery — `lint` attributes resolve lazily.
"""

from typing import Any

_LINT_EXPORTS = (
    "Finding", "LintError", "all_rules", "lint_paths", "lint_repo",
)

__all__ = list(_LINT_EXPORTS) + ["tsan"]


def __getattr__(name: str) -> Any:
    import importlib

    if name in _LINT_EXPORTS:
        lint = importlib.import_module("predictionio_tpu.analysis.lint")
        return getattr(lint, name)
    if name == "tsan":
        return importlib.import_module("predictionio_tpu.analysis.tsan")
    raise AttributeError(name)
