"""env-knob checker (ISSUE 12).

Every ``PIO_*`` environment read must go through the typed parsers in
``utils/env.py`` and be declared in the central knob registry. Before
this, 62 knobs were parsed at ~40 sites with at least four divergent
grammars (PR-5 found bool knobs that could not parse "false"; PR-6
round 6 moved one copy to utils/env.py — this rule retires the rest).

Violations:
  * ``os.environ[...]`` / ``os.environ.get(...)`` / ``os.getenv(...)``
    with a ``PIO_*`` literal key anywhere outside utils/env.py;
  * ``<mapping>.get("PIO_*")`` on ANY receiver (captured child envs
    included — they must parse through the same grammar via the
    parsers' ``env=`` parameter);
  * dynamic ``os.environ.get(<expr>)`` reads (unauditable — route
    through ``env_raw`` so the registry check still applies);
  * parser calls (``env_str``/``env_int``/… ) naming a knob that is
    not declared in the registry.

Writes (``os.environ[k] = v``, ``.pop``, child-env dict construction)
are allowed: the rule is about divergent READ grammars.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from predictionio_tpu.analysis.lint import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    str_const,
)

RULE_NAME = "env-knobs"

PARSERS = {
    "env_str", "env_path", "env_int", "env_float", "env_opt_float",
    "env_bool", "env_flag", "env_raw",
}


def _registered(name: str) -> bool:
    from predictionio_tpu.utils.env import KNOBS

    if name in KNOBS:
        return True
    return any(k.prefix and name.startswith(k.name) for k in KNOBS.values())


def _environ_recv(node: ast.AST) -> bool:
    return dotted_name(node) in ("os.environ", "_os.environ", "environ")


def check(mod: ModuleInfo) -> Iterator[Finding]:
    if mod.path.replace("\\", "/").endswith("utils/env.py"):
        return
    for node in ast.walk(mod.tree):
        # os.environ["PIO_X"] loads
        if isinstance(node, ast.Subscript) and _environ_recv(node.value):
            if isinstance(node.ctx, ast.Load):
                key = str_const(node.slice)
                if key is None or key.startswith("PIO_"):
                    yield Finding(
                        RULE_NAME, mod.path, node.lineno,
                        f"raw os.environ[{key or '<dynamic>'}] read — "
                        "use the typed parsers in utils/env.py",
                    )
            continue
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        callee = dotted_name(fn)
        # os.getenv("PIO_X")
        if callee in ("os.getenv", "_os.getenv", "getenv"):
            key = str_const(node.args[0]) if node.args else None
            if key is None or key.startswith("PIO_"):
                yield Finding(
                    RULE_NAME, mod.path, node.lineno,
                    f"os.getenv({key or '<dynamic>'}) read — use the "
                    "typed parsers in utils/env.py",
                )
            continue
        # <recv>.get("PIO_X") — os.environ or any captured env mapping
        if isinstance(fn, ast.Attribute) and fn.attr == "get" and node.args:
            key = str_const(node.args[0])
            if _environ_recv(fn.value):
                if key is None or key.startswith("PIO_"):
                    yield Finding(
                        RULE_NAME, mod.path, node.lineno,
                        f"raw os.environ.get({key or '<dynamic>'}) read "
                        "— use the typed parsers in utils/env.py",
                    )
            elif key is not None and key.startswith("PIO_"):
                yield Finding(
                    RULE_NAME, mod.path, node.lineno,
                    f".get({key!r}) on a captured env mapping — pass "
                    "the mapping to a utils/env.py parser (env=...) so "
                    "one grammar parses every knob",
                )
            continue
        # parser calls must name registered knobs
        base = callee.rsplit(".", 1)[-1] if callee else ""
        if base in PARSERS:
            key = str_const(node.args[0]) if node.args else None
            if key is None:
                kw = next(
                    (k.value for k in node.keywords if k.arg == "name"),
                    None,
                )
                key = str_const(kw) if kw is not None else None
            if key is not None and not _registered(key):
                yield Finding(
                    RULE_NAME, mod.path, node.lineno,
                    f"env knob {key!r} is not declared in the "
                    "utils/env.py registry (name, type, default, doc)",
                )


RULE = Rule(
    RULE_NAME,
    "PIO_* reads go through utils/env.py parsers + the knob registry",
    check,
)
