"""`pio lint` core: file discovery, comment annotations, suppression,
rule registry, and the findings pipeline (ISSUE 12).

Each checker module registers one Rule over a parsed ModuleInfo —
source, AST, parent map, and the comment-derived annotations:

  ``# lint: disable=<rule>[,<rule>]``   suppress on that line; a
                                        whole-line comment suppresses
                                        the rule file-wide
  ``# lint: holds=<lock>``              on a def line: callers hold
                                        <lock>, so guarded mutations
                                        inside count as locked
  ``# guarded-by: <lock>[|<lock>]``     on a self.<attr> assignment:
                                        the attr may only be mutated
                                        under one of the named locks
  ``# label-bound: <why>``              on a labeled metric-family
                                        creation: names the mechanism
                                        bounding the label values

Suppressions are expected to carry a justification after the rule list
(``# lint: disable=thread-lifecycle — self-stop from handler``); the
checker does not parse the prose, reviewers do.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator, Optional

DISABLE_RE = re.compile(r"#\s*lint:\s*disable=([a-z0-9_\-]+(?:\s*,\s*[a-z0-9_\-]+)*)")
HOLDS_RE = re.compile(r"#\s*lint:\s*holds=([A-Za-z0-9_]+(?:\s*[|,]\s*[A-Za-z0-9_]+)*)")
GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z0-9_]+(?:\s*[|,]\s*[A-Za-z0-9_]+)*)")
LABEL_BOUND_RE = re.compile(r"#\s*label-bound:\s*(\S.*)")


class LintError(RuntimeError):
    """A module could not be analyzed (syntax error, unreadable file)."""


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "message": self.message,
        }


@dataclass
class ModuleInfo:
    """One parsed source file + everything the checkers share."""

    path: str  # as passed (repo-relative in CI/console runs)
    source: str
    tree: ast.Module
    comments: dict[int, str] = field(default_factory=dict)
    #: rules disabled for the whole file (whole-line disable comments)
    file_disabled: set[str] = field(default_factory=set)
    #: line → rules disabled on that line (trailing disable comments)
    line_disabled: dict[int, set[str]] = field(default_factory=dict)
    #: line → lock names an attr on that line is guarded by
    guarded: dict[int, tuple[str, ...]] = field(default_factory=dict)
    #: line → lock names a def on that line declares its callers hold
    holds: dict[int, tuple[str, ...]] = field(default_factory=dict)
    #: lines carrying a `# label-bound:` annotation
    label_bound: set[int] = field(default_factory=set)
    parents: dict[ast.AST, ast.AST] = field(default_factory=dict)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_disabled:
            return True
        return rule in self.line_disabled.get(line, set())


@dataclass(frozen=True)
class Rule:
    name: str
    description: str
    check: Callable[[ModuleInfo], Iterator[Finding]]


def _split_names(raw: str) -> tuple[str, ...]:
    return tuple(
        n.strip() for n in re.split(r"[|,]", raw) if n.strip()
    )


def parse_module(path: str, source: Optional[str] = None) -> ModuleInfo:
    if source is None:
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            raise LintError(f"{path}: unreadable ({e})") from e
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        raise LintError(f"{path}:{e.lineno}: syntax error: {e.msg}") from e
    mod = ModuleInfo(path=path, source=source, tree=tree)
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            mod.parents[child] = node
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except tokenize.TokenizeError:  # pragma: no cover - ast parsed already
        tokens = []
    src_lines = source.splitlines()
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        line_no, col = tok.start
        text = tok.string
        mod.comments[line_no] = text
        whole_line = src_lines[line_no - 1][:col].strip() == ""
        m = DISABLE_RE.search(text)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            if whole_line:
                mod.file_disabled |= rules
            else:
                mod.line_disabled.setdefault(line_no, set()).update(rules)
        m = GUARDED_RE.search(text)
        if m:
            mod.guarded[line_no] = _split_names(m.group(1))
        m = HOLDS_RE.search(text)
        if m:
            mod.holds[line_no] = _split_names(m.group(1))
        if LABEL_BOUND_RE.search(text):
            mod.label_bound.add(line_no)
    return mod


# -- shared AST helpers ------------------------------------------------------

def self_attr(node: ast.AST) -> Optional[str]:
    """'x' when node is `self.x`, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target ('' when not a plain name chain)."""
    return dotted_name(node.func)


def dotted_name(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def enclosing(mod: ModuleInfo, node: ast.AST,
              kinds: tuple[type, ...]) -> Optional[ast.AST]:
    cur = mod.parent(node)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = mod.parent(cur)
    return None


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# -- rule registry -----------------------------------------------------------

_RULES: Optional[list[Rule]] = None


def all_rules() -> list[Rule]:
    global _RULES
    if _RULES is None:
        from predictionio_tpu.analysis import (
            check_env,
            check_jit,
            check_locks,
            check_metrics,
            check_threads,
        )

        _RULES = [
            check_threads.RULE,
            check_locks.RULE,
            check_env.RULE,
            check_jit.RULE,
            check_metrics.RULE,
        ]
    return _RULES


def discover_files(root: str) -> list[str]:
    """All .py files under `root` (or `root` itself when it is a file)."""
    if os.path.isfile(root):
        return [root]
    found: list[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d not in ("__pycache__", ".git")
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                found.append(os.path.join(dirpath, fn))
    return found


def lint_paths(
    paths: Iterable[str],
    rules: Optional[Iterable[Rule]] = None,
) -> tuple[list[Finding], list[str]]:
    """Run `rules` (default: all) over every .py under `paths`.

    Returns (findings, errors): suppressed findings are filtered here,
    unparseable files surface as error strings, not exceptions — one
    bad file must not hide the rest of the report."""
    rules = list(rules) if rules is not None else all_rules()
    findings: list[Finding] = []
    errors: list[str] = []
    for root in paths:
        for path in discover_files(root):
            try:
                mod = parse_module(path)
            except LintError as e:
                errors.append(str(e))
                continue
            for rule in rules:
                try:
                    found = list(rule.check(mod))
                except Exception as e:  # checker bug: loud, not fatal
                    errors.append(
                        f"{path}: checker {rule.name} crashed: {e!r}"
                    )
                    continue
                findings.extend(
                    f for f in found if not mod.suppressed(f.rule, f.line)
                )
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, errors


def package_root() -> str:
    import predictionio_tpu

    return os.path.dirname(os.path.abspath(predictionio_tpu.__file__))


def lint_repo(
    rules: Optional[Iterable[Rule]] = None,
) -> tuple[list[Finding], list[str]]:
    """Lint the installed predictionio_tpu package (the CI gate)."""
    return lint_paths([package_root()], rules)
