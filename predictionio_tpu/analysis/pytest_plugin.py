"""pytest integration for the thread sanitizer (ISSUE 12).

Wired through tests/conftest.py (so plain ``python -m pytest tests/``
picks it up with no -p flag): with ``PIO_TSAN=1`` in the environment,
``pytest_configure`` arms the lock-order sanitizer before any test
runs, and ``pytest_sessionfinish`` runs the thread-leak tripwire,
writes the JSON findings report (``PIO_TSAN_REPORT`` path, default
``tsan-report.json``), and FAILS the session (exit 3) on any finding —
the CI "zero sanitizer findings on the concurrency suites" gate.

Without PIO_TSAN both hooks are no-ops; tier-1 runs are unaffected.
"""

from __future__ import annotations

from predictionio_tpu.analysis import tsan
from predictionio_tpu.utils.env import env_flag

#: exit code a sanitizer finding turns the session into
TSAN_EXIT_CODE = 3


def pytest_configure(config) -> None:
    if env_flag("PIO_TSAN"):
        tsan.enable()


def pytest_sessionfinish(session, exitstatus) -> None:
    if not tsan.enabled():
        return
    rep = tsan.report()
    path = tsan.write_report(report_dict=rep)
    tw = getattr(session.config, "get_terminal_writer", lambda: None)()
    lines = [
        "",
        f"tsan: {rep['edges_total']} lock-order edges, "
        f"{len(rep['lock_order_cycles'])} cycles, "
        f"{len(rep['blocking_with_lock_held'])} blocked-while-holding, "
        f"{len(rep['leaked_threads'])} leaked threads "
        f"(report: {path})",
    ]
    for cyc in rep["lock_order_cycles"]:
        lines.append(f"tsan: CYCLE between {', '.join(cyc['sites'])}")
    for b in rep["blocking_with_lock_held"]:
        lines.append(
            f"tsan: BLOCKED on {b['kind']} holding "
            f"{', '.join(b['held_sites'])} (x{b['count']})"
        )
    for t in rep["leaked_threads"]:
        lines.append(f"tsan: LEAKED thread {t['name']!r}")
    text = "\n".join(lines)
    if tw is not None:
        tw.line(text)
    else:  # pragma: no cover - ancient pytest
        print(text)
    if rep["findings_count"]:
        session.exitstatus = TSAN_EXIT_CODE
