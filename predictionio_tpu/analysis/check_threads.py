"""thread-lifecycle checker (ISSUE 12).

Every ``threading.Thread(...)`` spawn site must be (1) daemon=True —
a crash elsewhere must never hang process exit on a worker loop —
(2) named — the CI no-leaked-threads guards and the tsan tripwire
identify threads by name — and (3) owned: reachable from something
that joins it. "Owned" is checked lexically:

  * assigned to ``self.<attr>``: the enclosing class must define a
    stop-like method (stop/close/shutdown/detach/stop_all/drain) AND
    contain a ``.join(...)`` call somewhere — the PR-8/9 discipline
    where every background thread joins on its owner's stop().
  * assigned to a local: the same function must ``.join()`` it, or
    append it to a ``self.<attr>`` collection of an owning class (the
    tracked-stray pattern).
  * anything else is a fire-and-forget thread — the exact leak class
    tier1.yml's no-leaked-threads step catches dynamically — and needs
    an explicit ``# lint: disable=thread-lifecycle`` with justification.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from predictionio_tpu.analysis.lint import (
    Finding,
    ModuleInfo,
    Rule,
    enclosing,
    self_attr,
)

RULE_NAME = "thread-lifecycle"
STOP_NAMES = {"stop", "close", "shutdown", "detach", "stop_all", "drain"}


def _is_thread_ctor(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr == "Thread":
        return isinstance(fn.value, ast.Name) and fn.value.id == "threading"
    return isinstance(fn, ast.Name) and fn.id == "Thread"


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _class_joins(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
        ):
            return True
    return False


def _class_has_stop(cls: ast.ClassDef) -> bool:
    return any(
        isinstance(n, ast.FunctionDef) and n.name in STOP_NAMES
        for n in cls.body
    )


def _local_join_or_tracked(
    fn: ast.AST, var: str
) -> bool:
    """var.join(...) in the same function, or var appended/added to a
    self.<attr> container (owner tracks it for a later join)."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        if (
            f.attr == "join"
            and isinstance(f.value, ast.Name)
            and f.value.id == var
        ):
            return True
        if f.attr in ("append", "add") and any(
            isinstance(a, ast.Name) and a.id == var for a in node.args
        ):
            if self_attr(f.value) is not None:
                return True
    return False


def check(mod: ModuleInfo) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
            continue
        line = node.lineno
        name_kw = _kw(node, "name")
        daemon_kw = _kw(node, "daemon")
        if name_kw is None:
            yield Finding(
                RULE_NAME, mod.path, line,
                "thread spawned without name= — leak guards and the "
                "sanitizer tripwire identify threads by name",
            )
        if not (
            isinstance(daemon_kw, ast.Constant) and daemon_kw.value is True
        ):
            yield Finding(
                RULE_NAME, mod.path, line,
                "thread spawned without daemon=True — a non-daemon "
                "worker loop hangs process exit on any crash",
            )
        parent = mod.parent(node)
        owner_attr = None
        local_var = None
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            target = parent.targets[0]
            owner_attr = self_attr(target)
            if isinstance(target, ast.Name):
                local_var = target.id
        if owner_attr is not None:
            cls = enclosing(mod, node, (ast.ClassDef,))
            if cls is not None and _class_has_stop(cls) and _class_joins(cls):
                continue
            yield Finding(
                RULE_NAME, mod.path, line,
                f"thread stored on self.{owner_attr} but the enclosing "
                "class has no stop()/join() path — background threads "
                "must be joined by their owner's stop",
            )
        elif local_var is not None:
            fn = enclosing(
                mod, node, (ast.FunctionDef, ast.AsyncFunctionDef)
            )
            if fn is not None and _local_join_or_tracked(fn, local_var):
                continue
            yield Finding(
                RULE_NAME, mod.path, line,
                f"thread bound to local {local_var!r} is never joined "
                "or tracked on an owner — it leaks past its spawner",
            )
        else:
            yield Finding(
                RULE_NAME, mod.path, line,
                "fire-and-forget thread: not assigned to an owner and "
                "never joined",
            )


RULE = Rule(
    RULE_NAME,
    "threading.Thread sites must be daemon+named and joined by an owner",
    check,
)
