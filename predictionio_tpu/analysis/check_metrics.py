"""metric-cardinality checker (ISSUE 12).

Prometheus-style label sets are per-value time series: an unbounded
label value (raw path, client-chosen id, exception text) grows the
registry — and everything scraping it — without limit. The PR-2
route-label table and the PR-6 tenant cap exist precisely to bound
this; the rule makes the bound a declared, checkable property:

  * every labeled metric-family creation
    (``registry.counter/gauge/histogram(..., ("route", ...))``) must
    carry a ``# label-bound: <mechanism>`` annotation within the call's
    line span naming what bounds the values (route-label table, tenant
    cap + (other) overflow, literal set, ...);
  * label VALUES at ``.inc/.set/.dec/.observe`` call sites must not be
    built by string construction (f-strings, ``+``/``%``/``.format``) —
    a constructed value is unbounded by construction; route it through
    the bounding table first.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from predictionio_tpu.analysis.lint import (
    Finding,
    ModuleInfo,
    Rule,
)

RULE_NAME = "metric-cardinality"

FAMILY_CTORS = {"counter", "gauge", "histogram"}
FEEDERS = {"inc", "set", "dec", "observe"}


def _labelnames_arg(call: ast.Call) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == "labelnames":
            return kw.value
    if len(call.args) >= 3:
        return call.args[2]
    return None


def _is_nonempty_literal(node: ast.expr) -> bool:
    return isinstance(node, (ast.Tuple, ast.List)) and bool(node.elts)


def _constructed(node: ast.expr) -> Optional[str]:
    """Describe the string-construction shape, or None when clean."""
    if isinstance(node, ast.JoinedStr):
        return "f-string"
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.Add, ast.Mod)
    ):
        return "string concatenation/format"
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "format"
    ):
        return ".format()"
    return None


def check(mod: ModuleInfo) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        if fn.attr in FAMILY_CTORS:
            labelnames = _labelnames_arg(node)
            if labelnames is None or (
                isinstance(labelnames, (ast.Tuple, ast.List))
                and not labelnames.elts
            ):
                continue
            if not _is_nonempty_literal(labelnames) and not isinstance(
                labelnames, ast.Name
            ):
                continue  # not a metric-family shape (e.g. dict.update)
            end = getattr(node, "end_lineno", node.lineno)
            span = range(node.lineno - 1, end + 2)
            if not any(ln in mod.label_bound for ln in span):
                yield Finding(
                    RULE_NAME, mod.path, node.lineno,
                    "labeled metric family without a `# label-bound:` "
                    "annotation — declare what bounds the label values "
                    "(route table, tenant cap, literal set, ...)",
                )
        elif fn.attr in FEEDERS and node.keywords:
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                shape = _constructed(kw.value)
                if shape is not None:
                    yield Finding(
                        RULE_NAME, mod.path, node.lineno,
                        f"label {kw.arg!r} fed a {shape}-constructed "
                        "value — unbounded by construction; route it "
                        "through the bounding table first",
                    )


RULE = Rule(
    RULE_NAME,
    "labeled metric families declare their bound; no constructed "
    "label values at feed sites",
    check,
)
