"""lock-discipline checker (ISSUE 12).

Attributes declared ``# guarded-by: <lock>`` (on their assignment line,
conventionally in ``__init__``) may only be MUTATED inside a
``with self.<lock>:`` block in the same class — the static version of
the runtime-swap-lock / cache-entries discipline that PR-5/6/9 review
rounds re-litigated by hand. Reads stay unchecked (snapshot-read
patterns are legitimate); the annotation may name alternatives
(``# guarded-by: _lock|_not_empty``) for Condition wrappers that hold
the same underlying lock.

Methods whose callers hold the lock declare it on the def line with
``# lint: holds=<lock>``. ``__init__`` is exempt: the object is not
shared yet. The check is lexical and per-class — mutations reached
through another object's reference are the dynamic sanitizer's job.
"""

from __future__ import annotations

import ast
from typing import Iterator

from predictionio_tpu.analysis.lint import (
    Finding,
    ModuleInfo,
    Rule,
    self_attr,
)

RULE_NAME = "lock-discipline"

#: method calls that mutate their receiver in place
MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "discard",
    "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "rotate", "sort", "reverse",
}


def _guard_decls(mod: ModuleInfo, cls: ast.ClassDef) -> dict[str, tuple[str, ...]]:
    """attr name → lock names, from `# guarded-by:` comments on
    self.<attr> assignment lines anywhere in the class body."""
    guards: dict[str, tuple[str, ...]] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        locks = mod.guarded.get(node.lineno)
        if not locks:
            continue
        for t in targets:
            attr = self_attr(t)
            if attr is not None:
                guards[attr] = locks
    return guards


class _MethodVisitor(ast.NodeVisitor):
    def __init__(self, mod: ModuleInfo, cls_name: str,
                 guards: dict[str, tuple[str, ...]],
                 held: tuple[str, ...]):
        self.mod = mod
        self.cls_name = cls_name
        self.guards = guards
        self.held: list[str] = list(held)
        self.findings: list[Finding] = []

    # -- lock tracking ---------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            attr = self_attr(item.context_expr)
            if attr is not None:
                self.held.append(attr)
                pushed += 1
        self.generic_visit(node)
        for _ in range(pushed):
            self.held.pop()

    # -- mutations -------------------------------------------------------
    def _flag(self, attr: str, line: int, what: str) -> None:
        locks = self.guards[attr]
        want = " or ".join(f"self.{lk}" for lk in locks)
        self.findings.append(Finding(
            RULE_NAME, self.mod.path, line,
            f"{self.cls_name}.{attr} is guarded-by {'|'.join(locks)} "
            f"but {what} outside `with {want}`",
        ))

    def _check_target(self, target: ast.AST, line: int) -> None:
        attr = self_attr(target)
        if attr in self.guards and not set(self.guards[attr]) & set(self.held):
            self._flag(attr, line, "assigned")
        if isinstance(target, (ast.Subscript, ast.Attribute)) and not (
            attr is not None
        ):
            inner = self_attr(target.value) if isinstance(
                target, (ast.Subscript, ast.Attribute)
            ) else None
            if inner in self.guards and not (
                set(self.guards[inner]) & set(self.held)
            ):
                self._flag(inner, line, "item-assigned")
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._check_target(elt, line)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._check_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._check_target(t, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in MUTATORS:
            attr = self_attr(fn.value)
            if attr in self.guards and not (
                set(self.guards[attr]) & set(self.held)
            ):
                self._flag(attr, node.lineno, f".{fn.attr}() called")
        self.generic_visit(node)

    # nested defs: visited with the current lexical held-stack — a
    # closure built under the lock but run later is a known blind spot
    # the dynamic sanitizer covers


def check(mod: ModuleInfo) -> Iterator[Finding]:
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guards = _guard_decls(mod, cls)
        if not guards:
            continue
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name == "__init__":
                continue  # not shared yet
            held = mod.holds.get(item.lineno, ())
            visitor = _MethodVisitor(mod, cls.name, guards, held)
            for stmt in item.body:
                visitor.visit(stmt)
            yield from visitor.findings


RULE = Rule(
    RULE_NAME,
    "# guarded-by: attrs may only be mutated under their declared lock",
    check,
)
