"""jit-boundary checker (ISSUE 12).

Two invariants over the device-dispatch surface:

1. Every top-level jit boundary — a module-level def decorated with
   ``jax.jit`` / ``partial(jax.jit, ...)`` (or a module-level
   ``name = jax.jit(...)`` / ``name = pl.pallas_call(...)`` binding) —
   must pass through ``devprof.instrument``: the profiler hooks ONLY
   wrapped call sites, so an uninstrumented boundary silently vanishes
   from FLOPs/MFU/HBM accounting (the PR-3 contract). A def containing
   a ``pallas_call`` must itself be jitted or called from a jitted def
   in the same module — a bare pallas launch bypasses both XLA's
   dispatch cache and the profiler.

2. No wall-clock or host-RNG calls inside jitted bodies: ``time.*``,
   ``datetime.*``, ``random.*``, ``np.random.*`` execute ONCE at trace
   time and bake a constant into the compiled program — the classic
   silent-staleness bug. Use traced arguments or ``jax.random``.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from predictionio_tpu.analysis.lint import (
    Finding,
    ModuleInfo,
    Rule,
    call_name,
    dotted_name,
)

RULE_NAME = "jit-boundary"

JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit", "pjit.pjit"}
HOST_CALL_PREFIXES = (
    "time.time", "time.perf_counter", "time.monotonic", "time.time_ns",
    "datetime.", "random.", "np.random.", "numpy.random.",
)


def _is_jit_decorator(dec: ast.expr) -> bool:
    name = dotted_name(dec)
    if name in JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fn_name = call_name(dec)
        if fn_name in JIT_NAMES:
            return True
        if fn_name.endswith("partial") and dec.args:
            return dotted_name(dec.args[0]) in JIT_NAMES
    return False


def _jit_value_call(value: ast.expr) -> Optional[str]:
    """'jit' / 'pallas_call' when value is jax.jit(...) / pallas_call(...)."""
    if not isinstance(value, ast.Call):
        return None
    name = call_name(value)
    if name in JIT_NAMES:
        return "jit"
    if name.split(".")[-1] == "pallas_call":
        return "pallas_call"
    return None


def _instrumented_names(tree: ast.Module) -> set[str]:
    """Names passed (as args or kwargs) to any *.instrument(...) call."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if not name.rsplit(".", 1)[-1] == "instrument":
            continue
        for arg in list(node.args) + [k.value for k in node.keywords]:
            if isinstance(arg, ast.Name):
                out.add(arg.id)
    return out


def _contains_pallas_call(fn: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Call)
        and call_name(n).split(".")[-1] == "pallas_call"
        for n in ast.walk(fn)
    )


def _called_names(fn: ast.AST) -> set[str]:
    return {
        call_name(n).split(".", 1)[0]
        for n in ast.walk(fn)
        if isinstance(n, ast.Call)
    }


def check(mod: ModuleInfo) -> Iterator[Finding]:
    instrumented = _instrumented_names(mod.tree)
    module_fns: dict[str, ast.FunctionDef] = {}
    jitted: dict[str, ast.FunctionDef] = {}
    bound_jits: dict[str, ast.stmt] = {}  # name = jax.jit(...) / pallas_call
    for node in mod.tree.body:
        if isinstance(node, ast.FunctionDef):
            module_fns[node.name] = node
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                jitted[node.name] = node
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if isinstance(target, ast.Name) and _jit_value_call(node.value):
                bound_jits[target.id] = node

    # (1a) jitted defs + jit/pallas bindings must be instrumented
    for name, fn in jitted.items():
        if name not in instrumented:
            yield Finding(
                RULE_NAME, mod.path, fn.lineno,
                f"jitted function {name!r} never passes through "
                "devprof.instrument — this boundary is invisible to "
                "FLOPs/MFU/HBM accounting",
            )
    for name, stmt in bound_jits.items():
        if name not in instrumented:
            yield Finding(
                RULE_NAME, mod.path, stmt.lineno,
                f"module-level jit/pallas binding {name!r} never passes "
                "through devprof.instrument",
            )

    # (1b) pallas_call sites must sit under a jitted entry point:
    # compute reachability from jitted defs through same-module calls
    reachable = set(jitted)
    frontier = list(jitted.values())
    while frontier:
        fn = frontier.pop()
        for callee in _called_names(fn):
            if callee in module_fns and callee not in reachable:
                reachable.add(callee)
                frontier.append(module_fns[callee])
    for name, fn in module_fns.items():
        if not _contains_pallas_call(fn):
            continue
        if name in reachable or name in instrumented:
            continue
        yield Finding(
            RULE_NAME, mod.path, fn.lineno,
            f"{name!r} launches a pallas_call but is neither jitted nor "
            "called from a jitted def in this module — the launch "
            "bypasses the dispatch cache and the profiler",
        )

    # (2) host wall-clock / RNG inside jitted bodies
    for name, fn in jitted.items():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            if cname.startswith("jax.") or cname.startswith("jnp."):
                continue
            if any(cname.startswith(p) for p in HOST_CALL_PREFIXES):
                yield Finding(
                    RULE_NAME, mod.path, node.lineno,
                    f"host call {cname}() inside jitted {name!r} runs "
                    "once at trace time and bakes a constant into the "
                    "compiled program",
                )


RULE = Rule(
    RULE_NAME,
    "jit boundaries route through devprof.instrument; no host "
    "clock/RNG inside jitted bodies",
    check,
)
