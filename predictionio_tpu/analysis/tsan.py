"""Dynamic thread sanitizer (ISSUE 12): lock-order graph + tripwires.

``PIO_TSAN=1`` (via the pytest plugin or an explicit ``enable()``)
patches ``threading.Lock``/``threading.RLock`` so every lock created
AFTERWARD is a recording proxy. Each acquisition appends the lock's
creation site to a per-thread held-stack; first-time (held → acquired)
pairs become edges in a global lock-order graph with a captured stack.
At report time:

  * cycles in the graph (an AB/BA inversion somewhere in the run) are
    potential deadlocks — the exact class the FairQueue/mux/cache lock
    nest could produce;
  * ``note_blocking(kind)`` hooks — called from the devprof dispatch
    wrapper and the storage RPC client — record any locks held across
    device dispatch or blocking I/O (a held lock there serializes the
    whole server behind one slow call);
  * the thread-leak tripwire diffs ``threading.enumerate()`` against
    the enable-time baseline: threads still alive at session end were
    never joined by their owner.

Locks are keyed by CREATION SITE (file:line), not instance, so an
inversion between two instances of the same two classes is still
caught; edges between two instances from the SAME site are ignored
(per-entry locks from one constructor line would self-cycle falsely).

Overhead when disabled: ``note_blocking`` is one attribute load + a
falsy check; no locks are wrapped. The proxies survive ``disable()``
(recording just stops), so tests can enable/disable freely.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import traceback
from typing import Any, Optional

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

#: modules whose frames are skipped when attributing a creation site
_SKIP_FILES = (os.sep + "threading.py", __file__)


class _State:
    def __init__(self) -> None:
        self.enabled = False
        # held-site -> acquired-site -> {"stack": [...], "count": n}
        self.graph: dict[str, dict[str, dict]] = {}
        # (kind, held-sites) -> {"stack": [...], "count": n}
        self.blocking: dict[tuple[str, tuple[str, ...]], dict] = {}
        self.allowed_blocking: set[str] = set()
        self.baseline_threads: set[int] = set()
        self.mu = _REAL_LOCK()
        self.tl = threading.local()

    def held(self) -> list:
        stack = getattr(self.tl, "stack", None)
        if stack is None:
            stack = self.tl.stack = []
        return stack


_state = _State()


def _caller_site() -> str:
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not any(fn.endswith(s) or fn == s for s in _SKIP_FILES):
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _stack_lines(limit: int = 14) -> list[str]:
    raw = traceback.format_stack(limit=limit)
    return [ln.rstrip() for ln in raw[:-2]]


class _SanLock:
    """Recording proxy over one real Lock/RLock instance."""

    def __init__(self, inner: Any, site: str):
        self._inner = inner
        self._site = site

    # -- core protocol ---------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok and _state.enabled:
            _record_acquire(self._site)
        return ok

    def release(self) -> None:
        if _state.enabled:
            _record_release(self._site)
        self._inner.release()

    def __enter__(self) -> "_SanLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return bool(locked()) if locked is not None else False

    # -- Condition compatibility ----------------------------------------
    # Condition(lock) PROBES for _release_save/_acquire_restore/_is_owned
    # at construction and falls back to proxy.acquire/release when they
    # are absent — so delegation must preserve absence: a plain Lock has
    # none of them, and defining them here would hand Condition methods
    # that raise at wait() time. RLocks get direct delegation (the
    # held-stack intentionally keeps the site across a wait; the thread
    # records nothing while blocked and is consistent after reacquire).
    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<tsan {self._inner!r} @ {self._site}>"


def _record_acquire(site: str) -> None:
    held = _state.held()
    if site not in held:
        new_edges = [h for h in held if h != site]
        if new_edges:
            with _state.mu:
                for h in new_edges:
                    edges = _state.graph.setdefault(h, {})
                    info = edges.get(site)
                    if info is None:
                        edges[site] = {
                            "stack": _stack_lines(), "count": 1,
                        }
                    else:
                        info["count"] += 1
    held.append(site)


def _record_release(site: str) -> None:
    held = _state.held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == site:
            del held[i]
            return


def _lock_factory(*args: Any, **kwargs: Any) -> Any:
    inner = _REAL_LOCK(*args, **kwargs)
    if not _state.enabled:
        return inner
    return _SanLock(inner, _caller_site())


def _rlock_factory(*args: Any, **kwargs: Any) -> Any:
    inner = _REAL_RLOCK(*args, **kwargs)
    if not _state.enabled:
        return inner
    return _SanLock(inner, _caller_site())


# -- public API --------------------------------------------------------------

def enabled() -> bool:
    return _state.enabled


def enable() -> None:
    """Patch the lock constructors and baseline the live thread set."""
    if _state.enabled:
        return
    _state.enabled = True
    threading.Lock = _lock_factory  # type: ignore[misc]
    threading.RLock = _rlock_factory  # type: ignore[misc]
    _state.baseline_threads = {t.ident for t in threading.enumerate()}


def disable() -> None:
    """Stop recording and restore the real constructors. Existing
    proxies keep working (recording is gated per-call)."""
    _state.enabled = False
    threading.Lock = _REAL_LOCK  # type: ignore[misc]
    threading.RLock = _REAL_RLOCK  # type: ignore[misc]


def reset() -> None:
    """Drop all recorded state (test isolation)."""
    with _state.mu:
        _state.graph.clear()
        _state.blocking.clear()
        _state.allowed_blocking.clear()


def allow_blocking(site_substring: str) -> None:
    """Declare a lock (by creation-site substring) EXPECTED to be held
    across device dispatch — e.g. a stage lock whose whole job is
    'one staging, many waiters'. The owner of the lock declares this,
    never the code that happens to trip it."""
    with _state.mu:
        _state.allowed_blocking.add(site_substring)


def allow_blocking_lock(lock: Any) -> None:
    """Instance form of `allow_blocking`: the owner passes the lock it
    just created. No-op when the sanitizer is off (the lock is then a
    plain threading lock with no site)."""
    site = getattr(lock, "_site", None)
    if site is not None:
        allow_blocking(site)


def note_blocking(kind: str) -> None:
    """Hot-path hook: called where the thread is about to block on
    device dispatch or remote I/O. Near-zero cost when disabled."""
    if not _state.enabled:
        return
    held = getattr(_state.tl, "stack", None)
    if not held:
        return
    sites = tuple(held)
    with _state.mu:
        live = [
            s for s in sites
            if not any(sub in s for sub in _state.allowed_blocking)
        ]
        if not live:
            return
        key = (kind, tuple(live))
        info = _state.blocking.get(key)
        if info is None:
            _state.blocking[key] = {"stack": _stack_lines(), "count": 1}
        else:
            info["count"] += 1


def _find_cycles(graph: dict[str, dict[str, dict]]) -> list[list[str]]:
    """Strongly-connected components of size > 1 (plus self-loops):
    every lock-order inversion lives inside one."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    counter = [0]
    sccs: list[list[str]] = []

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(graph.get(v, {}))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, {})))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1 or node in graph.get(node, {}):
                    sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


def leaked_threads() -> list[dict]:
    """Threads alive now that were not alive at enable() time —
    anything here outlived whatever spawned it without being joined."""
    current = threading.current_thread()
    out = []
    for t in threading.enumerate():
        if t.ident in _state.baseline_threads or t is current:
            continue
        if not t.is_alive():
            continue
        out.append({"name": t.name, "daemon": t.daemon})
    return sorted(out, key=lambda d: d["name"])


def report(check_leaks: bool = True) -> dict:
    """JSON-able findings report (the `pio lint --tsan-report` payload)."""
    with _state.mu:
        graph = {
            h: {a: dict(info) for a, info in edges.items()}
            for h, edges in _state.graph.items()
        }
        blocking = [
            {
                "kind": kind,
                "held_sites": list(sites),
                "stack": info["stack"],
                "count": info["count"],
            }
            for (kind, sites), info in sorted(_state.blocking.items())
        ]
    cycles = []
    for comp in _find_cycles(graph):
        edges = []
        for a in comp:
            for b, info in graph.get(a, {}).items():
                if b in comp:
                    edges.append({
                        "from": a, "to": b, "count": info["count"],
                        "stack": info["stack"],
                    })
        cycles.append({"sites": comp, "edges": edges})
    leaks = leaked_threads() if check_leaks else []
    return {
        "enabled": _state.enabled,
        "edges_total": sum(len(e) for e in graph.values()),
        "lock_order_cycles": cycles,
        "blocking_with_lock_held": blocking,
        "leaked_threads": leaks,
        "findings_count": len(cycles) + len(blocking) + len(leaks),
    }


def write_report(path: Optional[str] = None,
                 check_leaks: bool = True,
                 report_dict: Optional[dict] = None) -> str:
    """Dump the findings as JSON; returns the path written. Pass
    `report_dict` to write an already-computed snapshot (the pytest
    plugin decides exit status and writes from ONE report, so the
    JSON can never disagree with the console summary)."""
    if not path:
        from predictionio_tpu.utils.env import env_path

        path = env_path("PIO_TSAN_REPORT") or "tsan-report.json"
    rep = report_dict if report_dict is not None else report(
        check_leaks=check_leaks
    )
    with open(path, "w", encoding="utf-8") as f:
        json.dump(rep, f, indent=2, sort_keys=True)
    return path
