"""Friend-recommendation engine: keyword-profile similarity scoring.

Reference: examples/experimental/scala-local-friend-recommendation —
KeywordSimilarityAlgorithm.scala:14-67: users and items carry sparse
keyword→weight profiles; confidence(user, item) = Σ_k w_user[k]·w_item[k]
and acceptance = (weight·confidence ≥ threshold). The reference reads
profiles from flat files; here they are $set entity properties in the
event store (the PropertyMap road the framework already paves), and
batched scoring is ONE device matmul-row pass over dense
(n, |keyword vocab|) profile matrices instead of per-pair HashMap loops.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from functools import lru_cache
from typing import Optional

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    SanityCheck,
)
from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.data.store.bimap import BiMap
from predictionio_tpu.data.store.event_store import EventStoreFacade


@dataclass
class Query:
    user: str
    item: str


@dataclass
class PredictedResult:
    confidence: float = 0.0
    acceptance: bool = False


@dataclass
class DataSourceParams:
    app_name: str
    user_entity_type: str = "user"
    item_entity_type: str = "item"
    keyword_prop: str = "keywords"  # property: {keyword: weight, ...}


@dataclass
class TrainingData(SanityCheck):
    user_vocab: BiMap
    item_vocab: BiMap
    user_rows: list  # list[dict[kw_idx, weight]]
    item_rows: list
    n_keywords: int

    def sanity_check(self) -> None:
        if not self.user_rows or not self.item_rows:
            raise ValueError("no keyword profiles found on users/items")


class FriendRecDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        store = EventStoreFacade(ctx.storage)
        kw_vocab: dict[str, int] = {}

        def read(entity_type):
            props = store.aggregate_properties(
                app_name=self.params.app_name, entity_type=entity_type
            )
            ids: dict[str, int] = {}
            rows: list[dict] = []
            for ent_id, pmap in props.items():
                kw = pmap.get(self.params.keyword_prop)
                if not isinstance(kw, dict):
                    continue
                ids[ent_id] = len(rows)
                row = {}
                for k, v in kw.items():
                    kw_vocab.setdefault(str(k), len(kw_vocab))
                    row[kw_vocab[str(k)]] = float(v)
                rows.append(row)
            return BiMap(ids), rows

        user_vocab, user_rows = read(self.params.user_entity_type)
        item_vocab, item_rows = read(self.params.item_entity_type)
        return TrainingData(
            user_vocab=user_vocab,
            item_vocab=item_vocab,
            user_rows=user_rows,
            item_rows=item_rows,
            n_keywords=len(kw_vocab),
        )


@dataclass
class KeywordSimilarityParams:
    # reference KeywordSimilarityAlgorithm.scala:15-16 initial values
    sim_weight: float = 1.0
    threshold: float = 1.0


@dataclass
class FriendRecModel:
    user_vocab: BiMap
    item_vocab: BiMap
    user_mat: np.ndarray  # (U, K_v) float32 dense profiles
    item_mat: np.ndarray  # (I, K_v)
    sim_weight: float
    threshold: float

    def __post_init__(self):
        self._device = None
        self._stage_lock = threading.Lock()

    def __getstate__(self):
        d = dict(self.__dict__)
        d.pop("_device", None)
        d.pop("_stage_lock", None)
        return d

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._device = None
        self._stage_lock = threading.Lock()

    def device(self):
        # locked: the pipelined dispatcher (server.py pipeline_depth) can
        # run two batches for one model concurrently; double-staging would
        # transiently double the profile matrices' HBM footprint
        with self._stage_lock:
            if self._device is None:
                import jax.numpy as jnp

                self._device = (
                    jnp.asarray(self.user_mat), jnp.asarray(self.item_mat)
                )
            return self._device


@lru_cache(maxsize=1)
def _get_pair_scores():
    """Lazily-jitted (B, K_v)·(B, K_v) → (B,) row dots (jax imports stay
    off the module-import path, like every other engine)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fn(user_rows, item_rows):
        return jnp.sum(user_rows * item_rows, axis=-1)

    return fn


class KeywordSimilarityAlgorithm(Algorithm):
    def __init__(self, params: KeywordSimilarityParams):
        self.params = params

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> FriendRecModel:
        def dense(rows):
            m = np.zeros((len(rows), pd.n_keywords), dtype=np.float32)
            for i, row in enumerate(rows):
                for j, v in row.items():
                    m[i, j] = v
            return m

        return FriendRecModel(
            user_vocab=pd.user_vocab,
            item_vocab=pd.item_vocab,
            user_mat=dense(pd.user_rows),
            item_mat=dense(pd.item_rows),
            sim_weight=self.params.sim_weight,
            threshold=self.params.threshold,
        )

    def _score(self, model: FriendRecModel, pairs: np.ndarray) -> np.ndarray:
        """(B, 2) [user_idx, item_idx] → (B,) confidences, one device
        dispatch (the reference loops a HashMap per pair)."""
        um, im = model.device()
        return np.asarray(
            _get_pair_scores()(um[pairs[:, 0]], im[pairs[:, 1]])
        )

    def predict(self, model: FriendRecModel, query: Query) -> PredictedResult:
        ux = model.user_vocab.get(query.user)
        ix = model.item_vocab.get(query.item)
        if ux is None or ix is None:
            # reference behavior: unseen → confidence 0, thresholded
            conf = 0.0
        else:
            conf = float(
                self._score(model, np.array([[ux, ix]], dtype=np.int32))[0]
            )
        return PredictedResult(
            confidence=conf,
            acceptance=conf * model.sim_weight >= model.threshold,
        )

    def batch_predict(self, ctx, model: FriendRecModel, queries):
        pairs, slots = [], []
        out: list = [None] * len(queries)
        for n, (qx, q) in enumerate(queries):
            ux = model.user_vocab.get(q.user)
            ix = model.item_vocab.get(q.item)
            if ux is None or ix is None:
                out[n] = (qx, PredictedResult(
                    confidence=0.0,
                    acceptance=0.0 * model.sim_weight >= model.threshold,
                ))
            else:
                pairs.append((ux, ix))
                slots.append((n, qx))
        if pairs:
            confs = self._score(
                model, np.asarray(pairs, dtype=np.int32)
            )
            for (n, qx), c in zip(slots, confs):
                out[n] = (qx, PredictedResult(
                    confidence=float(c),
                    acceptance=float(c) * model.sim_weight >= model.threshold,
                ))
        return out


class FriendRecommendationEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            FriendRecDataSource,
            IdentityPreparator,
            {"keyword_similarity": KeywordSimilarityAlgorithm},
            FirstServing,
        )
