from predictionio_tpu.engines.friendrec.engine import FriendRecommendationEngine

__all__ = ["FriendRecommendationEngine"]
