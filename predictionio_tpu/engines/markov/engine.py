"""Markov-chain engine: next-item prediction from event sequences.

Reference: the experimental Markov demos (examples/experimental/
scala-parallel-trim-app and the Markov stock examples) built on the e2
MarkovChain kernel (e2/.../engine/MarkovChain.scala:25-89) — which until
this engine existed had no in-tree consumer.

Shape: the DataSource orders each user's events by time and emits
(item_t → item_{t+1}) transition counts; the algorithm builds the
row-normalized top-N-pruned transition matrix (e2/markov_chain.py);
serving answers "what follows item X" with the top transition targets,
optionally conditioned on a user's several recent items (their state
distribution is averaged — the reference model's vector×matrix predict).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    SanityCheck,
)
from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.data.store.bimap import BiMap
from predictionio_tpu.data.store.event_store import EventStoreFacade
from predictionio_tpu.e2.markov_chain import MarkovChain, MarkovChainModel


@dataclass
class Query:
    items: list[str] = field(default_factory=list)  # recent items, newest last
    num: int = 10


@dataclass
class ItemScore:
    item: str
    score: float


@dataclass
class PredictedResult:
    item_scores: list[ItemScore] = field(default_factory=list)


@dataclass
class DataSourceParams:
    app_name: str
    event_names: tuple[str, ...] = ("view",)
    entity_type: str = "user"


@dataclass
class TrainingData(SanityCheck):
    trans_rows: np.ndarray  # (T,) from-state idx
    trans_cols: np.ndarray  # (T,) to-state idx
    trans_counts: np.ndarray  # (T,)
    item_vocab: BiMap

    def sanity_check(self) -> None:
        if len(self.trans_rows) == 0:
            raise ValueError("no item→item transitions found")


class MarkovDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        frame = EventStoreFacade(ctx.storage).find_frame(
            app_name=self.params.app_name,
            entity_type=self.params.entity_type,
            event_names=list(self.params.event_names),
        )
        # per-user sequences ordered by event time (vectorized sort, then
        # boundaries between users — no per-event Python)
        mask = frame.target_idx >= 0
        users = frame.entity_idx[mask]
        items = frame.target_idx[mask]
        times = frame.time_ms[mask]
        order = np.lexsort((times, users))
        u, it = users[order], items[order]
        same_user = u[1:] == u[:-1]
        frm, to = it[:-1][same_user], it[1:][same_user]
        # duplicate (from, to) pairs aggregate inside MarkovChain.train's
        # np.add.at — no host-side pre-counting needed
        return TrainingData(
            trans_rows=frm.astype(np.int64),
            trans_cols=to.astype(np.int64),
            trans_counts=np.ones(len(frm), dtype=np.float64),
            item_vocab=frame.target_vocab,
        )


@dataclass
class MarkovAlgorithmParams:
    top_n: int = 50  # transition pruning per row (reference topN)


@dataclass
class MarkovModel:
    chain: MarkovChainModel
    item_vocab: BiMap


class MarkovAlgorithm(Algorithm):
    def __init__(self, params: MarkovAlgorithmParams):
        self.params = params

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> MarkovModel:
        n_states = len(pd.item_vocab)
        chain = MarkovChain.train(
            pd.trans_rows, pd.trans_cols, pd.trans_counts,
            n_states=n_states, top_n=self.params.top_n,
        )
        return MarkovModel(chain=chain, item_vocab=pd.item_vocab)

    def predict(self, model: MarkovModel, query: Query) -> PredictedResult:
        n_states = len(model.item_vocab)
        state = np.zeros(n_states, dtype=np.float32)
        known = [
            model.item_vocab.get(i)
            for i in query.items
            if model.item_vocab.get(i) is not None
        ]
        if not known:
            return PredictedResult()
        state[known] = 1.0 / len(known)
        probs = model.chain.predict(state)
        top = np.argsort(-probs)[: query.num]
        inv = model.item_vocab.inverse()
        return PredictedResult(
            item_scores=[
                ItemScore(item=inv(int(ix)), score=float(probs[ix]))
                for ix in top
                if probs[ix] > 0.0
            ]
        )


class MarkovEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            MarkovDataSource,
            IdentityPreparator,
            {"markov": MarkovAlgorithm},
            FirstServing,
        )
