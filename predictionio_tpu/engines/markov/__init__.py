from predictionio_tpu.engines.markov.engine import (
    DataSourceParams,
    ItemScore,
    MarkovAlgorithm,
    MarkovAlgorithmParams,
    MarkovDataSource,
    MarkovEngine,
    PredictedResult,
    Query,
)

__all__ = [
    "DataSourceParams",
    "ItemScore",
    "MarkovAlgorithm",
    "MarkovAlgorithmParams",
    "MarkovDataSource",
    "MarkovEngine",
    "PredictedResult",
    "Query",
]
