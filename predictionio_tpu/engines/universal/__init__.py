from predictionio_tpu.engines.universal.engine import (
    DataSourceParams,
    ItemScore,
    PredictedResult,
    Query,
    URAlgorithm,
    URAlgorithmParams,
    URDataSource,
    UniversalRecommenderEngine,
)

__all__ = [
    "DataSourceParams",
    "ItemScore",
    "PredictedResult",
    "Query",
    "URAlgorithm",
    "URAlgorithmParams",
    "URDataSource",
    "UniversalRecommenderEngine",
]
