"""Universal-Recommender-style engine: multi-event CCO + realtime history.

Reference: the ActionML Universal Recommender (external template
actionml/template-scala-parallel-universal-recommendation — the fork's
north-star workload, RELEASE.md:3; BASELINE.json configs #5). Its
prerequisites in the fork are all present here: batch events API,
SelfCleaningDataSource (core/self_cleaning.py), and
deploy-without-retraining.

Shape of the engine:
- DataSource reads one EventFrame per *indicator* event type (the first
  indicator is the PRIMARY — its targets define the recommendation item
  space) and optionally self-cleans the event store first.
- Algorithm computes, per indicator, each item's top correlators by CCO+LLR
  (models/cco.py — dense MXU matmuls, user-sharded over the mesh).
- Serving reads the user's RECENT event history live from the event store
  (the reason the reference fork needed serving-time LEventStore reads) and
  scores items by summed LLR over history hits, minus business rules.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    SanityCheck,
)
from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.core.self_cleaning import EventWindow, SelfCleaningDataSource
from predictionio_tpu.data.store.bimap import BiMap
from predictionio_tpu.data.store.event_store import EventStoreFacade
from predictionio_tpu.models import cco, ranking

log = logging.getLogger(__name__)


@dataclass
class Query:
    user: str
    num: int = 10
    blacklist: Optional[list[str]] = None
    # exclude items the user has already acted on with the primary event
    exclude_seen: bool = True


@dataclass
class ItemScore:
    item: str
    score: float


@dataclass
class PredictedResult:
    item_scores: list[ItemScore] = field(default_factory=list)


@dataclass
class DataSourceParams:
    app_name: str
    # indicator event names, PRIMARY first (UR's eventNames)
    indicators: tuple[str, ...] = ("buy", "view")
    # optional self-cleaning window: {"duration": "30 days", ...}
    event_window: Optional[dict] = None


@dataclass
class IndicatorData:
    name: str
    rows: np.ndarray  # user idx
    cols: np.ndarray  # target idx (into its own target vocab)
    target_vocab: BiMap


@dataclass
class TrainingData(SanityCheck):
    indicators: list[IndicatorData]
    n_users: int
    user_vocab: BiMap

    def sanity_check(self) -> None:
        if not self.indicators or len(self.indicators[0].rows) == 0:
            raise ValueError("no primary indicator events found")


class URDataSource(DataSource, SelfCleaningDataSource):
    def __init__(self, params: DataSourceParams):
        self.params = params
        self.app_name = params.app_name
        self.event_window = (
            EventWindow(**params.event_window) if params.event_window else None
        )

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        self.clean_persisted_events(ctx)
        store = EventStoreFacade(ctx.storage)
        frame = store.find_frame(
            app_name=self.params.app_name,
            entity_type="user",
            event_names=list(self.params.indicators),
        )
        indicators = []
        for name in self.params.indicators:
            sub = frame.where_event(name)
            mask = sub.target_idx >= 0
            # each indicator gets its own compact target vocabulary
            raw_targets = sub.target_idx[mask]
            uniq = np.unique(raw_targets)
            remap = {int(t): i for i, t in enumerate(uniq)}
            inv_frame = frame.target_vocab.inverse()
            vocab = BiMap({inv_frame(int(t)): i for t, i in remap.items()})
            indicators.append(
                IndicatorData(
                    name=name,
                    rows=sub.entity_idx[mask].astype(np.int32),
                    cols=np.asarray(
                        [remap[int(t)] for t in raw_targets], dtype=np.int32
                    ),
                    target_vocab=vocab,
                )
            )
        return TrainingData(
            indicators=indicators,
            n_users=frame.n_entities,
            user_vocab=frame.entity_vocab,
        )


# -- algorithm --------------------------------------------------------------


@dataclass
class URAlgorithmParams:
    app_name: str
    max_correlators_per_item: int = 50
    max_query_events: int = 100  # recent history depth per indicator
    indicators: Optional[tuple[str, ...]] = None  # default: all from data


@dataclass
class IndicatorModel:
    name: str
    correlator_scores: np.ndarray  # (I, top_n)
    correlator_idx: np.ndarray  # (I, top_n) into its target vocab, -1 pad
    target_vocab: BiMap


class URModel:
    def __init__(
        self,
        item_vocab: BiMap,
        indicator_models: list[IndicatorModel],
        primary_indicator: str,
    ):
        self.item_vocab = item_vocab  # primary target vocab = item space
        self.indicator_models = indicator_models
        self.primary_indicator = primary_indicator


class URAlgorithm(Algorithm):
    def __init__(self, params: URAlgorithmParams):
        self.params = params

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> URModel:
        primary = pd.indicators[0]
        n_items = len(primary.target_vocab)
        p_matrix = cco.edges_to_indicator(
            primary.rows, primary.cols, pd.n_users, n_items
        )
        wanted = self.params.indicators or tuple(i.name for i in pd.indicators)
        models = []
        for ind in pd.indicators:
            if ind.name not in wanted:
                continue
            s_matrix = cco.edges_to_indicator(
                ind.rows, ind.cols, pd.n_users, len(ind.target_vocab)
            )
            scores, idx = cco.cross_occurrence_topn(
                p_matrix,
                s_matrix,
                top_n=self.params.max_correlators_per_item,
                self_indicator=ind.name == primary.name,
                mesh=ctx.mesh,
            )
            models.append(
                IndicatorModel(
                    name=ind.name,
                    correlator_scores=scores,
                    correlator_idx=idx,
                    target_vocab=ind.target_vocab,
                )
            )
        return URModel(
            item_vocab=primary.target_vocab,
            indicator_models=models,
            primary_indicator=primary.name,
        )

    # -- serving -----------------------------------------------------------
    def _user_history(
        self,
        ctx: RuntimeContext,
        user: str,
        event_name: str,
        target_vocab: BiMap,
    ) -> np.ndarray:
        if ctx.storage is None:
            return np.empty(0, dtype=np.int64)
        store = EventStoreFacade(ctx.storage)
        try:
            events = store.find_by_entity(
                app_name=self.params.app_name,
                entity_type="user",
                entity_id=user,
                event_names=[event_name],
                limit=self.params.max_query_events,
                latest=True,
            )
            rows = []
            for e in events:
                ix = target_vocab.get(e.target_entity_id)
                if ix is not None:
                    rows.append(ix)
            return np.asarray(rows, dtype=np.int64)
        except Exception:
            log.exception("history lookup failed for %s", event_name)
            return np.empty(0, dtype=np.int64)

    def predict(self, model: URModel, query: Query) -> PredictedResult:
        ctx = self.serving_context
        n_items = len(model.item_vocab)
        scores = np.zeros(n_items, dtype=np.float32)
        for ind in model.indicator_models:
            history = self._user_history(
                ctx, query.user, ind.name, ind.target_vocab
            )
            scores += cco.score_history(
                ind.correlator_idx, ind.correlator_scores, history
            )
        # sparse exclusion set (O(history + blacklist), never a dense
        # item-space mask — catalog-scale serving stays O(B·k + history))
        exclude: list[int] = []
        if query.exclude_seen:
            # seen-filter always works in the PRIMARY item space, even when
            # the algorithm was configured to keep only secondary indicators
            primary_history = self._user_history(
                ctx, query.user, model.primary_indicator, model.item_vocab
            )
            exclude.extend(int(ix) for ix in primary_history)
        for it in query.blacklist or []:
            ix = model.item_vocab.get(it)
            if ix is not None:
                exclude.append(ix)
        inv = model.item_vocab.inverse()
        return PredictedResult(
            item_scores=[
                ItemScore(item=inv(int(ix)), score=float(scores[ix]))
                # positive_only: zero LLR evidence is not a recommendation
                for ix in ranking.top_k_filtered(
                    scores, query.num, exclude_idx=exclude, positive_only=True
                )
            ]
        )


class UniversalRecommenderEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            URDataSource,
            IdentityPreparator,
            {"ur": URAlgorithm},
            FirstServing,
        )
