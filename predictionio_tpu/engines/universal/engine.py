"""Universal-Recommender-style engine: multi-event CCO + realtime history.

Reference: the ActionML Universal Recommender (external template
actionml/template-scala-parallel-universal-recommendation — the fork's
north-star workload, RELEASE.md:3; BASELINE.json configs #5). Its
prerequisites in the fork are all present here: batch events API,
SelfCleaningDataSource (core/self_cleaning.py), and
deploy-without-retraining.

Shape of the engine:
- DataSource reads one EventFrame per *indicator* event type (the first
  indicator is the PRIMARY — its targets define the recommendation item
  space) and optionally self-cleans the event store first.
- Algorithm computes, per indicator, each item's top correlators by CCO+LLR
  (models/cco.py — dense MXU matmuls, user-sharded over the mesh).
- Serving reads the user's RECENT event history live from the event store
  (the reason the reference fork needed serving-time LEventStore reads) and
  scores items by summed LLR over history hits, minus business rules.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    SanityCheck,
)
from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.core.self_cleaning import EventWindow, SelfCleaningDataSource
from predictionio_tpu.data.store.bimap import BiMap
from predictionio_tpu.data.store.event_store import EventStoreFacade
from predictionio_tpu.models import cco
from predictionio_tpu.obs import devprof as _devprof

log = logging.getLogger(__name__)


@dataclass
class Query:
    user: str
    num: int = 10
    blacklist: Optional[list[str]] = None
    # exclude items the user has already acted on with the primary event
    exclude_seen: bool = True


@dataclass
class ItemScore:
    item: str
    score: float


@dataclass
class PredictedResult:
    item_scores: list[ItemScore] = field(default_factory=list)


@dataclass
class DataSourceParams:
    app_name: str
    # indicator event names, PRIMARY first (UR's eventNames)
    indicators: tuple[str, ...] = ("buy", "view")
    # optional self-cleaning window: {"duration": "30 days", ...}
    event_window: Optional[dict] = None


@dataclass
class IndicatorData:
    name: str
    rows: np.ndarray  # user idx
    cols: np.ndarray  # target idx (into its own target vocab)
    target_vocab: BiMap


@dataclass
class TrainingData(SanityCheck):
    indicators: list[IndicatorData]
    n_users: int
    user_vocab: BiMap

    def sanity_check(self) -> None:
        if not self.indicators or len(self.indicators[0].rows) == 0:
            raise ValueError("no primary indicator events found")


class URDataSource(DataSource, SelfCleaningDataSource):
    def __init__(self, params: DataSourceParams):
        self.params = params
        self.app_name = params.app_name
        self.event_window = (
            EventWindow(**params.event_window) if params.event_window else None
        )

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        self.clean_persisted_events(ctx)
        store = EventStoreFacade(ctx.storage)
        frame = store.find_frame(
            app_name=self.params.app_name,
            entity_type="user",
            event_names=list(self.params.indicators),
        )
        indicators = []
        for name in self.params.indicators:
            sub = frame.where_event(name)
            mask = sub.target_idx >= 0
            # each indicator gets its own compact target vocabulary
            raw_targets = sub.target_idx[mask]
            uniq = np.unique(raw_targets)
            remap = {int(t): i for i, t in enumerate(uniq)}
            inv_frame = frame.target_vocab.inverse()
            vocab = BiMap({inv_frame(int(t)): i for t, i in remap.items()})
            indicators.append(
                IndicatorData(
                    name=name,
                    rows=sub.entity_idx[mask].astype(np.int32),
                    cols=np.asarray(
                        [remap[int(t)] for t in raw_targets], dtype=np.int32
                    ),
                    target_vocab=vocab,
                )
            )
        return TrainingData(
            indicators=indicators,
            n_users=frame.n_entities,
            user_vocab=frame.entity_vocab,
        )


# -- algorithm --------------------------------------------------------------


@dataclass
class URAlgorithmParams:
    app_name: str
    max_correlators_per_item: int = 50
    max_query_events: int = 100  # recent history depth per indicator
    indicators: Optional[tuple[str, ...]] = None  # default: all from data


@dataclass
class IndicatorModel:
    name: str
    correlator_scores: np.ndarray  # (I, top_n)
    correlator_idx: np.ndarray  # (I, top_n) into its target vocab, -1 pad
    target_vocab: BiMap


class URModel:
    def __init__(
        self,
        item_vocab: BiMap,
        indicator_models: list[IndicatorModel],
        primary_indicator: str,
    ):
        self.item_vocab = item_vocab  # primary target vocab = item space
        self.indicator_models = indicator_models
        self.primary_indicator = primary_indicator
        self._device_tables = None
        self._stage_lock = threading.Lock()

    # device caches + lock are serving state, not part of the pickled model
    def __getstate__(self):
        return {
            "item_vocab": self.item_vocab,
            "indicator_models": self.indicator_models,
            "primary_indicator": self.primary_indicator,
        }

    def __setstate__(self, state):
        self.__init__(
            state["item_vocab"],
            state["indicator_models"],
            state["primary_indicator"],
        )

    def device_tables(self) -> list:
        """HBM-resident correlator tables [(idx, scores, J), …] — staged
        once, reused by every batched serving dispatch. Locked: the
        pipelined dispatcher (server.py pipeline_depth) may run two
        batches for the same model concurrently, and double-staging the
        tables would transiently double their HBM footprint."""
        with self._stage_lock:
            if self._device_tables is None:
                import jax.numpy as jnp

                self._device_tables = [
                    (
                        jnp.asarray(m.correlator_idx.astype("int32")),
                        jnp.asarray(m.correlator_scores.astype("float32")),
                        len(m.target_vocab),
                    )
                    for m in self.indicator_models
                ]
            return self._device_tables


class URAlgorithm(Algorithm):
    def __init__(self, params: URAlgorithmParams):
        self.params = params

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> URModel:
        primary = pd.indicators[0]
        n_items = len(primary.target_vocab)
        p_matrix = cco.edges_to_indicator(
            primary.rows, primary.cols, pd.n_users, n_items
        )
        wanted = self.params.indicators or tuple(i.name for i in pd.indicators)
        models = []
        for ind in pd.indicators:
            if ind.name not in wanted:
                continue
            s_matrix = cco.edges_to_indicator(
                ind.rows, ind.cols, pd.n_users, len(ind.target_vocab)
            )
            scores, idx = cco.cross_occurrence_topn(
                p_matrix,
                s_matrix,
                top_n=self.params.max_correlators_per_item,
                self_indicator=ind.name == primary.name,
                mesh=ctx.mesh,
            )
            models.append(
                IndicatorModel(
                    name=ind.name,
                    correlator_scores=scores,
                    correlator_idx=idx,
                    target_vocab=ind.target_vocab,
                )
            )
        return URModel(
            item_vocab=primary.target_vocab,
            indicator_models=models,
            primary_indicator=primary.name,
        )

    # -- serving -----------------------------------------------------------
    def _user_history(
        self,
        ctx: RuntimeContext,
        user: str,
        event_name: str,
        target_vocab: BiMap,
    ) -> np.ndarray:
        return self._user_histories(
            ctx, [user], event_name, target_vocab
        )[0]

    def _user_histories(
        self,
        ctx: RuntimeContext,
        users: list,
        event_name: str,
        target_vocab: BiMap,
    ) -> list:
        """Per-user history rows for a WHOLE serving micro-batch in ONE
        store round trip (VERDICT r4 #4 — the per-query loop cost one
        store call per (query, indicator); a remote/sharded store paid a
        network RTT each)."""
        empty = np.empty(0, dtype=np.int64)
        if ctx.storage is None:
            return [empty for _ in users]
        store = EventStoreFacade(ctx.storage)
        try:
            by_user = store.find_by_entities(
                app_name=self.params.app_name,
                entity_type="user",
                entity_ids=users,
                event_names=[event_name],
                limit_per_entity=self.params.max_query_events,
                latest=True,
            )
        except Exception:
            log.exception("history lookup failed for %s", event_name)
            return [empty for _ in users]
        out = []
        for u in users:
            rows = []
            for e in by_user.get(u, ()):
                ix = target_vocab.get(e.target_entity_id)
                if ix is not None:
                    rows.append(ix)
            out.append(np.asarray(rows, dtype=np.int64))
        return out

    def warmup(self, model: URModel) -> None:
        """Pre-compile the batched serving programs + stage correlator
        tables into HBM. Shapes are static per params (batch buckets
        {1,8,64}, fixed history depth, fixed exclusion width, k floor), so
        warming these covers live traffic; only a query with num above the
        k floor would compile a further shape."""
        if not model.indicator_models or len(model.item_vocab) == 0:
            return
        for batch in (1, 8, 64):
            self._predict_batch(
                self.serving_context, model,
                [Query(user="__warmup__")] * batch,
            )

    def _exclusion_width(self) -> int:
        # static per params: the seen-history is capped by max_query_events
        # and blacklists get 64 slots; a longer list is truncated (logged)
        # rather than compiling a new device shape per batch
        return 1 << (self.params.max_query_events + 64 - 1).bit_length()

    _DISPATCH_CHUNK = 64  # device micro-batch; eval-sized inputs chunk

    def _predict_batch(
        self, ctx: RuntimeContext, model: URModel, queries: list[Query]
    ) -> list[PredictedResult]:
        """The UR serving hot path as one device dispatch per ≤64-query
        chunk (VERDICT r2 #5): host gathers per-query histories from the
        event store, the device scores every (query, item) pair across all
        indicators, applies the sparse per-query exclusion sets, and
        top-ks."""
        if len(queries) > self._DISPATCH_CHUNK:
            out: list[PredictedResult] = []
            for lo in range(0, len(queries), self._DISPATCH_CHUNK):
                out.extend(self._predict_batch(
                    ctx, model, queries[lo : lo + self._DISPATCH_CHUNK]
                ))
            return out
        from predictionio_tpu.utils.bucket import batch_bucket, topk_bucket

        n_real = len(queries)
        n_items = len(model.item_vocab)
        if n_items == 0 or not model.indicator_models:
            return [PredictedResult() for _ in queries]
        bsz = batch_bucket(n_real)
        h_max = self.params.max_query_events

        users = [q.user for q in queries]
        histories = []
        for ind in model.indicator_models:
            h = np.full((bsz, h_max), -1, np.int32)
            per_user = self._user_histories(
                ctx, users, ind.name, ind.target_vocab
            )
            for qi, hist in enumerate(per_user):
                h[qi, : len(hist)] = hist[:h_max]
            histories.append(h)
        # seen-filter works in the PRIMARY item space, even when the
        # algorithm keeps only secondary indicators
        e_max = self._exclusion_width()
        exclude = np.full((bsz, e_max), -1, np.int32)
        # one batched primary-history fetch for every exclude_seen query
        seen_users = [q.user for q in queries if q.exclude_seen]
        seen_by_user = (
            dict(zip(
                seen_users,
                self._user_histories(
                    ctx, seen_users, model.primary_indicator,
                    model.item_vocab,
                ),
            ))
            if seen_users
            else {}
        )
        # exclusions beyond the static device width are NOT dropped
        # (ADVICE r3): the overflow is applied host-side after top-k,
        # with k widened so filtered rows still fill q.num results
        overflow: dict[int, set] = {}
        for qi, q in enumerate(queries):
            ex: list[int] = []
            if q.exclude_seen:
                seen = seen_by_user[q.user]
                ex.extend(int(ix) for ix in seen)
            for it in q.blacklist or []:
                ix = model.item_vocab.get(it)
                if ix is not None:
                    ex.append(ix)
            if len(ex) > e_max:
                overflow[qi] = set(ex[e_max:])
                log.info(
                    "query exclusion list %d > device width %d: overflow "
                    "filtered host-side", len(ex), e_max,
                )
            exclude[qi, : len(ex)] = ex[:e_max]

        k_req = min(max((q.num for q in queries), default=10), n_items)
        max_over = max((len(s) for s in overflow.values()), default=0)
        k = topk_bucket(min(k_req + max_over, n_items), n_items, floor=64)
        # padding-waste accounting (ISSUE 3) at the pad site: n_real live
        # queries ran in a bsz-shaped device program
        prof0 = _devprof.snapshot()
        vals, idx = cco.batch_score_topk(
            model.device_tables(), histories, exclude, k
        )
        _devprof.record_batch_padding(
            n_real, bsz, flops=_devprof.snapshot().flops - prof0.flops
        )
        inv = model.item_vocab.inverse()
        out = []
        for qi, q in enumerate(queries[:n_real]):
            scores = []
            skip = overflow.get(qi)
            for v, ix in zip(vals[qi], idx[qi]):
                if len(scores) >= q.num:
                    break
                if v <= 0.0:  # positive_only: no LLR evidence, or excluded
                    continue
                if skip is not None and int(ix) in skip:
                    continue
                scores.append(ItemScore(item=inv(int(ix)), score=float(v)))
            out.append(PredictedResult(item_scores=scores))
        return out

    def predict(self, model: URModel, query: Query) -> PredictedResult:
        return self._predict_batch(self.serving_context, model, [query])[0]

    def batch_predict(self, ctx, model: URModel, queries):
        preds = self._predict_batch(
            ctx or self.serving_context, model, [q for _, q in queries]
        )
        return [(qx, p) for (qx, _q), p in zip(queries, preds)]


class UniversalRecommenderEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            URDataSource,
            IdentityPreparator,
            {"ur": URAlgorithm},
            FirstServing,
        )
