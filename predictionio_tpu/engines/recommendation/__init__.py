from predictionio_tpu.engines.recommendation.engine import (
    ALSAlgorithm,
    ALSAlgorithmParams,
    DataSourceParams,
    ItemScore,
    PredictedResult,
    Query,
    RecommendationDataSource,
    RecommendationEngine,
    TrainingData,
)

__all__ = [
    "ALSAlgorithm",
    "ALSAlgorithmParams",
    "DataSourceParams",
    "ItemScore",
    "PredictedResult",
    "Query",
    "RecommendationDataSource",
    "RecommendationEngine",
    "TrainingData",
]
