from predictionio_tpu.engines.recommendation.engine import (
    ALSAlgorithm,
    ALSAlgorithmParams,
    DataSourceParams,
    FileDataSourceParams,
    FileRatingsDataSource,
    FileRecommendationEngine,
    ItemScore,
    PredictedResult,
    Query,
    RecommendationDataSource,
    RecommendationEngine,
    TrainingData,
)

__all__ = [
    "ALSAlgorithm",
    "ALSAlgorithmParams",
    "DataSourceParams",
    "FileDataSourceParams",
    "FileRatingsDataSource",
    "FileRecommendationEngine",
    "ItemScore",
    "PredictedResult",
    "Query",
    "RecommendationDataSource",
    "RecommendationEngine",
    "TrainingData",
]
